"""HLO-level structural facts about a jitted program.

Extraction runs entirely from XLA's own reporting — no execution, no chip:

- ``Compiled.cost_analysis()`` — FLOPs and bytes moved;
- ``Compiled.memory_analysis()`` — live-buffer peak components;
- the compiled HLO text — collective ops with payload bytes and group sizes
  (GSPMD inserts these only after partitioning, so they exist nowhere
  earlier), fusion count, entry-computation kernel count;
- the StableHLO text — the dtype audit. This MUST come from the jax-level
  lowering, not the compiled module: the CPU backend legalizes bf16 dots to
  f32 (convert + f32 dot), so every bf16 matmul *looks* upcast in backend
  HLO. StableHLO records the dtypes the program was written with, which is
  the chip-independent fact the audit wants (an accidental f32 upcast on a
  bf16 path happens at the JAX level and shows here on any backend).

All numbers are extracted under whatever platform is active; the gates pin
``JAX_PLATFORMS=cpu`` + a fixed virtual device count so budgets compare
like with like.
"""

import re
from dataclasses import dataclass, field
from typing import Dict, Optional

_DTYPE_BYTES = {
    "pred": 1, "s4": 0.5, "u4": 0.5, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "s32": 4, "u32": 4, "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e5m2": 1,
    "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "bf16": 2,
    "f16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
    # stablehlo spellings
    "i1": 0.125, "i4": 0.5, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
    "ui4": 0.5, "ui8": 1, "ui16": 2, "ui32": 4, "ui64": 8,
}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                   "collective-permute", "collective-broadcast", "ragged-all-to-all")

# `%name = <shapes> <op>(` definition lines; -start variants are the async
# halves (count those, skip -done so async pairs aren't double-counted)
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shapes>[^=]*?)\s(?P<op>" + "|".join(_COLLECTIVE_OPS) + r")(?P<start>-start)?\(")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# replica_groups=[4,2]<=[8]  (iota: 4 groups of 2)  |  replica_groups={{0,1},{2,3}}
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9,\s]*)\}")

_FUSION_DEF_RE = re.compile(r"=\s*[^=]*?\sfusion\(")

# every stablehlo op use — the jax-level program-size canary. The CPU
# backend optimizes through de-fusing injections (barriers, materialized
# intermediates) so compiled-level counters can miss them; the StableHLO
# module records the program as written, on any backend.
_STABLE_OP_RE = re.compile(r"\bstablehlo\.\w+")

# stablehlo.dot_general ... : (tensor<16x64xbf16>, tensor<64x64xbf16>) -> ...
_STABLE_DOT_RE = re.compile(
    r"stablehlo\.(?:dot_general|dot|convolution)\b[^\n]*?:\s*"
    r"\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)")


@dataclass
class CollectiveStats:
    op: str
    group_size: int
    count: int = 0
    bytes: int = 0

    @property
    def key(self) -> str:
        return f"{self.op}/g{self.group_size}"


@dataclass
class HloStats:
    name: str = "program"
    platform: str = ""
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    peak_bytes: int = 0
    collectives: Dict[str, dict] = field(default_factory=dict)  # key -> {op, group_size, count, bytes}
    collective_bytes_total: int = 0
    fusion_count: int = 0
    entry_instruction_count: int = 0
    stablehlo_op_count: int = 0
    dot_count: int = 0
    f32_dot_count: int = 0
    dots_by_dtype: Dict[str, int] = field(default_factory=dict)
    analytic_flops: Optional[float] = None
    recompute_ratio: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "platform": self.platform, "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes, "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes, "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes, "collectives": self.collectives,
            "collective_bytes_total": self.collective_bytes_total,
            "fusion_count": self.fusion_count,
            "entry_instruction_count": self.entry_instruction_count,
            "stablehlo_op_count": self.stablehlo_op_count,
            "dot_count": self.dot_count, "f32_dot_count": self.f32_dot_count,
            "dots_by_dtype": self.dots_by_dtype,
            "analytic_flops": self.analytic_flops,
            "recompute_ratio": self.recompute_ratio,
        }

    @staticmethod
    def from_dict(d: dict) -> "HloStats":
        known = {f for f in HloStats.__dataclass_fields__}
        return HloStats(**{k: v for k, v in d.items() if k in known})


def _shape_bytes(shapes_text: str) -> int:
    """Sum the byte sizes of every ``dtype[dims]`` token in a result-shape
    string (handles tuple-shaped collectives)."""
    total = 0.0
    for dtype, dims in _SHAPE_RE.findall(shapes_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token/opaque shapes carry no payload
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += size * n
    return int(total)


def _parse_collectives(compiled_text: str) -> Dict[str, dict]:
    out: Dict[str, CollectiveStats] = {}
    for line in compiled_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None:
            continue
        op = m.group("op")
        group_size = 0
        gi = _GROUPS_IOTA_RE.search(line)
        if gi is not None:
            group_size = int(gi.group(2))
        else:
            gl = _GROUPS_LIST_RE.search(line)
            if gl is not None:
                ids = [t for t in gl.group(1).replace(" ", "").split(",") if t]
                group_size = len(ids)
        cs = out.get(f"{op}/g{group_size}")
        if cs is None:
            cs = CollectiveStats(op=op, group_size=group_size)
            out[cs.key] = cs
        cs.count += 1
        cs.bytes += _shape_bytes(m.group("shapes"))
    return {k: {"op": v.op, "group_size": v.group_size, "count": v.count, "bytes": v.bytes}
            for k, v in out.items()}


def _entry_instruction_count(compiled_text: str) -> int:
    """Instructions in the ENTRY computation — the de-fusing canary (a split
    kernel adds definitions at the top level)."""
    in_entry, count = False, 0
    for line in compiled_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            if " = " in line:
                count += 1
    return count


def _parse_dots(stablehlo_text: str):
    dots_by_dtype: Dict[str, int] = {}
    dot_count = f32 = 0
    for lhs, rhs in _STABLE_DOT_RE.findall(stablehlo_text):
        lt = lhs.split("x")[-1].strip()
        rt = rhs.split("x")[-1].strip()
        dot_count += 1
        key = lt if lt == rt else f"{lt}*{rt}"
        dots_by_dtype[key] = dots_by_dtype.get(key, 0) + 1
        if lt == "f32" or rt == "f32":
            f32 += 1
    return dot_count, f32, dots_by_dtype


def stats_from_lowered(lowered, name: str = "program",
                       analytic_flops: Optional[float] = None) -> HloStats:
    """Extract :class:`HloStats` from a ``jax.stages.Lowered`` (compiles the
    program — which XLA would do anyway on first call — but never runs it)."""
    import jax

    stable_text = lowered.as_text()
    compiled = lowered.compile()
    compiled_text = compiled.as_text()

    props = compiled.cost_analysis()
    if isinstance(props, (list, tuple)):
        props = props[0] if props else {}
    props = props or {}

    stats = HloStats(name=name, platform=jax.default_backend())
    stats.flops = float(props.get("flops", 0.0))
    stats.bytes_accessed = float(props.get("bytes accessed", 0.0))

    try:
        mem = compiled.memory_analysis()
    except Exception:  # pragma: no cover - backends without the API
        mem = None
    if mem is not None:
        stats.argument_bytes = int(getattr(mem, "argument_size_in_bytes", 0))
        stats.output_bytes = int(getattr(mem, "output_size_in_bytes", 0))
        stats.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0))
        stats.alias_bytes = int(getattr(mem, "alias_size_in_bytes", 0))
        stats.peak_bytes = (stats.argument_bytes + stats.output_bytes +
                            stats.temp_bytes + stats.alias_bytes)

    stats.collectives = _parse_collectives(compiled_text)
    stats.collective_bytes_total = sum(c["bytes"] for c in stats.collectives.values())
    stats.fusion_count = len(_FUSION_DEF_RE.findall(compiled_text))
    stats.entry_instruction_count = _entry_instruction_count(compiled_text)
    stats.stablehlo_op_count = len(_STABLE_OP_RE.findall(stable_text))
    stats.dot_count, stats.f32_dot_count, stats.dots_by_dtype = _parse_dots(stable_text)

    if analytic_flops:
        stats.analytic_flops = float(analytic_flops)
        stats.recompute_ratio = stats.flops / float(analytic_flops)
    return stats


def stats_from_callable(fn, *args, name: str = "program",
                        analytic_flops: Optional[float] = None, **kwargs) -> HloStats:
    """Lower ``fn`` on ``args`` and extract stats. ``fn`` may be a jitted
    callable (``jax.jit`` output — used directly, so the analyzed program IS
    the one the engine runs) or a plain function (jitted here)."""
    import jax
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return stats_from_lowered(fn.lower(*args, **kwargs), name=name,
                              analytic_flops=analytic_flops)
