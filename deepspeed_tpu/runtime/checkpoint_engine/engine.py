"""Checkpoint save/load for the engine.

Reference: ``deepspeed/runtime/engine.py:3052-3548`` (save/load incl. ZeRO shards)
and ``deepspeed/runtime/checkpoint_engine/`` (CheckpointEngine ABC / torch / nebula).
The TPU design (SURVEY.md §5.4): ONE logical checkpoint in sharded-array format
(orbax → tensorstore). Every host writes only its shards; restore reshards into
whatever mesh/topology is current — which is the reference's "universal checkpoint"
(ds_to_universal.py) for free.
"""

import json
import os
import pickle

import numpy as np

from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"


class CheckpointEngine:
    """Reference: checkpoint_engine/checkpoint_engine.py (ABC)."""

    def __init__(self, config_params=None):
        ...

    def create(self, tag):
        logger.info(f"[TPU] Saving checkpoint tag {tag}")

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded async-capable checkpoint engine over orbax/tensorstore."""

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ckptr = ocp.StandardCheckpointer() if not use_async else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, state_dict, path: str):
        self._ckptr.save(path, state_dict, force=True)

    def load(self, path: str, map_location=None, target=None):
        if target is not None:
            return self._ckptr.restore(path, target=target)
        return self._ckptr.restore(path)

    def finish(self):
        """Join the in-flight commit WITHOUT closing (the async engine is
        reused across saves)."""
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()

    def wait(self):
        # orbax finalizes array commits on background threads even for the
        # "synchronous" checkpointer; a caller (or interpreter exit) racing
        # them sees a missing/partial state dir. close() joins them.
        self.finish()
        self._ckptr.close()


def _ckpt_path(save_dir, tag):
    return os.path.join(os.path.abspath(save_dir), str(tag))


def checkpoint_barrier(engine):
    """Join any in-flight async save (Nebula-class): the barrier the next
    save/load takes, so at most one commit is ever outstanding. A commit
    that FAILED in the background re-raises here — save_checkpoint already
    returned, so the barrier is the first point the failure can surface."""
    st = getattr(engine, "_async_ckpt", None)
    if st and st.get("thread") is not None:
        st["thread"].join()
        st["thread"] = None
        err = st.pop("error", None)
        if err is not None:
            raise RuntimeError(f"async checkpoint commit failed: {err[1]}") from err[1]


def _write_host_state(path, save_dir, tag, host_state, save_latest):
    import jax
    # host-side metadata is identical on every process; only rank 0 writes it
    # (shared-filesystem checkpoints must not see N concurrent writers)
    if jax.process_index() == 0:
        with open(os.path.join(path, "host_state.pkl"), "wb") as f:
            pickle.dump(host_state, f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))


def save_engine_state(engine, save_dir, tag, client_state, save_latest,
                      async_save=False):
    """``async_save`` (reference nebula_checkpoint_engine.py role): the array
    commit proceeds on background threads while training continues; the
    host-state + ``latest`` marker are written only AFTER the commit is
    durable, so a crash mid-commit leaves the previous checkpoint current
    (the reference's tier-commit semantics). ``checkpoint_barrier`` (taken by
    the next save/load) bounds in-flight saves to one."""
    import threading

    path = _ckpt_path(save_dir, tag)
    os.makedirs(save_dir, exist_ok=True)

    checkpoint_barrier(engine)  # previous in-flight save must land first

    arrays = {
        "params": engine.params,
        "opt_state": _named_opt_state(engine._offload.checkpoint_view(engine.opt_state)),
        "scale_state": engine.scale_state._asdict(),
    }
    host_state = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": int(engine._overflow_count),
        "current_lr": engine._current_lr,
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        "ds_config": engine._config._param_dict,
        "client_state": client_state,
    }

    if not async_save:
        ck = OrbaxCheckpointEngine()
        ck.save(arrays, os.path.join(path, "state"))
        ck.wait()  # checkpoint must be durable before save_checkpoint returns
        _write_host_state(path, save_dir, tag, host_state, save_latest)
        logger.info(f"Saved checkpoint to {path}")
        return True

    st = getattr(engine, "_async_ckpt", None)
    if st is None:
        st = engine._async_ckpt = {"thread": None, "ckptr": None}
    if st["ckptr"] is None:
        st["ckptr"] = OrbaxCheckpointEngine(use_async=True)
    ck = st["ckptr"]
    # the async save stages a device→host snapshot synchronously (so later
    # donated train steps can't corrupt it) and commits on background threads
    ck.save(arrays, os.path.join(path, "state"))

    def finalize():
        try:
            ck.finish()
            _write_host_state(path, save_dir, tag, host_state, save_latest)
            logger.info(f"Async checkpoint committed to {path}")
        except BaseException as e:  # surfaced at the next checkpoint_barrier
            st["error"] = (tag, e)
            logger.error(f"Async checkpoint commit for {path} FAILED: {e}")

    # non-daemon: the interpreter joins it at exit, so a short-lived trainer
    # can't lose its last checkpoint
    t = threading.Thread(target=finalize, name=f"ckpt-commit-{tag}")
    t.start()
    st["thread"] = t
    logger.info(f"Async checkpoint save dispatched for {path}")
    return True


def load_engine_state(engine, load_dir, tag, load_optimizer_states=True, load_lr_scheduler_states=True,
                      load_module_only=False):
    import jax
    checkpoint_barrier(engine)  # an in-flight async save must land first
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.isfile(latest):
            logger.warning(f"Unable to find latest file at {latest}, returning (None, None)")
            return None, None
        with open(latest) as f:
            tag = f.read().strip()
    path = _ckpt_path(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"Checkpoint path {path} does not exist")
        return None, None

    ck = OrbaxCheckpointEngine()
    # Restore against the engine's current shardings → automatic resharding
    # (the universal-checkpoint reshape of deepspeed/checkpoint/ds_to_universal.py).
    target = {
        "params": _shaped(engine.params, engine._param_shardings),
        "opt_state": _named_opt_state(engine._offload.restore_template(engine.opt_state)),
        "scale_state": {k: v for k, v in engine.scale_state._asdict().items()},
    }
    restored = ck.load(os.path.join(path, "state"), target=target)
    engine.params = jax.device_put(restored["params"], engine._param_shardings)
    if load_optimizer_states and not load_module_only:
        # restore straight into the at-rest placement (pinned host when
        # offloaded, NVMe files under ZeRO-Infinity)
        engine.opt_state = engine._offload.accept_restored(
            type(engine.opt_state)(**restored["opt_state"]))
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaleState
        # scalars must live on the CURRENT mesh (restored under a different
        # topology they'd sit on one device and poison the jitted step)
        rep = NamedSharding(engine.mesh, P())
        engine.scale_state = LossScaleState(**{k: jax.device_put(restored["scale_state"][k], rep)
                                               for k in ("cur_scale", "good_steps", "hysteresis")})

    with open(os.path.join(path, "host_state.pkl"), "rb") as f:
        host_state = pickle.load(f)
    if not load_module_only:
        import jax.numpy as jnp
        engine.global_steps = host_state["global_steps"]
        engine.global_samples = host_state["global_samples"]
        engine.micro_steps = host_state["micro_steps"]
        engine._current_lr = host_state["current_lr"]
        engine._overflow_count = jnp.asarray(host_state.get("skipped_steps", 0), jnp.int32)
        if load_lr_scheduler_states and engine.lr_scheduler is not None and host_state["lr_scheduler"]:
            engine.lr_scheduler.load_state_dict(host_state["lr_scheduler"])
    logger.info(f"Loaded checkpoint from {path}")
    return path, host_state.get("client_state", {})


def _named_opt_state(opt_state):
    """NamedTuple → dict (orbax-friendly)."""
    if hasattr(opt_state, "_asdict"):
        return dict(opt_state._asdict())
    return opt_state


def _shaped(tree, shardings):
    return tree
