"""Budget ratchet semantics: one-sided, exact dtype audit, collective keys."""

import json

import pytest

from deepspeed_tpu.perf.budgets import (Budget, budget_from_stats, check_stats,
                                        list_budgets, load_budget, write_budget)
from deepspeed_tpu.perf.hlo_stats import HloStats


def _stats(**kw):
    base = dict(name="prog", platform="cpu", flops=1e9, bytes_accessed=1e8,
                peak_bytes=10**7, argument_bytes=10**6, output_bytes=10**5,
                collective_bytes_total=4096, fusion_count=10,
                entry_instruction_count=20, dot_count=6, f32_dot_count=0,
                dots_by_dtype={"bf16": 6},
                collectives={"all-gather/g8": {"op": "all-gather", "group_size": 8,
                                               "count": 4, "bytes": 2048},
                             "all-reduce/g8": {"op": "all-reduce", "group_size": 8,
                                               "count": 4, "bytes": 2048}})
    base.update(kw)
    return HloStats(**base)


@pytest.fixture
def budget():
    return budget_from_stats(_stats(), note="test baseline")


def test_identical_stats_pass(budget):
    assert check_stats(_stats(), budget) == []


def test_improvements_never_trip(budget):
    better = _stats(flops=5e8, bytes_accessed=1e7, peak_bytes=10**6,
                    fusion_count=3, dot_count=2,
                    collectives={"all-gather/g8": {"op": "all-gather", "group_size": 8,
                                                   "count": 1, "bytes": 100}})
    better.collective_bytes_total = 100
    assert check_stats(better, budget) == []


def test_small_drift_within_tolerance_passes(budget):
    drift = _stats(bytes_accessed=1e8 * 1.05)  # tol 0.10
    assert check_stats(drift, budget) == []


@pytest.mark.parametrize("metric,value", [
    ("flops", 1e9 * 1.2),
    ("bytes_accessed", 1e8 * 1.2),
    ("peak_bytes", int(10**7 * 1.2)),
    ("fusion_count", 20),
    ("entry_instruction_count", 40),
])
def test_regressions_trip(budget, metric, value):
    bad = _stats(**{metric: value})
    tripped = [v.metric for v in check_stats(bad, budget)]
    assert metric in tripped


def test_dtype_audit_is_exact(budget):
    bad = _stats(f32_dot_count=1, dot_count=7, dots_by_dtype={"bf16": 6, "f32": 1})
    tripped = [v.metric for v in check_stats(bad, budget)]
    assert "f32_dot_count" in tripped and "dot_count" in tripped


def test_new_collective_key_trips(budget):
    bad = _stats()
    bad.collectives["all-to-all/g8"] = {"op": "all-to-all", "group_size": 8,
                                        "count": 1, "bytes": 64}
    vs = check_stats(bad, budget)
    assert any(v.metric == "collectives[all-to-all/g8]" for v in vs)


def test_collective_payload_growth_trips(budget):
    bad = _stats()
    bad.collectives["all-gather/g8"] = {"op": "all-gather", "group_size": 8,
                                        "count": 4, "bytes": 4096}
    vs = check_stats(bad, budget)
    assert any(v.metric == "collectives[all-gather/g8].bytes" for v in vs)


def test_collective_count_growth_trips(budget):
    bad = _stats()
    bad.collectives["all-reduce/g8"] = {"op": "all-reduce", "group_size": 8,
                                        "count": 5, "bytes": 2048}
    vs = check_stats(bad, budget)
    assert any(v.metric == "collectives[all-reduce/g8].count" for v in vs)


def test_per_budget_tolerance_override(budget):
    budget.tolerances["bytes_accessed"] = 0.5
    assert check_stats(_stats(bytes_accessed=1e8 * 1.4), budget) == []


def test_violation_message_names_everything(budget):
    v = check_stats(_stats(flops=1e12), budget)[0]
    msg = str(v)
    assert "prog" in msg and "flops" in msg and "limit" in msg


# ----------------------------------------------------------------- file i/o --
def test_write_load_round_trip(tmp_path, budget):
    path = write_budget(str(tmp_path), budget)
    assert path.endswith("prog.json")
    loaded = load_budget(str(tmp_path), "prog")
    assert loaded.to_json() == budget.to_json()
    assert list_budgets(str(tmp_path)) == ["prog"]


def test_missing_budget_names_the_rebaseline_path(tmp_path):
    with pytest.raises(FileNotFoundError, match="dstpu_perfgate rebaseline"):
        load_budget(str(tmp_path), "nope")


def test_schema_version_mismatch_rejected(tmp_path, budget):
    path = write_budget(str(tmp_path), budget)
    doc = json.load(open(path))
    doc["schema_version"] = 99
    json.dump(doc, open(path, "w"))
    with pytest.raises(ValueError, match="schema_version"):
        load_budget(str(tmp_path), "prog")


def test_checked_in_budgets_exist_for_every_flagship_program():
    """The acceptance bar: every flagship program ships a budget file."""
    from deepspeed_tpu.perf.budgets import default_budgets_dir
    from deepspeed_tpu.perf.programs import FLAGSHIP_PROGRAMS
    have = set(list_budgets(default_budgets_dir()))
    assert have >= set(FLAGSHIP_PROGRAMS), \
        f"missing budget files for {sorted(set(FLAGSHIP_PROGRAMS) - have)}"
    for name in FLAGSHIP_PROGRAMS:
        b = load_budget(default_budgets_dir(), name)
        assert b.platform == "cpu"
        assert b.stats["bytes_accessed"] > 0
