"""Soak: ~200 requests through a tiny llama with mixed outcomes (completions,
deadline timeouts, cancellations) — the scheduler must end with zero KV-block
and zero tracked-sequence leakage. Marked slow: tier-1 runs the sub-second
units in this directory; nightly/soak lanes run this."""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.serving import ServingConfig, ServingScheduler

N_REQUESTS = 200


@pytest.mark.slow
def test_soak_no_kv_or_sequence_leak(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=64, block_size=16, max_context=256)
    free0 = engine.free_blocks
    sched = ServingScheduler(engine, ServingConfig(queue_capacity=N_REQUESTS,
                                                   decode_chunk=2))
    requests = []
    lock = threading.Lock()

    def submitter(worker):
        worker_rng = np.random.default_rng(worker)
        for i in range(N_REQUESTS // 4):
            prompt = worker_rng.integers(0, cfg.vocab_size,
                                         int(worker_rng.integers(3, 40))).tolist()
            kw = {"max_new_tokens": int(worker_rng.integers(1, 5))}
            if i % 10 == 3:
                kw["deadline_s"] = 0.001  # will time out (queued or mid-flight)
            req = sched.submit(prompt, **kw)
            if i % 7 == 2:
                req.cancel()  # cancelled at whatever stage the tick finds it
            with lock:
                requests.append(req)

    threads = [threading.Thread(target=submitter, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(requests) == N_REQUESTS

    deadline = time.monotonic() + 600
    for req in requests:
        assert req.wait(timeout=max(0.0, deadline - time.monotonic())), req
    sched.stop(drain=True)

    stats = sched.stats()
    finished = sum(stats["counters"][k]
                   for k in ("completed", "cancelled", "timed_out", "failed"))
    assert finished == N_REQUESTS
    assert stats["counters"]["failed"] == 0
    assert stats["counters"]["completed"] >= N_REQUESTS // 2
    # the leak assertions this soak exists for:
    assert engine.free_blocks == free0
    assert engine._state_manager.n_tracked_sequences == 0
