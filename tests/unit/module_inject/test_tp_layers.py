"""TP layer library (reference module_inject/layers.py — LinearAllreduce,
LinearLayer, EmbeddingLayer, Normalize)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.module_inject import (EmbeddingLayer, LinearAllreduce, LinearLayer,
                                         Normalize)
from deepspeed_tpu.utils import groups


def _mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = LinearLayer(features=32, name="up")(x)      # column-parallel
            h = nn.gelu(h)
            return LinearAllreduce(features=8, name="down")(h)  # row-parallel

    return MLP()


def test_tp_layers_match_dense_numerics():
    """On a model=2 mesh, the column→row pair must equal the unsharded
    computation (the collective is a pure reduction)."""
    groups.initialize_mesh(model_parallel_size=2, force=True)
    m = _mlp()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]

    out_sharded = jax.jit(lambda p, x: m.apply({"params": p}, x))(params, x)

    # unsharded reference: same weights, plain mesh
    groups.destroy_mesh()
    groups.initialize_mesh(force=True)
    out_plain = m.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(out_plain),
                               rtol=1e-5, atol=1e-6)


def test_row_parallel_lowers_to_all_reduce():
    """The row-parallel output constraint must put a cross-replica reduction in
    the HLO when params are sharded per the layer specs (the reference's
    explicit dist.all_reduce)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = groups.initialize_mesh(model_parallel_size=2, force=True)
    m = _mlp()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    specs = {"up": {"linear": {"kernel": LinearLayer.kernel_spec(), "bias": P("model")}},
             "down": {"linear": {"kernel": LinearAllreduce.kernel_spec(), "bias": P()}}}
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda n: isinstance(n, P))
    placed = jax.device_put(params, shardings)
    hlo = jax.jit(lambda p, x: m.apply({"params": p}, x)).lower(placed, x).compile().as_text()
    assert "all-reduce" in hlo, "row-parallel contraction must reduce across TP ranks"


def test_embedding_and_normalize():
    groups.initialize_mesh(model_parallel_size=2, force=True)
    emb = EmbeddingLayer(num_embeddings=64, features=16)
    ids = jnp.asarray([[1, 2, 63]], jnp.int32)
    p = emb.init(jax.random.PRNGKey(0), ids)["params"]
    out = emb.apply({"params": p}, ids)
    assert out.shape == (1, 3, 16)
    table = np.asarray(p["embedding"]["embedding"])
    np.testing.assert_allclose(np.asarray(out[0, 0]), table[1], rtol=1e-6)

    norm = Normalize()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16)), jnp.float32)
    np_ = norm.init(jax.random.PRNGKey(0), x)["params"]
    y = np.asarray(norm.apply({"params": np_}, x))
    np.testing.assert_allclose(y.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.var(-1), 1.0, atol=1e-3)
