"""Accelerator abstraction.

TPU-native analog of the reference's ``accelerator/abstract_accelerator.py:10-277``
(``DeepSpeedAccelerator`` ABC, ~60 methods). The surface is kept recognizable so code
written against the reference maps one-to-one, but the semantics are JAX/XLA-native:

- "streams"/"events" — XLA owns scheduling; ``synchronize`` blocks on async dispatch.
- tensor factories return ``jax.numpy`` arrays on the accelerator.
- ``communication_backend_name()`` names the collective backend ('xla-ici' on TPU),
  consumed by ``deepspeed_tpu.comm.init_distributed`` the way the reference feeds
  'nccl'/'ccl'/'hccl' into torch.distributed.
- op builders dispatch to Pallas/XLA implementations under ``deepspeed_tpu/ops``.
"""

import abc
from abc import ABC


class DeepSpeedAccelerator(ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None
        self._compile_backend = None

    # ---- device APIs -------------------------------------------------------------
    @abc.abstractmethod
    def is_synchronized_device(self):
        ...

    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def set_device(self, device_index):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    @abc.abstractmethod
    def current_device_name(self):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    # ---- RNG APIs ----------------------------------------------------------------
    @abc.abstractmethod
    def random(self):
        ...

    @abc.abstractmethod
    def set_rng_state(self, new_state, device_index=None):
        ...

    @abc.abstractmethod
    def get_rng_state(self, device_index=None):
        ...

    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    @abc.abstractmethod
    def initial_seed(self):
        ...

    @abc.abstractmethod
    def default_generator(self, device_index):
        ...

    # ---- streams/events (XLA: async dispatch; these are compatibility no-ops) ----
    @abc.abstractmethod
    def Stream(self, device=None, priority=0, **kwargs):
        ...

    @abc.abstractmethod
    def stream(self, stream):
        ...

    @abc.abstractmethod
    def current_stream(self, device_index=None):
        ...

    @abc.abstractmethod
    def default_stream(self, device_index=None):
        ...

    @abc.abstractmethod
    def Event(self, **kwargs):
        ...

    # ---- memory management -------------------------------------------------------
    @abc.abstractmethod
    def empty_cache(self):
        ...

    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def reset_max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def memory_cached(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_cached(self, device_index=None):
        ...

    @abc.abstractmethod
    def reset_max_memory_cached(self, device_index=None):
        ...

    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        ...

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index=None):
        ...

    @abc.abstractmethod
    def memory_reserved(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_reserved(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None):
        ...

    # ---- dtype support -----------------------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    # ---- misc --------------------------------------------------------------------
    @abc.abstractmethod
    def amp(self):
        ...

    @abc.abstractmethod
    def is_available(self):
        ...

    @abc.abstractmethod
    def range_push(self, msg):
        ...

    @abc.abstractmethod
    def range_pop(self):
        ...

    @abc.abstractmethod
    def lazy_call(self, callback):
        ...

    @abc.abstractmethod
    def communication_backend_name(self):
        ...

    @abc.abstractmethod
    def is_triton_supported(self):
        ...

    # ---- graph operations (XLA: jit IS the graph capture) ------------------------
    @abc.abstractmethod
    def create_graph(self):
        ...

    @abc.abstractmethod
    def capture_to_graph(self, graph, pool=None, stream=None):
        ...

    @abc.abstractmethod
    def replay_graph(self, graph):
        ...

    # ---- tensor factories --------------------------------------------------------
    @property
    @abc.abstractmethod
    def BFloat16Tensor(self):
        ...

    @property
    @abc.abstractmethod
    def ByteTensor(self):
        ...

    @property
    @abc.abstractmethod
    def DoubleTensor(self):
        ...

    @property
    @abc.abstractmethod
    def FloatTensor(self):
        ...

    @property
    @abc.abstractmethod
    def HalfTensor(self):
        ...

    @property
    @abc.abstractmethod
    def IntTensor(self):
        ...

    @property
    @abc.abstractmethod
    def LongTensor(self):
        ...

    @abc.abstractmethod
    def pin_memory(self, tensor, align_bytes=1):
        ...

    @abc.abstractmethod
    def is_pinned(self, tensor):
        ...

    @abc.abstractmethod
    def on_accelerator(self, tensor):
        ...

    # ---- op builder dispatch -----------------------------------------------------
    @abc.abstractmethod
    def op_builder_dir(self):
        ...

    @abc.abstractmethod
    def create_op_builder(self, class_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, class_name):
        ...

    @abc.abstractmethod
    def build_extension(self):
        ...

    @abc.abstractmethod
    def export_envs(self):
        ...
