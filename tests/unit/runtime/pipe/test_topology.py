"""Pure rank-math tests (reference: tests/unit/runtime/pipe/test_topology.py)."""

import pytest

from deepspeed_tpu.runtime.pipe.module import partition_balanced, partition_uniform
from deepspeed_tpu.runtime.pipe.topology import (PipeDataParallelTopology, PipeModelDataParallelTopology,
                                                 ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3


def test_topology_dims():
    topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("b") == 3
    assert topo.get_dim("missing") == 0


def test_topology_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    assert topo.get_axis_comm_lists("pipe") == [[0, 2], [1, 3]]
    assert topo.get_axis_comm_lists("data") == [[0, 1], [2, 3]]
    assert topo.get_axis_comm_lists("bogus") == []


def test_topology_filter():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    assert topo.filter_match(pipe=0) == [0, 1]
    assert topo.filter_match(pipe=1, data=0) == [2]


def test_topology_coord():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 2])
    c = topo.get_coord(2)
    assert c.pipe == 1 and c.data == 0


def test_topology_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    assert topo.get_rank_repr(rank=0) == "model_00"
    assert topo.get_rank_repr(rank=1) == "model_01"


def test_3d_topology():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    # axes order: pipe, data, model
    assert topo.get_rank(pipe=0, data=0, model=0) == 0
    assert topo.get_rank(pipe=0, data=0, model=1) == 1
    assert topo.get_rank(pipe=1, data=1, model=1) == 7


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(9, 4) == [0, 3, 5, 7, 9]
    assert partition_uniform(3, 3) == [0, 1, 2, 3]


def test_partition_balanced():
    parts = partition_balanced([1, 1, 1, 1], 2)
    assert parts[0] == 0 and parts[-1] == 4
    # heavy first layer should sit alone
    parts = partition_balanced([10, 1, 1, 1], 2)
    assert parts == [0, 1, 4]
    # monotone boundaries
    parts = partition_balanced([3, 2, 2, 3, 1, 1], 3)
    assert parts[0] == 0 and parts[-1] == 6
    assert all(a <= b for a, b in zip(parts, parts[1:]))
