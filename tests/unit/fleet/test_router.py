"""Router dispatch policy + HTTP surface: affinity stability under replica
loss, least-loaded picks, 503/429 failover, fleet-wide drain."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.fleet import (FleetRouter, ReplicaUnavailable, RoutingError)
from deepspeed_tpu.fleet.router import _rendezvous_score
from deepspeed_tpu.serving.server import TRACE_HEADER


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def _post(url, doc, headers=None, timeout=120):
    req = urllib.request.Request(url, data=json.dumps(doc).encode(),
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


# ---------------------------------------------------------------------------
# dispatch policy (no HTTP)
# ---------------------------------------------------------------------------
def test_affinity_same_key_same_replica(make_fleet):
    manager = make_fleet(roles=("mixed", "mixed", "mixed"))
    router = FleetRouter(manager)
    picks = set()
    for _ in range(4):
        routed = router.route({"prompt": _prompt(), "max_new_tokens": 2},
                              session_key="user-42")
        routed.result()
        picks.add(routed._legs_meta[0]["replica"])
    assert len(picks) == 1, f"affinity must be sticky, saw {picks}"


def test_affinity_stable_under_replica_loss(make_fleet):
    """Rendezvous property: draining one replica only moves the keys that
    lived on it — every other key keeps its replica."""
    manager = make_fleet(roles=("mixed", "mixed", "mixed"))
    replicas = manager.replicas()
    ids = [r.id for r in replicas]
    keys = [f"session-{i}" for i in range(60)]
    before = {k: max(ids, key=lambda rid: _rendezvous_score(k, rid)) for k in keys}
    victim = ids[0]
    manager.drain(victim)
    survivors = [rid for rid in ids if rid != victim]
    after = {k: max(survivors, key=lambda rid: _rendezvous_score(k, rid)) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    assert all(before[k] == victim for k in moved), \
        "only keys on the drained replica may move"
    assert any(before[k] != victim for k in keys)  # the stable majority

    # and the live router agrees with the pure-function prediction
    router = FleetRouter(manager)
    k = next(k for k in keys if before[k] != victim)
    routed = router.route({"prompt": _prompt(), "max_new_tokens": 2}, session_key=k)
    routed.result()
    assert routed._legs_meta[0]["replica"] == after[k] == before[k]


def test_least_loaded_prefers_idle_replica(make_fleet, monkeypatch):
    manager = make_fleet(roles=("mixed", "mixed"))
    busy, idle = manager.replicas()
    monkeypatch.setattr(type(busy), "load", property(
        lambda self: 5 if self is busy else 0))
    router = FleetRouter(manager)
    routed = router.route({"prompt": _prompt(), "max_new_tokens": 2})
    routed.result()
    assert routed._legs_meta[0]["replica"] == idle.id


def test_failover_excludes_unavailable_replica(make_fleet, monkeypatch):
    manager = make_fleet(roles=("mixed", "mixed"))
    bad, good = manager.replicas()
    original = type(bad).dispatch

    def flaky(self, *args, **kwargs):
        if self is bad:
            raise ReplicaUnavailable("injected 503", status=503)
        return original(self, *args, **kwargs)

    monkeypatch.setattr(type(bad), "dispatch", flaky)
    # force the bad replica to be picked first
    monkeypatch.setattr(type(bad), "load", property(
        lambda self: 0 if self is bad else 1))
    router = FleetRouter(manager)
    routed = router.route({"prompt": _prompt(), "max_new_tokens": 2})
    doc = routed.result()
    assert doc["state"] == "DONE"
    assert routed._legs_meta[0]["replica"] == good.id
    assert bad.failures == 1 and bad.dispatches == 1


def test_all_replicas_down_is_routing_error(make_fleet):
    manager = make_fleet(roles=("mixed",))
    manager.drain(manager.replicas()[0].id)
    router = FleetRouter(manager)
    with pytest.raises(RoutingError) as err:
        router.route({"prompt": _prompt()})
    assert err.value.status == 503


def test_fleet_backpressure_surfaces_429(make_fleet, monkeypatch):
    manager = make_fleet(roles=("mixed",))
    replica = manager.replicas()[0]
    monkeypatch.setattr(type(replica), "dispatch",
                        lambda self, *a, **k: (_ for _ in ()).throw(
                            ReplicaUnavailable("full", status=429)))
    router = FleetRouter(manager)
    with pytest.raises(RoutingError) as err:
        router.route({"prompt": _prompt()})
    assert err.value.status == 429  # the last refusal was backpressure


def test_router_drain_stops_admission(make_fleet):
    manager = make_fleet(roles=("mixed",))
    router = FleetRouter(manager)
    router.drain(timeout=5.0)
    with pytest.raises(RoutingError) as err:
        router.route({"prompt": _prompt()})
    assert err.value.status == 503
    assert all(not r.available for r in manager.replicas())


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------
@pytest.fixture
def http_router(make_fleet):
    manager = make_fleet(roles=("mixed", "mixed"))
    router = FleetRouter(manager).start()
    yield router
    router.stop(drain=False)


def test_http_generate_roundtrip(http_router):
    with _post(http_router.url + "/v1/generate",
               {"prompt": _prompt(), "max_new_tokens": 3}) as resp:
        doc = json.loads(resp.read())
    assert doc["state"] == "DONE" and doc["n_tokens"] == len(doc["tokens"])
    assert doc["legs"][0]["kind"] == "serve"
    assert "handoff" not in doc  # internal transport never leaks to clients


def test_http_sse_stream_and_session_header(http_router):
    with _post(http_router.url + "/v1/generate",
               {"prompt": _prompt(), "max_new_tokens": 3, "stream": True},
               headers={"X-DSTPU-Session": "abc"}) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        events = [json.loads(line.decode().strip()[len("data: "):])
                  for line in resp if line.decode().strip().startswith("data: ")]
    done = events[-1]
    assert done["done"] and done["state"] == "DONE"
    assert [e["token"] for e in events[:-1]] == done["tokens"]


def test_http_fleet_stats_and_healthz(http_router):
    with _post(http_router.url + "/v1/generate",
               {"prompt": _prompt(), "max_new_tokens": 2}) as resp:
        resp.read()
    stats = json.loads(urllib.request.urlopen(
        http_router.url + "/v1/fleet/stats", timeout=10).read())
    assert stats["roles"] == {"mixed": 2}
    assert sum(r["dispatches"] for r in stats["replicas"]) == 1
    assert stats["router"]["requests"] == 1
    health = json.loads(urllib.request.urlopen(
        http_router.url + "/healthz", timeout=10).read())
    assert health["status"] == "ok"
    # single-replica wire shape for loadgen-style clients
    agg = json.loads(urllib.request.urlopen(
        http_router.url + "/v1/stats", timeout=10).read())
    assert agg["replicas"] == 2 and "queue_depth" in agg


def test_http_bad_request_400_and_unknown_route_404(http_router):
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(http_router.url + "/v1/generate", {"prompt": []})
    assert err.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(http_router.url + "/v1/nope", {})
    assert err.value.code == 404


def test_http_trace_header_adopted(http_router):
    from deepspeed_tpu import telemetry
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    try:
        with _post(http_router.url + "/v1/generate",
                   {"prompt": _prompt(), "max_new_tokens": 2},
                   headers={TRACE_HEADER: "deadbeef01"}) as resp:
            doc = json.loads(resp.read())
            assert resp.headers[TRACE_HEADER] == "deadbeef01"
        assert doc["trace_id"] == "deadbeef01"
    finally:
        telemetry.shutdown()


def test_loadgen_through_router_prints_replica_attribution(http_router, llama_setup):
    """The ISSUE satellite: percentiles measured through the router, plus
    per-replica request counts from /v1/fleet/stats."""
    import os
    import subprocess
    import sys
    cfg = llama_setup[0]
    bin_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "bin")
    r = subprocess.run(
        [sys.executable, os.path.join(bin_dir, "dstpu_loadgen"),
         "--target", http_router.url, "--target", http_router.url,
         "--requests", "4", "--concurrency", "2", "--prompt-len", "8",
         "--max-new-tokens", "3", "--vocab-size", str(cfg.vocab_size)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-800:]
    assert "ok=4 err=0" in r.stdout
    assert f"# fleet {http_router.url}" in r.stdout
    assert r.stdout.count("replica mixed-") == 2  # one row per replica
