"""Roofline math: pure, no jax."""

import pytest

from deepspeed_tpu.perf.chip_specs import CHIP_SPECS, ChipSpec, get_chip_spec
from deepspeed_tpu.perf.hlo_stats import HloStats
from deepspeed_tpu.perf.roofline import predict

SPEC = ChipSpec("test", peak_bf16_flops=100e12, hbm_bytes_per_s=1e12,
                hbm_bytes=16 * 2**30, ici_bytes_per_s=100e9)


def test_compute_bound():
    st = HloStats(flops=100e12, bytes_accessed=1e9, collective_bytes_total=0)
    p = predict(st, SPEC)
    assert p.bound == "compute"
    assert p.step_s == pytest.approx(1.0)
    assert p.mfu_bound == pytest.approx(1.0)
    assert p.arithmetic_intensity == pytest.approx(100e12 / 1e9)


def test_memory_bound_caps_mfu():
    st = HloStats(flops=1e12, bytes_accessed=1e12, collective_bytes_total=0)
    p = predict(st, SPEC)
    assert p.bound == "memory"
    assert p.step_s == pytest.approx(1.0)
    assert p.mfu_bound == pytest.approx(0.01)


def test_collective_bound():
    st = HloStats(flops=1e9, bytes_accessed=1e9, collective_bytes_total=100e9)
    p = predict(st, SPEC)
    assert p.bound == "collective"
    assert p.step_s == pytest.approx(1.0)


def test_analytic_flops_discount_recompute_in_mfu():
    # HLO flops double the analytic model's (remat recompute): MFU halves
    st = HloStats(flops=100e12, bytes_accessed=1.0, analytic_flops=50e12)
    p = predict(st, SPEC)
    assert p.mfu_bound == pytest.approx(0.5)


def test_fits_hbm_flag():
    small = HloStats(flops=1.0, bytes_accessed=1.0, peak_bytes=2**30)
    big = HloStats(flops=1.0, bytes_accessed=1.0, peak_bytes=32 * 2**30)
    assert predict(small, SPEC).fits_hbm
    assert not predict(big, SPEC).fits_hbm


def test_empty_program():
    p = predict(HloStats(), SPEC)
    assert p.bound == "none" and p.step_s == 0.0 and p.mfu_bound == 0.0


def test_chip_table_lookup_and_default():
    assert get_chip_spec().name == "v5e"
    assert get_chip_spec("v5e").peak_bf16_flops == pytest.approx(197e12)
    with pytest.raises(KeyError):
        get_chip_spec("v99")
    # v5e numbers feed bench.py's MFU convention — keep them consistent
    assert set(CHIP_SPECS) >= {"v5e", "v5p", "v4", "v6e"}


def test_prediction_serializes():
    d = predict(HloStats(flops=1e12, bytes_accessed=1e9), SPEC).to_dict()
    assert d["chip"] == "test" and "step_s" in d and "mfu_bound" in d
