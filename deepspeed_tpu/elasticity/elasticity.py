"""Elastic batch-size math.

Reference: ``deepspeed/elasticity/elasticity.py:233`` (compute_elastic_config) —
given a max acceptable global batch, candidate micro-batch sizes and a
chip-count range, find the global batch size compatible with the most chip
counts, so a job can scale up/down across that set without changing the
effective batch (GAS absorbs the difference). v0.1 lets the batch float over
highly-composite multiples; v0.2 fixes the global batch at node granularity.

The algorithm is scale-invariant pure arithmetic, ported semantically: the
candidate set is {base * HCN <= max} for each base in micro_batches + their
LCM, scored by how many chip counts in [min, max] divide it with some
micro-batch.
"""

import json
import math
import os
from typing import List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# highly composite numbers — dense divisor sets make good batch multipliers
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680, 2520,
            5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400]

ELASTICITY = "elasticity"
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Reference elasticity/config.py — schema of the "elasticity" block."""

    def __init__(self, param_dict: dict):
        self.enabled = param_dict.get("enabled", False)
        if "max_train_batch_size" not in param_dict:
            raise ElasticityConfigError("elasticity config missing max_train_batch_size")
        self.max_acceptable_batch_size = param_dict["max_train_batch_size"]
        if "micro_batch_sizes" not in param_dict:
            raise ElasticityConfigError("elasticity config missing micro_batch_sizes")
        self.micro_batches = param_dict["micro_batch_sizes"]
        if not isinstance(self.micro_batches, list) or \
                not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"micro_batch_sizes must be positive ints, "
                                        f"got {self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10000)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(f"bad chip range [{self.min_gpus}, {self.max_gpus}]")
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", 0.1)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch_size", True)
        self.ignore_non_elastic_batch_info = param_dict.get("ignore_non_elastic_batch_info", False)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, indent=2)


def elasticity_enabled(ds_config: dict) -> bool:
    return ds_config.get(ELASTICITY, {}).get("enabled", False)


def _candidate_batch_sizes(base_list: List[int], max_batch: int) -> List[int]:
    out = set()
    for base in base_list:
        if base >= max_batch:
            out.add(base)
            continue
        best = base
        for h in HCN_LIST:
            if h * base > max_batch:
                break
            best = h * base
        out.add(best)
    return sorted(out)


def _valid_gpus(batch_size: int, micro_batches: List[int], min_gpus: int,
                max_gpus: int) -> List[int]:
    """Chip counts n in range such that batch_size == micro * gas * n for some
    micro in the list (i.e. n divides batch_size/micro)."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        top = batch_size // micro
        for n in range(1, int(math.isqrt(top)) + 1):
            if top % n == 0:
                for cand in (n, top // n):
                    if min_gpus <= cand <= max_gpus:
                        valid.add(cand)
    return sorted(valid)


def _best_candidate(candidates: List[int], micro_batches: List[int], min_gpus: int,
                    max_gpus: int, prefer_larger: bool) -> Tuple[int, List[int]]:
    best_batch, best_valid = min(micro_batches), []
    for batch in candidates:
        valid = _valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better = len(valid) > len(best_valid) or (
            len(valid) == len(best_valid) and
            (batch > best_batch if prefer_larger else batch < best_batch))
        if better:
            best_batch, best_valid = batch, valid
    return best_batch, best_valid


def _compatible_gpus_v01(micro_batches, max_batch, min_gpus=None, max_gpus=None,
                         prefer_larger=True):
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_batch // min(micro_batches)
    if not all(m <= max_batch for m in micro_batches):
        raise ElasticityError(f"all micro batches must be <= {max_batch}")
    lcm = micro_batches[0]
    for m in micro_batches[1:]:
        lcm = lcm * m // math.gcd(lcm, m)
    candidates = _candidate_batch_sizes(list(micro_batches) + [lcm], max_batch)
    return _best_candidate(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _compatible_gpus_v02(micro_batches, max_batch, current_num_gpus, min_gpus, max_gpus,
                         prefer_larger, num_gpus_per_node, model_parallel_size):
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(f"chips per node {num_gpus_per_node} must be divisible by "
                              f"model parallel size {model_parallel_size}")
    dp_per_node = num_gpus_per_node // model_parallel_size

    def pick_micro(batch):
        chosen = None
        for m in micro_batches:
            if (batch // current_num_gpus) % m == 0:
                if chosen is None or (prefer_larger and m > chosen):
                    chosen = m
        return chosen

    batch, valid_nodes = _compatible_gpus_v01(
        micro_batches, max_batch // dp_per_node,
        max(1, min_gpus // num_gpus_per_node), max(1, max_gpus // num_gpus_per_node),
        prefer_larger)
    batch *= dp_per_node
    valid_dp = [n * dp_per_node for n in valid_nodes]
    if current_num_gpus // model_parallel_size in valid_dp:
        return batch, valid_dp, pick_micro(batch)

    # current world incompatible with the elastic set: fix batch to the current
    # dp size (reference _get_compatible_gpus_v02 fallback — float node ratio,
    # so a sub-node world degrades gracefully instead of dividing by zero)
    current_dp = max(1, round((current_num_gpus / num_gpus_per_node) * dp_per_node))
    cands = [m * current_dp * (max_batch // (m * current_dp)) for m in micro_batches
             if m * current_dp <= max_batch]
    if not cands:
        raise ElasticityIncompatibleWorldSize(f"no batch fits {current_num_gpus} chips")
    batch = max(cands) if prefer_larger else min(cands)
    return batch, [int(current_dp)], pick_micro(batch)


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str = "0.13.2",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference elasticity.py:233. Returns (final_batch_size, valid_gpus[,
    micro_batch]) — deterministic for a given config, so the scheduler and the
    runtime independently agree."""
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(f"config missing {ELASTICITY!r} block")
    cfg = ElasticityConfig(ds_config[ELASTICITY])

    if float(cfg.version) == 0.1:
        batch, valid = _compatible_gpus_v01(cfg.micro_batches, cfg.max_acceptable_batch_size,
                                            cfg.min_gpus, cfg.max_gpus,
                                            cfg.prefer_larger_batch_size)
        micro = None
        if world_size > 0 and world_size not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in elastic set {valid}")
        if return_microbatch and world_size > 0:
            for m in sorted(cfg.micro_batches, reverse=cfg.prefer_larger_batch_size):
                if (batch // world_size) % m == 0:
                    micro = m
                    break
    elif float(cfg.version) == 0.2:
        current = world_size or cfg.num_gpus_per_node
        batch, valid, micro = _compatible_gpus_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, current, cfg.min_gpus,
            cfg.max_gpus, cfg.prefer_larger_batch_size, cfg.num_gpus_per_node,
            cfg.model_parallel_size)
    else:
        raise ElasticityConfigError(f"unknown elasticity version {cfg.version}")

    logger.info(f"elasticity: batch={batch} valid_chip_counts={valid}")
    if return_microbatch:
        return batch, valid, micro
    return batch, valid
