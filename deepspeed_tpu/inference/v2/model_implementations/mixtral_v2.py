"""Mixtral ragged inference model with expert parallelism (fork feature).

Reference: ``deepspeed/inference/v2/model_implementations/mixtral/`` + the fork's
``DSMultiGemmMoEEp`` MoE path (``cutlass_multi_gemm_ep.py:32``).

Consumes the TRAINING param tree of :class:`deepspeed_tpu.models.mixtral.
MixtralForCausalLM` (``layers_i.block_sparse_moe.{gate, ExpertFFN_0.{wi,wo}}``),
so EP inference logits can be tested against the single-device training forward.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.model_implementations.llama_v2 import LlamaV2Model, _rms, _root
from deepspeed_tpu.inference.v2.modules.moe import RaggedMoE
from deepspeed_tpu.inference.v2.tracer import record
from deepspeed_tpu.models.mixtral import MixtralConfig


class MixtralV2Model(LlamaV2Model):

    def __init__(self, params, config: MixtralConfig, engine_config, state_manager=None):
        super().__init__(params, config.as_llama(), engine_config, state_manager)
        self._moe_config = config
        ep_cfg = getattr(engine_config, "expert_parallel", None)
        self._moes = [
            RaggedMoE(num_experts=config.num_local_experts,
                      top_k=config.num_experts_per_tok,
                      capacity_factor=(ep_cfg.capacity_factor if ep_cfg is not None else 2.0),
                      layer_id=li) for li in range(config.num_hidden_layers)
        ]

    @property
    def num_layers(self):
        return self._moe_config.num_hidden_layers

    def _moe_params(self, params, li):
        mp = _root(params)[f"layers_{li}"]["block_sparse_moe"]
        return mp["gate"], mp["ExpertFFN_0"]["wi"], mp["ExpertFFN_0"]["wo"]

    def _ffn_phase(self, params, li, x, batch=None):
        cfg = self._moe_config
        lp = _root(params)[f"layers_{li}"]
        h = _rms(x, lp["post_attention_layernorm"]["weight"], cfg.rms_norm_eps)
        gate_w, wi, wo = self._moe_params(params, li)
        token_valid = None if batch is None else batch["token_valid"]
        # Data-dependent gating seed: live token positions differ every decode
        # step, so simulated-gating routing varies across forwards (the fork's
        # load-testing intent) without threading a host counter through jit.
        gate_seed = None if batch is None else jnp.sum(
            jnp.where(batch["token_valid"], batch["token_pos"], 0)).astype(jnp.int32)
        out = self._moes[li](h, gate_w, wi, wo, token_valid=token_valid,
                             activation=jax.nn.silu, gate_seed=gate_seed)
        return x + out.astype(x.dtype)

    def layer_forward(self, params, li, x, cache, attn_fn, batch):
        x, cache = self._attn_phase(params, li, x, cache, attn_fn, batch)
        return self._ffn_phase(params, li, x, batch=batch), cache

    def layer_forward_traced(self, params, li, x, cache, attn_fn, batch):
        with record("attn"):
            x, cache = self._attn_phase(params, li, x, cache, attn_fn, batch)
            x.block_until_ready()
        with record("moe_ffn"):
            x = self._ffn_phase(params, li, x, batch=batch)
            x.block_until_ready()
        return x, cache
