"""0/1 Adam.

Reference: ``deepspeed/runtime/fp16/onebit/zoadam.py`` (ZeroOneAdam,
arXiv:2202.06009). Semantics reproduced:

- **Variance-update policy** (zoadam.py:265-280): until ``var_freeze_step`` the
  variance refreshes only at steps divisible by ``var_interval``; each
  ``var_update_scaler`` refreshes, the interval doubles. At refresh steps the
  momentum consumes the exact gradient; between refreshes it consumes the
  sign-compressed gradient with error feedback (zoadam.py:205-218).
- **Local-step policy** (zoadam.py:241-261): after the variance freezes,
  parameters advance every step while the accumulated update
  (``momentum_accumulator``) syncs only every ``local_step_interval`` steps —
  scaled by the denominator, sign-compressed with error feedback, and the
  momentum is re-seeded from the synced buffer divided by the accumulated lr.
  The interval doubles every ``local_step_scaler`` counts, clipped at
  ``local_step_clipper``.

TPU note: under single-program SPMD the gradient arriving here is already the
group mean (XLA's psum), so the compression models the wire fidelity while the
interval policies reproduce the optimizer's trajectory exactly.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class ZeroOneAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    worker_error: any         # gradient-compression error (warmup stage)
    sync_error: any           # buffer-compression error (local-step stage —
                              # the reference reinitializes its error buffers at
                              # the freeze transition, zoadam.py:306-311)
    comm_buffer: any          # momentum_accumulator (local-step stage)
    lrs: jnp.ndarray          # accumulated lr between syncs
    var_interval: jnp.ndarray
    var_counter: jnp.ndarray
    local_interval: jnp.ndarray
    local_counter: jnp.ndarray


class ZeroOneAdam(TpuOptimizer):

    name = "zerooneadam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 var_freeze_step=100, var_update_scaler=16, local_step_scaler=100,
                 local_step_clipper=16, cuda_aware=False, comm_backend_name="xla"):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.betas = betas
        self.eps = eps
        self.var_freeze_step = int(var_freeze_step)
        self.var_update_scaler = int(var_update_scaler)
        self.local_step_scaler = int(local_step_scaler)
        self.local_step_clipper = int(local_step_clipper)

    def init(self, params):
        return ZeroOneAdamState(step=jnp.zeros([], jnp.int32),
                                exp_avg=_tree_zeros_like(params),
                                exp_avg_sq=_tree_zeros_like(params),
                                worker_error=_tree_zeros_like(params),
                                sync_error=_tree_zeros_like(params),
                                comm_buffer=_tree_zeros_like(params),
                                lrs=jnp.zeros([], jnp.float32),
                                var_interval=jnp.ones([], jnp.int32),
                                var_counter=jnp.zeros([], jnp.int32),
                                local_interval=jnp.ones([], jnp.int32),
                                local_counter=jnp.zeros([], jnp.int32))

    def update(self, grads, state, params, lr):
        b1, b2 = self.betas
        eps = self.eps
        wd = self.weight_decay
        step = state.step + 1
        frozen = step > self.var_freeze_step
        var_refresh = (~frozen) & (step % state.var_interval == 0)
        sync_now = frozen & (step % state.local_interval == 0)
        lrs_new = jnp.where(sync_now, 0.0, jnp.where(frozen, state.lrs + lr, state.lrs))
        lrs_at_sync = state.lrs + lr

        def upd(p, g, m, v, err, serr, buf):
            g = g.astype(p.dtype)
            # between variance refreshes the momentum sees the compressed grad
            compensated = g + err
            scale = jnp.mean(jnp.abs(compensated))
            g_comp = scale * jnp.sign(compensated).astype(p.dtype)
            use_exact = var_refresh | frozen
            g_used = jnp.where(use_exact, g, g_comp)
            err_new = jnp.where(use_exact, err, compensated - g_comp)

            m_new = b1 * m + (1.0 - b1) * g_used
            v_new = jnp.where(var_refresh, b2 * v + (1.0 - b2) * (g * g), v)

            denom = jnp.sqrt(v_new) + eps
            update = m_new / denom
            if wd > 0.0:
                update = update + wd * p
            p_new = p - lr * update
            buf_acc = jnp.where(frozen, buf - lr * update, buf)

            # ---- local-step sync (zoadam.py:243-261) ----
            # revert local drift, sync the denominator-scaled buffer
            # (compressed, error-fed), re-seed momentum, re-apply
            p_revert = p_new - buf_acc
            buf_scaled = buf_acc * denom
            comp2 = buf_scaled + serr
            scale2 = jnp.mean(jnp.abs(comp2))
            buf_sync = scale2 * jnp.sign(comp2).astype(p.dtype)
            serr_sync = comp2 - buf_sync
            m_sync = -buf_sync / jnp.maximum(lrs_at_sync, 1e-12)
            p_sync = p_revert + buf_sync / denom

            p_out = jnp.where(sync_now, p_sync, p_new)
            m_out = jnp.where(sync_now, m_sync, m_new)
            buf_out = jnp.where(sync_now, jnp.zeros_like(buf), buf_acc)
            serr_out = jnp.where(sync_now, serr_sync, serr)
            return p_out, m_out, v_new, err_new, serr_out, buf_out

        p_flat, treedef = jax.tree.flatten(params)
        flats = [treedef.flatten_up_to(t) for t in
                 (grads, state.exp_avg, state.exp_avg_sq, state.worker_error,
                  state.sync_error, state.comm_buffer)]
        out = [upd(p, *args) for p, *args in zip(p_flat, *flats)]
        unf = lambda i: jax.tree.unflatten(treedef, [o[i] for o in out])

        # interval policies (zoadam.py:265-286)
        vc = jnp.where(var_refresh, state.var_counter + 1, state.var_counter)
        double_var = vc == self.var_update_scaler
        var_counter = jnp.where(~frozen, jnp.where(double_var, 0, vc), state.var_counter)
        var_interval = jnp.where((~frozen) & double_var, state.var_interval * 2,
                                 state.var_interval)
        lc = jnp.where(frozen, state.local_counter + 1, state.local_counter)
        double_local = lc == self.local_step_scaler
        local_counter = jnp.where(frozen, jnp.where(double_local, 0, lc), state.local_counter)
        local_interval = jnp.where(frozen & double_local,
                                   jnp.minimum(self.local_step_clipper,
                                               state.local_interval * 2),
                                   state.local_interval)

        return unf(0), ZeroOneAdamState(step=step, exp_avg=unf(1), exp_avg_sq=unf(2),
                                        worker_error=unf(3), sync_error=unf(4),
                                        comm_buffer=unf(5),
                                        lrs=lrs_new, var_interval=var_interval,
                                        var_counter=var_counter,
                                        local_interval=local_interval,
                                        local_counter=local_counter)
