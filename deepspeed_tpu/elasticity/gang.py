"""Gang liveness primitives: per-rank heartbeat files + agent state document.

The multi-process training gang's weakest failure mode is the *silent* one: a
rank that is wedged inside a collective is indistinguishable from a rank that
is merely slow — its process is alive, the JAX coordination service still
sees its background heartbeat threads, and its peers block forever waiting
for it. The signal that *does* distinguish them is train-loop progress, and
that is what this module carries:

- each rank writes a tiny heartbeat file (``rank<k>.hb``) from the train loop
  (step entry/exit) and around collective entry (``monitored_barrier``) —
  written atomically, read without locks;
- the elastic agent's watchdog reads the heartbeats: a rank whose process is
  alive but whose heartbeat is stale past ``hang_timeout_s`` is *wedged*
  (hung in a collective, deadlocked, or stalled), and the whole gang is torn
  down and relaunched rather than waiting forever;
- the agent also maintains ``gang_state.json`` in the same directory — the
  inspectable record (``bin/dstpu_report --gang``) of world size, valid
  shrink targets, crash history and the last shrink event.

The directory is announced to ranks via ``DSTPU_GANG_DIR`` (exported by
``DSElasticAgent._spawn``); everything here is stdlib-only and costs one
``is None`` check when the env var is absent.
"""

import json
import os
import re
import time
from typing import Dict, Optional

GANG_DIR_ENV = "DSTPU_GANG_DIR"
STATE_FILE = "gang_state.json"

_HB_RE = re.compile(r"^rank(\d+)\.hb$")


def heartbeat_path(gang_dir: str, rank: int) -> str:
    return os.path.join(gang_dir, f"rank{int(rank)}.hb")


def atomic_write_json(path: str, doc: dict) -> None:
    """tmp + os.replace: readers always see a complete JSON document, never a
    torn write — the one atomic-marker primitive the gang machinery shares
    (heartbeats, gang state, checkpoint shard seals, barrier rendezvous)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class GangHeartbeat:
    """One rank's heartbeat writer. ``beat`` is called from the train loop
    (step entry/exit) and at collective entry; each beat atomically replaces
    the rank's heartbeat file, so the watchdog's read is always a complete
    JSON document (never a torn write)."""

    def __init__(self, gang_dir: str, rank: int):
        self.gang_dir = gang_dir
        self.rank = int(rank)
        os.makedirs(gang_dir, exist_ok=True)
        self._path = heartbeat_path(gang_dir, self.rank)

    @classmethod
    def from_env(cls, rank: Optional[int] = None) -> Optional["GangHeartbeat"]:
        """A heartbeat writer when ``DSTPU_GANG_DIR`` is armed, else None
        (the disabled path is one env read at engine init)."""
        gang_dir = os.environ.get(GANG_DIR_ENV)
        if not gang_dir:
            return None
        if rank is None:
            rank = int(os.environ.get("DSTPU_PROCESS_ID", "0") or 0)
        return cls(gang_dir, rank)

    def beat(self, step: Optional[int] = None, phase: str = "step") -> None:
        try:
            atomic_write_json(self._path, {
                "rank": self.rank,
                "unix": time.time(),
                "step": step,
                "phase": phase,
                "pid": os.getpid(),
            })
        except OSError:
            # liveness reporting must never kill the training it reports on
            pass


def read_heartbeats(gang_dir: str) -> Dict[int, dict]:
    """``{rank: heartbeat_doc + "age_s"}`` for every rank that has beaten.
    Unreadable/torn files are skipped (the next beat replaces them)."""
    out: Dict[int, dict] = {}
    if not os.path.isdir(gang_dir):
        return out
    now = time.time()
    for name in os.listdir(gang_dir):
        m = _HB_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(gang_dir, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        doc["age_s"] = max(0.0, now - doc.get("unix", now))
        out[int(m.group(1))] = doc
    return out


def clear_heartbeats(gang_dir: str) -> None:
    """Remove every rank heartbeat (the agent calls this before each launch so
    one life's staleness can never indict the next life's ranks)."""
    if not os.path.isdir(gang_dir):
        return
    for name in os.listdir(gang_dir):
        if _HB_RE.match(name):
            try:
                os.unlink(os.path.join(gang_dir, name))
            except OSError:
                pass


def write_gang_state(gang_dir: str, state: dict) -> None:
    """Atomically publish the agent's state document (``gang_state.json``) —
    what ``bin/dstpu_report --gang`` renders."""
    os.makedirs(gang_dir, exist_ok=True)
    doc = dict(state)
    doc["updated_unix"] = time.time()
    atomic_write_json(os.path.join(gang_dir, STATE_FILE), doc)


def read_gang_state(gang_dir: str) -> Optional[dict]:
    path = os.path.join(gang_dir, STATE_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
