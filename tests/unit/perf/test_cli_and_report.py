"""bin/dstpu_perfgate + dstpu_report --perf + bench.py --microbench plumbing."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
BIN = os.path.join(REPO, "bin")


def _run(script, *args, timeout=300):
    return subprocess.run([sys.executable, os.path.join(BIN, script), *args],
                          capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow  # two subprocess jax imports + flash builds; the diff/check
# logic itself is tier-1-covered by tests/unit/perf/test_gate.py
def test_dstpu_perfgate_diff_single_program(tmp_path):
    """End-to-end CLI on the cheapest flagship program: rebaseline into a
    scratch dir, then diff against it (rc 0, table rendered, JSON written)."""
    r = _run("dstpu_perfgate", "rebaseline", "--program", "flash_attention_fwd_bwd",
             "--budgets", str(tmp_path), "--note", "cli test")
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "flash_attention_fwd_bwd.json").exists()

    out = tmp_path / "gate_report.json"
    r = _run("dstpu_perfgate", "diff", "--program", "flash_attention_fwd_bwd",
             "--budgets", str(tmp_path), "--json", str(out))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "flash_attention_fwd_bwd" in r.stdout
    assert "within budgets" in r.stdout
    report = json.loads(out.read_text())
    assert report["ok"] is True

    # dstpu_report --perf renders the dir (budgets + the report the CLI wrote)
    r = _run("dstpu_report", "--perf", str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "flash_attention_fwd_bwd" in r.stdout
    assert "roofline" in r.stdout


def test_dstpu_perfgate_rejects_unknown_program():
    r = _run("dstpu_perfgate", "diff", "--program", "nope")
    assert r.returncode == 2
    assert "unknown program" in r.stdout


def test_dstpu_report_perf_renders_violating_report(tmp_path):
    """--perf on a gate-report JSON: pure rendering, rc 1 on violations."""
    report = {
        "kind": "dstpu_perfgate_report", "chip": "v5e", "ok": False,
        "programs": {
            "zero3_train_batch": {
                "ok": False,
                "stats": {"flops": 5.1e7, "bytes_accessed": 2.2e7,
                          "peak_bytes": 2.1e6, "collective_bytes_total": 1.1e6,
                          "f32_dot_count": 61},
                "roofline": {"chip": "v5e", "bound": "memory", "step_s": 2.7e-5,
                             "mfu_bound": 0.015},
                "budget_created": "2026-08-04", "budget_missing": False,
                "meta": {},
                "violations": [{"metric": "f32_dot_count", "measured": 61,
                                "budget": 0, "limit": 0,
                                "detail": "accidental f32 upcast"}],
            }
        },
    }
    p = tmp_path / "report.json"
    p.write_text(json.dumps(report))
    r = _run("dstpu_report", "--perf", str(p))
    assert r.returncode == 1
    assert "VIOLATION f32_dot_count" in r.stdout
    assert "budget violations" in r.stdout


def test_dstpu_report_perf_checked_in_budgets():
    """The shipped budgets dir renders without touching jax."""
    budgets = os.path.join(REPO, "deepspeed_tpu", "perf", "budgets")
    r = _run("dstpu_report", "--perf", budgets)
    assert r.returncode == 0, r.stderr
    assert "zero3_train_batch" in r.stdout
    assert "prefix_suffix_prefill" in r.stdout


def test_dstpu_report_perf_bad_path():
    r = _run("dstpu_report", "--perf", "/nonexistent/thing")
    assert r.returncode == 2


# ------------------------------------------------------------ bench plumbing --
def test_bench_microbench_structured_skip_on_cpu():
    """Driver contract under a dead/absent TPU: one JSON line, rc 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"), "--microbench"],
                       capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "paged_decode_kernel_step_ms"
    assert doc["skipped"] == "tpu_unavailable"
    assert doc["extra"]["mode"] == "microbench"


def test_bench_microbench_kernel_bodies_run_tiny():
    """The kernel legs themselves execute (interpret mode, shrunk shapes) —
    the TPU run uses the same code with the default shapes."""
    import jax.numpy as jnp
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    r = bench._microbench_int4_unpack(jnp, K=64, N=64, N1=1, N2=3)
    assert set(r) >= {"bf16", "int4", "int4_speedup"}
    assert r["int4"]["matmul_us"] > 0
    r = bench._microbench_paged_decode(jnp, T=2, H=2, KVH=2, D=16, bs=4, S=2, MB=4,
                                       N1=1, N2=2)
    assert r["kernel_step_ms"] > 0
    assert r["context"] == 16
