"""Top-level API parity with ``deepspeed/__init__.py`` — a reference user's
imports must resolve (VERDICT-standard surface check)."""

import numpy as np
import pytest

import deepspeed_tpu


def test_reference_top_level_names_exist():
    names = ["initialize", "init_inference", "init_distributed",
             "add_config_arguments", "add_tuning_arguments",
             "default_inference_config", "DeepSpeedEngine",
             "DeepSpeedHybridEngine", "PipelineEngine", "InferenceEngine",
             "InferenceEngineV2", "DeepSpeedInferenceConfig", "DeepSpeedConfig",
             "DeepSpeedConfigError", "checkpointing", "zero", "PipelineModule",
             "ops", "module_inject", "get_accelerator", "log_dist", "OnDevice",
             "logger", "comm", "dist", "DeepSpeedOptimizer", "ZeROOptimizer",
             "DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig",
             "replace_transformer_layer", "revert_transformer_layer",
             "__version__", "git_hash", "git_branch"]
    missing = [n for n in names if not hasattr(deepspeed_tpu, n)]
    assert not missing, missing
    with pytest.raises(AttributeError):
        deepspeed_tpu.definitely_not_a_real_name


def test_lazy_engine_classes_resolve_to_real_classes():
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    assert deepspeed_tpu.DeepSpeedEngine is DeepSpeedEngine
    assert issubclass(deepspeed_tpu.PipelineEngine, DeepSpeedEngine)


def test_replace_transformer_layer_points_at_checkpoint_path():
    with pytest.raises(NotImplementedError, match="init_inference"):
        deepspeed_tpu.replace_transformer_layer()
    with pytest.raises(NotImplementedError, match="checkpoint"):
        deepspeed_tpu.revert_transformer_layer()


def test_on_device_scopes_default_device():
    import jax
    import jax.numpy as jnp

    cpu0 = jax.devices()[0]
    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device=cpu0):
        x = jnp.ones(4)
        assert deepspeed_tpu.OnDevice.current_dtype() == jnp.bfloat16
    assert list(x.devices()) == [cpu0]
    assert deepspeed_tpu.OnDevice.current_dtype() is None

    with pytest.raises(NotImplementedError, match="zero.Init"):
        deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device="meta")

    # disabled is a no-op passthrough
    with deepspeed_tpu.OnDevice(dtype=jnp.float32, device="meta", enabled=False):
        assert deepspeed_tpu.OnDevice.current_dtype() is None


def test_on_device_casts_init_dtype_and_is_reentrant():
    """The dtype knob must actually act (module.init leaves cast) and nested
    scopes must unwind correctly."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    cpu0 = jax.devices()[0]
    od = deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device=cpu0)
    with od:
        with od:  # reentrant: same instance nested
            v = M().init(jax.random.PRNGKey(0), jnp.ones((1, 4)))
        assert deepspeed_tpu.OnDevice.current_dtype() == jnp.bfloat16
    kernel = v["params"]["Dense_0"]["kernel"]
    assert kernel.dtype == jnp.bfloat16
    # the patch is unwound: init outside the scope is fp32 again
    v2 = M().init(jax.random.PRNGKey(0), jnp.ones((1, 4)))
    assert v2["params"]["Dense_0"]["kernel"].dtype == jnp.float32


def test_zero_engine_optimizer_isinstance_markers():
    """Reference-style isinstance checks on engine.optimizer must hold:
    DeepSpeedOptimizer always, ZeROOptimizer exactly when ZeRO shards."""
    from deepspeed_tpu.utils import groups

    from .simple_model import make_simple_model, random_batches

    groups.initialize_mesh(force=True)
    model, params = make_simple_model(hidden_dim=16, batch_size=8)

    def eng(stage):
        groups.initialize_mesh(force=True)
        e, opt, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": stage}})
        return e, opt

    e0, opt0 = eng(0)
    assert isinstance(opt0, deepspeed_tpu.DeepSpeedOptimizer)
    assert not isinstance(opt0, deepspeed_tpu.ZeROOptimizer)
    e2, opt2 = eng(2)
    assert isinstance(opt2, deepspeed_tpu.ZeROOptimizer)
    # the remix keeps the optimizer functional
    float(e2.train_batch(batch=random_batches(1, 8, 16)[0]))


def test_user_supplied_optimizer_not_mutated_by_zero_marker():
    """A user-supplied optimizer object (any init/update duck type) must not
    have its class rewritten by the ZeRO marker mixin."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.utils import groups
    from .simple_model import make_simple_model, random_batches

    class UserSGD:
        def __init__(self):
            self.lr = 1e-2
            self.weight_decay = 0.0

        def init(self, params):
            return ()

        def update(self, grads, state, params, lr):
            return jax.tree.map(lambda g: -lr * g, grads), state

        def get_lr(self):
            return self.lr

        def set_lr(self, lr):
            self.lr = lr

    groups.initialize_mesh(force=True)
    model, params = make_simple_model(hidden_dim=16, batch_size=8)
    opt = UserSGD()
    cls_before = type(opt)
    eng, ret_opt, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, optimizer=opt,
        config={"train_micro_batch_size_per_gpu": 8,
                "zero_optimization": {"stage": 2}})
    assert type(opt) is cls_before  # untouched
    assert not isinstance(ret_opt, deepspeed_tpu.ZeROOptimizer)
    loss = float(eng.train_batch(batch=random_batches(1, 8, 16)[0]))
    assert np.isfinite(loss)
