"""ZeRO-Infinity NVMe tier: optimizer states at rest on disk.

Reference semantics: ``deepspeed/runtime/swap_tensor/partitioned_optimizer_swapper.py:29``
+ ``zero/stage3.py:1816``: between steps the accelerator (and host) holds no
optimizer state — only files under ``nvme_path``; the step swaps in, updates,
swaps out. Numerics are identical to the in-HBM run.
"""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.swap_tensor import NvmeSwappedLeaf
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches

HIDDEN = 16


def _cfg(stage, nvme_path=None, gas=1):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": {"stage": stage},
    }
    if nvme_path is not None:
        cfg["zero_optimization"]["offload_optimizer"] = {
            "device": "nvme", "nvme_path": str(nvme_path), "buffer_count": 2}
        cfg["aio"] = {"thread_count": 2, "queue_depth": 4}
    return cfg


def _stub_leaves(opt_state):
    import jax
    return [l for l in jax.tree.leaves(opt_state) if isinstance(l, NvmeSwappedLeaf)]


def _train(engine, batches, fused=False):
    if fused:
        for b in batches:
            engine.train_batch(batch=b)
    else:
        for b in batches:
            loss = engine.forward(b)
            engine.backward(loss)
            engine.step()


@pytest.mark.parametrize("stage", [1, 3])
@pytest.mark.parametrize("fused", [False, True])
def test_nvme_parity_and_residency(tmp_path, stage, fused):
    """device=nvme trains to the exact same params as the in-HBM run, and
    between steps every moment leaf is a file stub — no array anywhere."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage))
    _train(ref, batches, fused)

    groups.initialize_mesh(force=True)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage, nvme_path=tmp_path / "swap"))
    # at rest (post-init): moments are stubs backed by real files
    stubs = _stub_leaves(eng.opt_state)
    assert stubs, "optimizer state should be swapped out after init"
    eng._offload.swapper._drain_writes()  # write-back is async by design
    for s in stubs:
        assert os.path.exists(s.path)
    _train(eng, batches, fused)
    assert _stub_leaves(eng.opt_state), "state must return to NVMe after each step"

    for g, w in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(g, w, rtol=1e-6, atol=1e-6)


def test_nvme_checkpoint_roundtrip(tmp_path):
    """save_checkpoint materializes states from disk; load_checkpoint swaps the
    restored tree straight back out to NVMe, and training continues bit-exact."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(6, 16, HIDDEN)

    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(2, nvme_path=tmp_path / "swapA"))
    _train(eng, batches[:3])
    eng.save_checkpoint(tmp_path / "ckpt", tag="t3")
    _train(eng, batches[3:])
    final_direct = jax.device_get(eng.params)

    groups.initialize_mesh(force=True)
    eng2, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                             config=_cfg(2, nvme_path=tmp_path / "swapB"))
    eng2.load_checkpoint(tmp_path / "ckpt", tag="t3")
    assert _stub_leaves(eng2.opt_state), "restored state must live on NVMe"
    _train(eng2, batches[3:])
    for a, b in zip(jax.tree.leaves(jax.device_get(eng2.params)),
                    jax.tree.leaves(final_direct)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_swapper_unit(tmp_path):
    """Swapper alone: tree out → stubs, tree in → identical arrays."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.swap_tensor import PartitionedOptimizerSwapper

    tree = {"m": jnp.arange(64, dtype=jnp.float32),
            "v": {"a": jnp.ones((8, 8), jnp.bfloat16), "b": jnp.zeros((3, ), jnp.int32)}}
    sw = PartitionedOptimizerSwapper(str(tmp_path), buffer_count=1)
    stubs = sw.swap_out(tree)
    assert all(isinstance(l, NvmeSwappedLeaf) for l in jax.tree.leaves(stubs))
    back = sw.swap_in(stubs, None)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    host = sw.materialize_host(stubs)
    assert isinstance(jax.tree.leaves(host)[0], np.ndarray)
    sw.close()
