"""Automatic prefix caching through the serving scheduler: token-identical
outputs with the cache on vs off (greedy AND sampled), the CPU perf gate (a
fully-cached prompt schedules only its last token — zero prefill chunks),
eviction-under-pressure preferring unreferenced trie leaves, refcount
correctness under concurrent admit/evict/cancel, and fleet handoff of
sequences holding shared blocks.

Mechanism units (allocator refcounts, the radix index, COW forks) live in
tests/unit/inference/v2/test_prefix_cache.py.
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving import (PrefixCacheConfig, RequestState, ServingConfig,
                                   ServingScheduler)

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _cached_config(**pc_kw):
    pc_kw.setdefault("enabled", True)
    return ServingConfig(prefix_cache=PrefixCacheConfig(**pc_kw))


def _prompt(cfg, n, seed=0):
    return np.random.default_rng(seed).integers(0, cfg.vocab_size, n).tolist()


# --------------------------------------------------------- token identity --
def test_token_identical_greedy_full_and_partial_hit(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    full = _prompt(cfg, 64)                     # 4 full blocks: a full hit
    partial = full[:32] + _prompt(cfg, 30, 1)   # 2 shared blocks + cold tail

    cold_engine = make_engine()
    cold = ServingScheduler(cold_engine, ServingConfig(), start=False)
    warm_engine = make_engine()
    warm = ServingScheduler(warm_engine, _cached_config(), start=False)
    try:
        expect = {}
        for key, prompt in (("full", full), ("partial", partial)):
            req = cold.submit(prompt, max_new_tokens=6)
            _run_until(cold, lambda: req.finished)
            expect[key] = req.result()

        seed_req = warm.submit(full, max_new_tokens=6)  # publisher (cold miss)
        _run_until(warm, lambda: seed_req.finished)
        assert seed_req.cached_tokens == 0
        assert seed_req.result() == expect["full"]

        hit = warm.submit(full, max_new_tokens=6)
        _run_until(warm, lambda: hit.finished)
        assert hit.cached_tokens == 63  # fully cached: only the last token re-fed
        assert hit.result() == expect["full"]

        part = warm.submit(partial, max_new_tokens=6)
        _run_until(warm, lambda: part.finished)
        assert part.cached_tokens == 32  # the shared block-aligned prefix
        assert part.result() == expect["partial"]
    finally:
        cold.stop(drain=False)
        warm.stop(drain=False)
    # the trie's pins release at stop: no leaked device blocks
    assert warm_engine.free_blocks == warm_engine._state_manager.kv_cache.num_blocks


def test_token_identical_sampled(make_engine, llama_setup):
    """Sampling draws from a per-request seeded stream; a hit changes where
    prefix KV comes from, never the logits or the draw sequence."""
    cfg, _, _ = llama_setup
    prompt = _prompt(cfg, 48)
    kw = dict(max_new_tokens=6, temperature=0.8, seed=1234)

    cold = ServingScheduler(make_engine(), ServingConfig(), start=False)
    warm = ServingScheduler(make_engine(), _cached_config(), start=False)
    try:
        ref = cold.submit(prompt, **kw)
        _run_until(cold, lambda: ref.finished)

        seed_req = warm.submit(prompt, **kw)
        _run_until(warm, lambda: seed_req.finished)
        hit = warm.submit(prompt, **kw)
        _run_until(warm, lambda: hit.finished)
        assert hit.cached_tokens == 47
        assert seed_req.result() == ref.result()
        assert hit.result() == ref.result()
    finally:
        cold.stop(drain=False)
        warm.stop(drain=False)


# ------------------------------------------------------------- perf gate --
def test_full_hit_schedules_zero_prefill_chunks_cpu_perf_gate(make_engine, llama_setup):
    """The chip-independent perf evidence (ROADMAP item 1 direction): via the
    PR-4 compile/step counters, a repeated prompt executes ZERO prefill model
    chunks — the engine is fed exactly the suffix (one last-token step) plus
    the decode inputs, and no new XLA program compiles."""
    cfg, _, _ = llama_setup
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, _cached_config(), start=False)
    prompt = _prompt(cfg, 64)
    N = 6

    def counters():
        snap = telemetry.get_registry().snapshot()
        return (sum(v for _, v in snap.get("inference_tokens_total", [])),
                sum(v for _, v in snap.get("inference_batches_total", [])),
                sum(v for _, v in snap.get("compile_cache_misses_total", [])))

    try:
        cold = sched.submit(prompt, max_new_tokens=N)
        _run_until(sched, lambda: cold.finished)
        tok0, batch0, compile0 = counters()
        # cold fed the whole prompt plus N-1 decode inputs
        assert tok0 == 64 + N - 1

        warm = sched.submit(prompt, max_new_tokens=N)
        _run_until(sched, lambda: warm.finished)
        tok1, batch1, compile1 = counters()
        # the first warm request may compile once-per-process programs (the
        # COW fork copy, a decode bucket the cold run never hit); the SECOND
        # warm request is the steady state the gate measures
        warm2 = sched.submit(prompt, max_new_tokens=N)
        _run_until(sched, lambda: warm2.finished)
        tok2, batch2, compile2 = counters()
    finally:
        sched.stop(drain=False)

    assert warm.result() == cold.result()
    assert warm2.result() == cold.result()
    # prefill tokens fed == suffix length (1): the whole warm request cost
    # exactly N single-token steps — zero prefill chunks
    assert tok1 - tok0 == N
    assert batch1 - batch0 == N
    assert tok2 - tok1 == N
    assert compile2 == compile1  # steady state: nothing compiles, no prefill bucket runs
    stats = sched.stats()
    assert stats["counters"]["prefix_hits"] == 2
    assert stats["counters"]["prefix_tokens_saved"] == 126


def test_partial_hit_prefills_only_the_suffix(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, _cached_config(), start=False)
    base = _prompt(cfg, 64)
    try:
        seed_req = sched.submit(base, max_new_tokens=2)
        _run_until(sched, lambda: seed_req.finished)
        snap = telemetry.get_registry().snapshot()
        tok0 = sum(v for _, v in snap.get("inference_tokens_total", []))

        fork = base[:48] + _prompt(cfg, 16, 7)  # 3 shared blocks + 16 new tokens
        req = sched.submit(fork, max_new_tokens=2)
        _run_until(sched, lambda: req.finished)
        snap = telemetry.get_registry().snapshot()
        tok1 = sum(v for _, v in snap.get("inference_tokens_total", []))
    finally:
        sched.stop(drain=False)
    assert req.cached_tokens == 48
    assert tok1 - tok0 == 16 + 1  # the 16-token suffix + one decode input


# --------------------------------------------------------------- eviction --
def test_eviction_under_pressure_prefers_trie_leaves(make_engine, llama_setup):
    """KV pressure reclaims cached-but-idle trie blocks (LRU) BEFORE
    offloading any live sequence."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=8)  # 8 x 16 tokens
    # max_prefill_chunk keeps every chunk in the T=64 pad bucket (a 96-token
    # chunk would compile a T=128 program just for this test)
    cfg_pc = _cached_config().model_copy(update={"max_prefill_chunk": 48})
    sched = ServingScheduler(engine, cfg_pc, start=False)
    try:
        seed_req = sched.submit(_prompt(cfg, 48), max_new_tokens=2)
        _run_until(sched, lambda: seed_req.finished)
        assert sched._prefix_cache.n_blocks == 3  # 48 committed tokens pinned

        big = sched.submit(_prompt(cfg, 96, 5), max_new_tokens=2)  # needs 7 blocks
        _run_until(sched, lambda: big.finished)
        stats = sched.stats()
        assert big.state is RequestState.DONE
        assert stats["counters"]["prefix_evictions"] >= 1
        assert stats["counters"]["evictions"] == 0  # no live sequence offloaded
    finally:
        sched.stop(drain=False)
    assert engine.free_blocks == 8


def test_trie_never_starves_admissions(make_engine, llama_setup):
    """A trie pinning most of the pool must yield to new work: back-to-back
    distinct prompts each publish, evict, and complete."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=6)
    sched = ServingScheduler(engine, _cached_config(), start=False)
    try:
        for seed in range(3):
            req = sched.submit(_prompt(cfg, 64, seed + 10), max_new_tokens=2)
            _run_until(sched, lambda: req.finished)
            assert req.state is RequestState.DONE
    finally:
        sched.stop(drain=False)
    assert engine.free_blocks == 6


def test_failed_cow_fork_leaks_no_references(make_engine, llama_setup,
                                             monkeypatch):
    """A device failure inside the copy-on-write fork degrades the request to
    a cold prefill AND drops every reference the hit acquired — the trie's
    blocks stay evictable (refcount 1) instead of ratcheting up per retry."""
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, _cached_config(), start=False)
    prompt = _prompt(cfg, 32)
    kv = engine._state_manager.kv_cache
    try:
        seed_req = sched.submit(prompt, max_new_tokens=2)
        _run_until(sched, lambda: seed_req.finished)
        trie_blocks = [n.block for n in sched._prefix_cache._by_digest.values()]

        monkeypatch.setattr(kv, "fork_blocks",
                            lambda *a, **k: (_ for _ in ()).throw(
                                RuntimeError("injected fork failure")))
        req = sched.submit(prompt, max_new_tokens=2)
        _run_until(sched, lambda: req.finished)
        assert req.state is RequestState.DONE
        assert req.cached_tokens == 0  # degraded to a cold prefill
        assert req.result() == seed_req.result()
    finally:
        sched.stop(drain=False)
    assert engine.free_blocks == kv.num_blocks  # nothing leaked
    for b in trie_blocks:
        with pytest.raises(ValueError):  # fully freed at stop: refs hit zero
            kv.free([b])


# ------------------------------------------------------------ concurrency --
def test_refcount_correctness_under_concurrent_admit_evict_cancel(make_engine,
                                                                  llama_setup):
    """Hammer the cache from many submitter threads with mid-flight
    cancellations on a pool small enough to force trie evictions: no double
    free (the allocator raises — step() would log and the accounting below
    would drift), no freeing a shared block under a live sequence, and the
    pool balances exactly at the end."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=24)
    sched = ServingScheduler(engine, _cached_config())
    prefixes = [_prompt(cfg, 32, 100 + g) for g in range(3)]
    requests, lock = [], threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        for i in range(4):
            prompt = prefixes[int(rng.integers(3))] + \
                rng.integers(0, cfg.vocab_size, 8).tolist()
            req = sched.submit(prompt, max_new_tokens=3)
            with lock:
                requests.append(req)
            if rng.random() < 0.3:
                time.sleep(float(rng.random()) * 0.01)
                req.cancel()

    threads = [threading.Thread(target=client, args=(s, )) for s in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 120
    for req in requests:
        assert req.wait(timeout=max(0.1, deadline - time.monotonic())), req

    pc = sched._prefix_cache
    kv = engine._state_manager.kv_cache
    # every device block is either free or pinned exactly once by the trie
    assert engine.free_blocks + pc.n_blocks == kv.num_blocks
    assert engine._state_manager.n_tracked_sequences == 0
    sched.stop(drain=False)
    assert engine.free_blocks == kv.num_blocks  # trie pins released


# ---------------------------------------------------------------- handoff --
def test_handoff_of_sequence_holding_shared_blocks_token_identical(make_engine,
                                                                   llama_setup):
    """Fleet prefill→decode handoff of a request served from the cache: the
    export materializes shared-block contents, the donor's trie keeps its
    references, and the continuation matches the single-engine run exactly."""
    cfg, _, _ = llama_setup
    prompt = _prompt(cfg, 64)

    donor_engine = make_engine()
    donor = ServingScheduler(donor_engine, _cached_config(), start=False)
    recipient = ServingScheduler(make_engine(), ServingConfig(), start=False)
    try:
        # the publisher doubles as the single-engine ground truth (cold miss)
        whole = donor.submit(prompt, max_new_tokens=8)
        _run_until(donor, lambda: whole.finished)
        assert whole.cached_tokens == 0

        head = donor.submit(prompt, max_new_tokens=4, handoff=True)
        _run_until(donor, lambda: head.finished)
        assert head.cached_tokens == 63  # the handed-off sequence shared blocks
        assert head.handoff_payload is not None
        # donor side stays coherent: trie intact, no block leaked or lost
        hit_again = donor.submit(prompt, max_new_tokens=2)
        _run_until(donor, lambda: hit_again.finished)
        assert hit_again.cached_tokens == 63

        tail = recipient.submit_resume(head.handoff_payload, max_new_tokens=4)
        _run_until(recipient, lambda: tail.finished)
        assert head.result() + tail.result() == whole.result()
    finally:
        donor.stop(drain=False)
        recipient.stop(drain=False)
    assert donor_engine.free_blocks == donor_engine._state_manager.kv_cache.num_blocks


# -------------------------------------------------------- stats and config --
def test_stats_and_flight_report_prefix_cache(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    sched = ServingScheduler(make_engine(), _cached_config(), start=False)
    prompt = _prompt(cfg, 32)
    try:
        r1 = sched.submit(prompt, max_new_tokens=2)
        _run_until(sched, lambda: r1.finished)
        r2 = sched.submit(prompt, max_new_tokens=200)
        _run_until(sched, lambda: r2.state is RequestState.DECODE)
        doc = sched.stats()
        pc = doc["prefix_cache"]
        assert pc["lookups"] == 2 and pc["hits"] == 1
        assert 0 < pc["hit_rate"] < 1
        assert pc["trie_blocks"] == 2
        assert [r["cached_tokens"] for r in doc["requests"]] == [31]
        flight = sched.flight_state()
        assert flight["prefix_cache"]["hits"] == 1
        assert flight["requests"][0]["cached_tokens"] == 31
        r2.cancel()
        _run_until(sched, lambda: r2.finished)
    finally:
        sched.stop(drain=False)


def test_stats_report_none_when_disabled(make_engine):
    sched = ServingScheduler(make_engine(), ServingConfig(), start=False)
    try:
        assert sched.stats()["prefix_cache"] is None
    finally:
        sched.stop(drain=False)


def test_prefix_cache_config_validation():
    with pytest.raises(Exception):
        PrefixCacheConfig(max_blocks=0)
    with pytest.raises(Exception):
        PrefixCacheConfig(min_prefix_blocks=0)
    cfg = ServingConfig(prefix_cache={"enabled": True, "max_blocks": 64,
                                      "min_prefix_blocks": 2})
    assert cfg.prefix_cache.enabled and cfg.prefix_cache.max_blocks == 64


def test_fleet_config_plumbs_prefix_cache_per_role():
    """FleetConfig.prefix_cache is authoritative per role when enabled: the
    prefill/mixed pools cache, the decode pool (which only imports handed-off
    KV) does not — and an operator's serving config keeps its own block when
    the fleet stays silent."""
    from deepspeed_tpu.fleet.config import FleetConfig
    from deepspeed_tpu.fleet.manager import ReplicaManager

    fleet = FleetConfig(prefix_cache=PrefixCacheConfig(enabled=True, max_blocks=32))
    mgr = ReplicaManager(config=fleet,
                         serving_config=ServingConfig(default_max_new_tokens=7))
    for role in ("mixed", "prefill"):
        sc = mgr._role_serving_config(role)
        assert sc.prefix_cache.enabled and sc.prefix_cache.max_blocks == 32
        assert sc.default_max_new_tokens == 7  # the base config survives
    assert not mgr._role_serving_config("decode").prefix_cache.enabled

    # fleet silent -> the replica-level serving config is untouched
    silent = ReplicaManager(config=FleetConfig(),
                            serving_config=_cached_config())
    assert silent._role_serving_config("decode").prefix_cache.enabled
