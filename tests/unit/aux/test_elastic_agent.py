"""Elastic agent: restart-on-failure with elasticity-valid world shrink
(reference deepspeed/elasticity/elastic_agent.py DSElasticAgent)."""

import os
import sys
import textwrap

import pytest

from deepspeed_tpu.elasticity import DSElasticAgent, ElasticAgentError

ELASTIC_CFG = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                              "micro_batch_sizes": [2, 4, 6], "min_gpus": 1,
                              "max_gpus": 64, "version": 0.1}}


def _worker_script(tmp_path, fail_first: bool):
    """Rank 0 fails on the first attempt (before any flag exists), then
    succeeds — the restart path."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, sys, pathlib
        flag = pathlib.Path({str(repr(str(tmp_path / 'attempted')))})
        rank = os.environ["DSTPU_PROCESS_ID"]
        world = os.environ["DSTPU_NUM_PROCESSES"]
        log = pathlib.Path({str(repr(str(tmp_path)))}) / f"rank{{rank}}_restart{{os.environ['DSTPU_ELASTIC_RESTART']}}.txt"
        log.write_text(world)
        if {fail_first!r} and rank == "0" and not flag.exists():
            flag.write_text("1")
            sys.exit(3)
        sys.exit(0)
    """))
    return str(path)


def test_agent_clean_run(tmp_path):
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=False)],
                           num_processes=2, max_restarts=1, monitor_interval=0.05)
    assert agent.run() == 0
    assert agent.restart_count == 0
    assert (tmp_path / "rank1_restart0.txt").exists()


def test_agent_restarts_after_failure(tmp_path):
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=True)],
                           num_processes=2, max_restarts=2, monitor_interval=0.05)
    assert agent.run() == 0
    assert agent.restart_count == 1
    assert (tmp_path / "rank0_restart1.txt").exists(), "second attempt must have run"


def test_agent_exports_restart_count_for_chaos_one_shot(tmp_path):
    """DSTPU_RESTART_COUNT drives the training chaos injector's one-shot
    kill/sigterm suppression (runtime/faults.first_life): every relaunch
    must see its life number or a deterministic kill replays forever."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, pathlib, sys
        d = pathlib.Path({str(repr(str(tmp_path)))})
        life = os.environ["DSTPU_ELASTIC_RESTART"]
        (d / f"rc{{life}}").write_text(os.environ.get("DSTPU_RESTART_COUNT", "missing"))
        sys.exit(3 if life == "0" else 0)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=1,
                           max_restarts=2, monitor_interval=0.05)
    assert agent.run() == 0
    assert (tmp_path / "rc0").read_text() == "0"
    assert (tmp_path / "rc1").read_text() == "1"


def test_agent_gives_up_after_max_restarts(tmp_path):
    path = tmp_path / "always_fail.py"
    path.write_text("import sys; sys.exit(1)")
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=1,
                           max_restarts=1, monitor_interval=0.05)
    with pytest.raises(ElasticAgentError, match="after 1 restarts"):
        agent.run()


def test_agent_shrinks_to_valid_world(tmp_path):
    """After a node loss the new world size must come from the elastic set."""
    agent = DSElasticAgent(["true"], num_processes=8, ds_config=ELASTIC_CFG,
                           max_restarts=1)
    from deepspeed_tpu.elasticity import compute_elastic_config
    _, valid = compute_elastic_config(ELASTIC_CFG)
    w = agent.next_world_size(capacity=7)
    assert w in valid and w <= 7
    # larger capacity → at least as large a world
    assert agent.next_world_size(capacity=64) >= w


def test_agent_no_valid_world_raises():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2], "min_gpus": 40, "max_gpus": 64,
                          "version": 0.1}}
    agent = DSElasticAgent(["true"], num_processes=64, ds_config=cfg, max_restarts=1)
    with pytest.raises(ElasticAgentError, match="fits the surviving capacity"):
        agent.next_world_size(capacity=2)


def test_agent_restart_shrinks_world_end_to_end(tmp_path):
    """Failure + reduced capacity → relaunch with a *smaller, valid* world;
    workers observe the shrunken DSTPU_NUM_PROCESSES."""
    caps = iter([3])  # after the failure, only 3 slots survive
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=True)],
                           num_processes=4, ds_config=ELASTIC_CFG, max_restarts=2,
                           monitor_interval=0.05, capacity_fn=lambda: next(caps))
    assert agent.run() == 0
    from deepspeed_tpu.elasticity import compute_elastic_config
    _, valid = compute_elastic_config(ELASTIC_CFG)
    observed = int((tmp_path / "rank0_restart1.txt").read_text())
    assert observed <= 3 and observed in valid
