"""Block-sparse attention layouts + evoformer attention
(reference ops/sparse_attention/, ops/deepspeed4science/evoformer_attn.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.evoformer import DS4Sci_EvoformerAttention, evoformer_attention
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                                                DenseSparsityConfig, FixedSparsityConfig,
                                                LocalSlidingWindowSparsityConfig,
                                                SparseSelfAttention, VariableSparsityConfig,
                                                layout_to_dense_mask, sparse_self_attention)


# ---------------------------------------------------------------------- layouts --
def test_fixed_layout_unidirectional():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                              num_global_blocks=1, attention="unidirectional")
    lay = cfg.make_layout(16 * 8)
    assert lay.shape == (2, 8, 8)
    assert np.array_equal(lay[0], lay[1])  # propagated single layout
    assert np.all(np.triu(lay[0], 1) == 0), "unidirectional must stay lower-triangular"
    # local window: block row 2 sees rows 0-2 of its window
    assert lay[0, 2, 0] and lay[0, 2, 2]
    # global: window representative (block 3) attended by later rows
    assert lay[0, 7, 3] == 1
    # outside window + not global → 0
    assert lay[0, 2, 1] == 1 and lay[0, 1, 0] == 1


def test_fixed_layout_bidirectional_horizontal_global():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              num_global_blocks=1, attention="bidirectional",
                              horizontal_global_attention=True)
    lay = cfg.make_layout(16 * 8)[0]
    assert lay[0, 3] == 1, "vertical global visible from every row"
    assert np.all(lay[3, :] == 1), "horizontal global row fully attends"


def test_fixed_layout_different_patterns_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                              num_global_blocks=1, attention="bidirectional",
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    lay = cfg.make_layout(16 * 8)
    # each head uses a different window representative → layouts differ
    assert not np.array_equal(lay[0], lay[1])


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=2, block=16, num_random_blocks=2,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    lay = cfg.make_layout(16 * 8)
    assert np.all(lay[0, 0, :] == 1) and np.all(lay[0, :, 0] == 1)  # global ITC
    for r in range(1, 7):  # sliding window
        assert lay[0, r, r - 1] and lay[0, r, r] and lay[0, r, r + 1]
    # randomness beyond window+global exists with 2 random blocks over 8
    assert lay.sum() >= 2 * (8 + 8 + 3 * 8 - 4)


def test_bigbird_unidirectional_is_causal():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1,
                                attention="unidirectional")
    lay = cfg.make_layout(16 * 8)[0]
    assert np.all(np.triu(lay, 1) == 0)


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16, num_sliding_window_blocks=3,
                                     global_block_indices=[0, 5])
    lay = cfg.make_layout(16 * 8)[0]
    assert np.all(lay[5, :] == 1) and np.all(lay[:, 5] == 1)
    assert lay[3, 1] == 0  # outside window, not global


def test_variable_and_local_window_layouts():
    lay = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                 local_window_blocks=[2, 4],
                                 global_block_indices=[0]).make_layout(16 * 8)[0]
    assert lay[1, 0] and lay[1, 1]  # first window of 2
    assert np.all(lay[:, 0] == 1)   # global column

    lay = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=3).make_layout(16 * 8)[0]
    assert np.all(np.triu(lay, 1) == 0)
    assert lay[4, 3] and lay[4, 4] and not lay[4, 1]


# -------------------------------------------------------------- sparse attention --
def test_dense_layout_matches_full_attention():
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32) for _ in range(3))
    lay = DenseSparsityConfig(num_heads=H, block=16).make_layout(S)
    out = sparse_self_attention(q, k, v, lay, block=16)
    scale = 1.0 / np.sqrt(D)
    ref = jax.nn.softmax(jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale) @ v
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_sparse_attention_honors_layout():
    """Tokens in unattended blocks must not influence the output."""
    rng = np.random.default_rng(1)
    B, H, S, D = 1, 1, 64, 8
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32) for _ in range(3))
    cfg = LocalSlidingWindowSparsityConfig(num_heads=H, block=16, num_sliding_window_blocks=1)
    lay = cfg.make_layout(S)
    out1 = sparse_self_attention(q, k, v, lay, block=16)
    # perturb keys/values in a block row 0 never attends (block 3)
    k2 = k.at[:, :, 48:, :].set(99.0)
    v2 = v.at[:, :, 48:, :].set(99.0)
    out2 = sparse_self_attention(q, k2, v2, lay, block=16)
    np.testing.assert_array_equal(np.asarray(out1[:, :, :16]), np.asarray(out2[:, :, :16]))


def test_sparse_self_attention_module_and_padding():
    rng = np.random.default_rng(2)
    B, H, S, D = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32) for _ in range(3))
    attn = SparseSelfAttention(FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2))
    out = attn(q, k, v)
    assert out.shape == (B, H, S, D)
    # padding mask drops keys
    kpm = np.ones((B, S), bool)
    kpm[:, 32:] = False
    out_pad = attn(q, k, v, key_padding_mask=jnp.asarray(kpm))
    assert np.all(np.isfinite(np.asarray(out_pad)))
    assert not np.allclose(np.asarray(out), np.asarray(out_pad))


# -------------------------------------------------------------------- evoformer --
def test_evoformer_matches_naive():
    rng = np.random.default_rng(3)
    B, N, S, H, D = 2, 3, 16, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32) for _ in range(3))
    bias1 = jnp.asarray(rng.normal(size=(B, N, 1, 1, S)), jnp.float32)
    bias2 = jnp.asarray(rng.normal(size=(B, 1, H, S, S)), jnp.float32)
    out = DS4Sci_EvoformerAttention(q, k, v, [bias1, bias2])
    assert out.shape == (B, N, S, H, D)

    # naive: head-first layout
    qh = np.swapaxes(np.asarray(q), -2, -3) / np.sqrt(D)
    kh = np.swapaxes(np.asarray(k), -2, -3)
    vh = np.swapaxes(np.asarray(v), -2, -3)
    scores = qh @ np.swapaxes(kh, -1, -2) + np.asarray(bias1) + np.asarray(bias2)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.swapaxes(probs @ vh, -2, -3)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_evoformer_gradients_flow():
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(1, 2, 8, 2, 4)), jnp.float32)
    k, v = q + 0.1, q + 0.2
    bias2 = jnp.zeros((1, 1, 2, 8, 8), jnp.float32)
    g = jax.grad(lambda b: jnp.sum(evoformer_attention(q, k, v, bias2=b)))(bias2)
    assert float(jnp.max(jnp.abs(g))) > 0


def test_evoformer_rejects_three_biases():
    q = jnp.zeros((1, 1, 4, 1, 4))
    with pytest.raises(ValueError):
        DS4Sci_EvoformerAttention(q, q, q, [q, q, q])
