"""Scheduling results.

Reference: ``deepspeed/inference/v2/scheduling_utils.py`` (SchedulingResult /
SchedulingError used by ``engine_v2.can_schedule``/``put``).
"""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class SchedulingError(RuntimeError):

    def __init__(self, result: SchedulingResult):
        self.status = result
        super().__init__(f"Batch scheduling failed: {result.name}")
