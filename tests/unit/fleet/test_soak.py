"""Slow mixed-role fleet soak (ISSUE satellite): concurrent routed traffic —
affinity keys, disaggregated handoffs, sampled and greedy, the occasional
cancel — then prove no KV block and no tracked sequence leaked anywhere."""

import threading

import numpy as np
import pytest

from deepspeed_tpu.fleet import FleetRouter, LocalReplica
from deepspeed_tpu.serving import ServingConfig


@pytest.mark.slow
def test_mixed_role_fleet_soak_no_kv_or_sequence_leak(make_fleet):
    fleet = make_fleet(roles=("prefill", "prefill", "decode", "decode", "mixed"),
                       serving_config=ServingConfig(decode_chunk=2),
                       num_blocks=96)
    router = FleetRouter(fleet)
    rng = np.random.default_rng(0)
    n_requests = 48
    outcomes = []
    lock = threading.Lock()

    def one(i):
        prompt = rng.integers(0, 64, int(rng.integers(4, 40))).tolist()
        doc = {"prompt": prompt, "max_new_tokens": int(rng.integers(2, 12)),
               "temperature": 0.7 if i % 3 == 0 else 0.0, "seed": i}
        try:
            routed = router.route(doc, session_key=f"user-{i % 7}" if i % 2 else None)
            if i % 11 == 0:
                # a client that goes away mid-stream: KV must still free
                it = routed.tokens()
                next(it, None)
                routed.cancel()
                for _ in it:
                    pass
                with lock:
                    outcomes.append(("cancelled-ok", i))
                return
            final = routed.result()
            with lock:
                outcomes.append((final["state"], i))
        except Exception as e:  # pragma: no cover - the assert below reports it
            with lock:
                outcomes.append((f"error: {type(e).__name__}: {e}", i))

    threads = [threading.Thread(target=one, args=(i, )) for i in range(n_requests)]
    for batch in range(0, n_requests, 8):   # 8 concurrent clients at a time
        group = threads[batch:batch + 8]
        for t in group:
            t.start()
        for t in group:
            t.join(timeout=300)
            assert not t.is_alive(), "soak request wedged"

    states = {s for s, _ in outcomes}
    bad = [o for o in outcomes if o[0] not in ("DONE", "CANCELLED", "cancelled-ok")]
    assert not bad, f"soak failures: {bad[:5]}"
    assert "DONE" in states
    assert len(outcomes) == n_requests

    # the leak check: every engine's pool is whole and nothing stays tracked
    # (handoff donors flushed, cancels flushed, resumes flushed at DONE)
    for replica in fleet.replicas():
        assert isinstance(replica, LocalReplica)
        engine = replica.engine
        assert engine._state_manager.n_tracked_sequences == 0, replica.id
        assert engine.free_blocks == 96, \
            f"{replica.id} leaked {96 - engine.free_blocks} KV blocks"
        assert not replica.scheduler._active and replica.scheduler.queue_depth == 0
