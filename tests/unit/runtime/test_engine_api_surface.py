"""Reference engine public-API surface (reference engine.py:600-1700 accessors;
user code probes these freely, so they must all resolve and return sane
values)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches

HIDDEN = 16


@pytest.fixture()
def engine():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config={"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "betas": [0.9, 0.999]}},
                "zero_optimization": {"stage": 2}})
    return eng


REFERENCE_SURFACE = [
    "fp16_enabled", "bfloat16_enabled", "amp_enabled", "amp_params",
    "dynamic_loss_scale", "initial_dynamic_scale", "postscale_gradients",
    "gradient_predivide_factor", "communication_data_type", "graph_harvesting",
    "optimizer_name", "optimizer_params", "scheduler_name", "scheduler_params",
    "steps_per_print", "dump_state", "memory_breakdown", "dataloader_drop_last",
    "sparse_gradients_enabled", "aio_config", "swap_tensor_config", "get_data_types",
    "use_node_local_storage", "load_universal_checkpoint", "elasticity_enabled",
    "eigenvalue_enabled", "eigenvalue_max_iter", "pld_enabled", "pld_theta",
    "pld_gamma", "curriculum_enabled_legacy", "curriculum_learning_enabled",
    "data_efficiency_enabled", "data_sampling_enabled", "random_ltd_enabled",
    "flops_profiler_enabled", "flops_profiler_profile_step", "autotuning_enabled",
    "autotuning_metric", "zero_allow_untested_optimizer", "zero_cpu_offload",
    "zero_has_nvme_offload", "zero_optimization_partition_gradients",
    "zero_optimization_partition_weights", "zero_contiguous_gradients",
    "zero_reduce_scatter", "zero_overlap_comm", "zero_reduce_bucket_size",
    "zero_allgather_partitions", "zero_allgather_bucket_size", "zero_sub_group_size",
    "zero_prefetch_bucket_size", "zero_param_persistence_threshold",
    "zero_max_live_parameters", "zero_max_reuse_distance",
    "zero_gather_16bit_weights_on_model_save", "zero_ignore_unused_parameters",
    "zero_legacy_stage1", "zero_load_from_fp32_weights", "zero_elastic_checkpoint",
    "zero_round_robin_gradients", "zero_hpz_partition_size", "mics_shard_size",
    "zero_quantized_weights", "zero_quantized_gradients", "get_mom", "get_type",
    "get_pld_theta", "get_batch_info", "is_first_weights_partition_group",
]


def test_accessor_surface_resolves(engine):
    for name in REFERENCE_SURFACE:
        fn = getattr(engine, name)
        fn()  # must not raise


def test_accessor_values(engine):
    assert engine.fp16_enabled() is False
    assert engine.optimizer_name() == "adamw"
    assert engine.get_type() == "FusedAdam"
    assert engine.get_mom() == [0.9]
    assert engine.get_batch_info() == (32, 2, 2)  # micro 2 x gas 2 x dp 8
    assert engine.zero_optimization_partition_gradients()
    assert not engine.zero_optimization_partition_weights()
    assert not engine.zero_has_nvme_offload()
    assert engine.zero_hpz_partition_size() == 1


def test_module_state_dict_roundtrip(engine):
    import jax
    sd = engine.module_state_dict()
    zeroed = jax.tree.map(np.zeros_like, sd)
    engine.load_module_state_dict(zeroed)
    assert all(np.all(np.asarray(l) == 0) for l in jax.tree.leaves(engine.params))
    engine.load_module_state_dict(sd)
    for a, b in zip(jax.tree.leaves(jax.device_get(engine.params)), jax.tree.leaves(sd)):
        np.testing.assert_array_equal(a, b)


def test_zero_grad_and_step_applied(engine):
    b = random_batches(1, 16, HIDDEN)[0]
    loss = engine.forward(b)
    engine.backward(loss)
    assert engine.acc_grads is not None
    engine.zero_grad()
    assert engine.acc_grads is None
    assert engine.was_step_applied() is False  # no step yet

    loss = engine.forward(b)
    engine.backward(loss)
    engine.step()
    engine.forward(b)  # micro step 2 of 2
    engine.backward(loss)
    engine.step()
    assert engine.was_step_applied() is True


def test_gas_boundary_override(engine):
    engine.set_gradient_accumulation_boundary(True)
    assert engine.is_gradient_accumulation_boundary()
    engine.set_gradient_accumulation_boundary(False)
    assert not engine.is_gradient_accumulation_boundary()


def test_destroy(engine):
    engine.destroy()
    assert engine.acc_grads is None and not engine._compiled
