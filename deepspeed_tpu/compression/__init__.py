from deepspeed_tpu.compression.compress import (get_compression_config, init_compression,
                                                redundancy_clean, student_initialization)
from deepspeed_tpu.compression.basic_layer import fake_quantize, head_prune_mask, row_prune_mask
from deepspeed_tpu.compression.scheduler import CompressionScheduler
