"""ZeRO-Inference weight quantization (reference README.md:17 news item;
deepspeed/inference/quantization role)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.quantization import (dequantize_tree, is_quantized_leaf,
                                                     quantize_tree, tree_nbytes)
from deepspeed_tpu.utils import groups


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(128, 64)), jnp.float32)
    tree = {"layer": {"kernel": w, "bias": jnp.ones((64, ))}}
    q = quantize_tree(tree, min_size=1024)
    assert is_quantized_leaf(q["layer"]["kernel"])
    assert q["layer"]["kernel"]["__wq_int8__"].dtype == jnp.int8
    assert not is_quantized_leaf(q["layer"]["bias"])  # small leaves stay fp

    back = dequantize_tree(q)
    assert back["layer"]["kernel"].dtype == jnp.float32
    # symmetric per-channel int8: max error <= scale/2 = max|col|/254
    err = np.abs(np.asarray(back["layer"]["kernel"]) - np.asarray(w))
    bound = np.abs(np.asarray(w)).max(axis=0) / 254.0 + 1e-7
    assert (err <= bound[None, :] + 1e-6).all()


def test_quantize_memory_halves():
    rng = np.random.default_rng(1)
    tree = {"k": jnp.asarray(rng.normal(size=(256, 256)), jnp.bfloat16)}
    q = quantize_tree(tree, min_size=0)
    # bf16 (2B) -> int8 (1B) + small scale row
    assert tree_nbytes(q) < 0.6 * tree_nbytes(tree)
    back = dequantize_tree(q)
    assert back["k"].dtype == jnp.bfloat16


def test_bits_guard():
    with pytest.raises(NotImplementedError):
        quantize_tree({"k": jnp.ones((64, 64))}, bits=4)


def test_engine_quantized_logits_close():
    """A quantized llama v2 engine must store int8 weights and produce logits
    close to the full-precision engine (prefill + decode)."""
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, init_params

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4, max_position_embeddings=64)
    _, params = init_params(cfg, seq_len=8)

    def mgr():
        return DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                               size=64),
                                    max_context=64, max_ragged_batch_size=64,
                                    max_ragged_sequence_count=4)

    prompt = np.arange(10) % 128
    fp = build_engine(params, cfg, RaggedInferenceEngineConfig(state_manager=mgr()))
    ref_logits = np.asarray(fp.put([0], [prompt]))

    q = build_engine(params, cfg,
                     RaggedInferenceEngineConfig(state_manager=mgr(),
                                                 weight_quantization={"enabled": True,
                                                                      "min_size": 1024}))
    import jax as _jax
    int8_leaves = [l for l in _jax.tree.leaves(q._model._params) if l.dtype == jnp.int8]
    assert int8_leaves, "engine must hold int8 weights at rest"
    q_logits = np.asarray(q.put([0], [prompt]))

    assert q_logits.shape == ref_logits.shape
    # int8 per-channel quantization: logits agree to first-order
    assert np.mean(np.abs(q_logits - ref_logits)) < 0.05 * np.mean(np.abs(ref_logits)) + 0.05
    # randomly initialized weights give near-uniform logits, so exact argmax
    # can flip on ties — the robust claim is top-k containment
    top5 = np.argsort(ref_logits[-1])[-5:]
    assert np.argmax(q_logits[-1]) in top5


def test_quantization_rejects_tp():
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.models.llama import LlamaConfig, init_params

    groups.initialize_mesh(model_parallel_size=2, force=True)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=1, num_attention_heads=4,
                           num_key_value_heads=4)
    _, params = init_params(cfg, seq_len=8)
    with pytest.raises(NotImplementedError, match="AutoTP"):
        build_engine(params, cfg,
                     RaggedInferenceEngineConfig(tp={"tp_size": 2},
                                                 weight_quantization={"enabled": True}))
