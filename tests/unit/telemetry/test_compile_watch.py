"""Compile watch: jit-cache miss attribution, bucket-switch accounting, and
the ISSUE acceptance — decode across a pow2 bucket boundary recompiles
exactly once (and never within a bucket)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
from deepspeed_tpu.telemetry import compile_watch


def _misses(site):
    snap = telemetry.get_registry().snapshot()
    for labels, value in snap.get("compile_cache_misses_total", []):
        if labels.get("site") == site:
            return value
    return 0.0


def test_wrapped_site_attribution_and_seconds():
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    cw = compile_watch.get()
    assert cw is not None
    fn = cw.wrap("site_a", ("k", 1), jax.jit(lambda x: x * 2 + 1))
    fn(jnp.ones(3))
    assert _misses("site_a") == 1.0
    fn(jnp.ones(3))  # cached: no new compile
    assert _misses("site_a") == 1.0
    fn(jnp.ones(7))  # jax-internal shape recompile still attributes here
    assert _misses("site_a") == 2.0

    snap = telemetry.get_registry().snapshot()
    secs = {tuple(sorted(labels.items())): v
            for labels, v in snap["compile_seconds_total"]}
    assert secs[(("site", "site_a"),)] > 0
    entries = {labels["site"]: v for labels, v in snap["compile_cache_entries"]}
    assert entries["site_a"] == 1.0
    # compiles show up inline in the trace with the triggering key
    compile_spans = [s for s in telemetry.state.spans.tail(1000)
                     if s["name"] == "xla_compile"
                     and s.get("args", {}).get("site") == "site_a"]
    assert len(compile_spans) == 2
    assert compile_spans[0]["args"]["key"] == repr(("k", 1))
    assert all(s["dur_us"] > 0 for s in compile_spans)


def test_unattributed_compiles_land_in_other_site():
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    jax.jit(lambda x: x - 3)(jnp.ones(5))
    assert _misses("other") >= 1.0


def test_disabled_watch_is_inert():
    assert compile_watch.get() is None
    jax.jit(lambda x: x + 10)(jnp.ones(2))  # listener forwards nothing
    assert telemetry.get_registry().api_calls == 0


def test_compile_watch_optout():
    telemetry.configure(telemetry.TelemetryConfig(enabled=True, compile_watch=False))
    assert compile_watch.get() is None


def test_bucket_switch_counter():
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    cw = compile_watch.get()
    cw.note_bucket((8, 8, 4))       # first batch: the baseline, not a switch
    cw.note_bucket((8, 8, 4))       # same bucket: no switch
    cw.note_bucket((64, 8, 4))      # novel bucket: switch
    cw.note_bucket((8, 8, 4))       # steady alternation between live buckets
    cw.note_bucket((64, 8, 4))      # ... is not churn (both recently seen)
    cw.note_bucket((64, 16, 4))     # novel again: switch
    snap = telemetry.get_registry().snapshot()
    assert snap["compile_bucket_switches_total"] == [({}, 2.0)]


def test_bucket_window_eviction_recounts_cold_bucket():
    """A bucket evicted from the recently-seen window counts again on
    re-entry — mirroring that its compiled program has likely gone cold."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    cw = compile_watch.get()
    cw.note_bucket((1, 1, 1))
    for i in range(2, 2 + cw._RECENT_BUCKET_WINDOW):  # flush (1,1,1) out
        cw.note_bucket((i, 1, 1))
    cw.note_bucket((1, 1, 1))                         # cold again: a switch
    snap = telemetry.get_registry().snapshot()
    assert snap["compile_bucket_switches_total"] == [({}, float(cw._RECENT_BUCKET_WINDOW + 1))]


# ------------------------------------------------------------- acceptance --
@pytest.fixture(scope="module")
def llama_setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = {"model": model.init(jax.random.PRNGKey(0), ids)["params"]}
    return cfg, params


def test_decode_across_pow2_bucket_boundary_recompiles_exactly_once(llama_setup):
    """ISSUE acceptance: host-loop decode within one pad bucket never
    recompiles; crossing the pow2 block-table boundary recompiles exactly
    once. block_size=16, so blocks pass the MB=4 pow2 bucket at 64 seen
    tokens: prompt 60t (4 blocks) leaves the boundary a few decode steps
    away."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    cfg, params = llama_setup
    mgr = DSStateManagerConfig(
        memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=64),
        max_context=512)
    engine = build_engine(params, cfg,
                          RaggedInferenceEngineConfig(state_manager=mgr,
                                                      kv_block_size=16))
    try:
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size, 60)
        logits = engine.put([0], [prompt])            # prefill bucket compile
        tok = int(np.argmax(logits[0]))
        logits = engine.put([0], [tok])               # decode bucket compile
        base = _misses("inference_forward")
        assert base >= 2.0

        # within the bucket: seen goes 61 -> 63, blocks stay at 4 (MB=4)
        for _ in range(2):
            tok = int(np.argmax(logits[0]))
            logits = engine.put([0], [tok])
        assert _misses("inference_forward") == base  # zero within a bucket

        # seen crosses 64: a 5th block is allocated, MB pow2-pads 4 -> 8,
        # a new decode bucket compiles — exactly once
        for _ in range(3):
            tok = int(np.argmax(logits[0]))
            logits = engine.put([0], [tok])
        assert _misses("inference_forward") == base + 1.0
        # and the bucket churn was observed by the ragged-wrapper hook
        snap = telemetry.get_registry().snapshot()
        assert snap["compile_bucket_switches_total"][0][1] >= 2.0
    finally:
        engine.close()
