"""Cross-replica tracing (ISSUE satellite): one routed request — prefill on
one replica, decode on another — renders as a SINGLE parented trace:
route → dispatch:prefill → replica request, dispatch:decode → replica request."""

import json
import urllib.request

import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet import FleetRouter
from deepspeed_tpu.serving.server import TRACE_HEADER


def _events(trace_id):
    evs = telemetry.state.spans.chrome_trace()["traceEvents"]
    return [e for e in evs if e.get("args", {}).get("trace_id") == trace_id
            and e.get("ph") == "X"]


def test_disaggregated_request_is_one_parented_trace(make_fleet):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    fleet = make_fleet(roles=("prefill", "decode"))
    router = FleetRouter(fleet).start()
    try:
        prompt = (np.arange(15) % 64).tolist()
        body = json.dumps({"prompt": prompt, "max_new_tokens": 5}).encode()
        req = urllib.request.Request(router.url + "/v1/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            doc = json.loads(resp.read())
            trace_id = resp.headers[TRACE_HEADER]
    finally:
        router.stop(drain=False)

    assert doc["state"] == "DONE" and doc["trace_id"] == trace_id
    evs = _events(trace_id)
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)

    # the router's root covers the whole request
    (route, ) = by_name["route"]
    assert route["args"]["disaggregated"] is True
    assert len(route["args"]["legs"]) == 2

    # one dispatch hop per leg, parented under the route span
    (hop_prefill, ) = by_name["dispatch:prefill"]
    (hop_decode, ) = by_name["dispatch:decode"]
    for hop in (hop_prefill, hop_decode):
        assert hop["args"]["parent_id"] == route["args"]["span_id"]
    assert hop_prefill["args"]["role"] == "prefill"
    assert hop_decode["args"]["role"] == "decode"

    # each replica's request root parents under ITS dispatch hop — the
    # Perfetto track reads router -> prefill replica -> decode replica
    requests = by_name["request"]
    assert len(requests) == 2
    parents = {r["args"]["parent_id"] for r in requests}
    assert parents == {hop_prefill["args"]["span_id"],
                       hop_decode["args"]["span_id"]}
    resumed = {r["args"]["resumed"] for r in requests}
    assert resumed == {True, False}

    # every lifecycle span of both replica legs shares the one trace id
    names = {e["name"] for e in evs}
    assert {"queued", "prefill", "decode"} <= names
