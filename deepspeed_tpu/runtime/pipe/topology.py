"""Cartesian process topology (pure rank math).

Reference: ``deepspeed/runtime/pipe/topology.py`` (ProcessTopology:12,
PipeDataParallelTopology, PipelineParallelGrid:251). This is pure logic in the
reference too — it ports as semantics, and doubles as the mapping between
(pipe, data, model) coordinates and positions in our global mesh.
"""

from collections import namedtuple
from itertools import product


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates <-> linear ranks; axes ordered
    outermost-first (reference topology.py:12)."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() requires all axes: {self.axes}")
        key = self.ProcessCoord(**coord_kwargs)
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that vary only along ``axis`` (the reference's group
        construction primitive)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in product(*ranges):
            fixed = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coordinates match all filters."""

        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return [self.mapping[c] for c in sorted(self.mapping.keys(), key=lambda c: self.mapping[c]) if matches(c)]

    def get_slice(self, **filter_kwargs):
        return self.filter_match(**filter_kwargs)

    def world_size(self):
        size = 1
        for d in self.dims:
            size *= d
        return size

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """Reference: axes=['pipe','data'] — adjacent pipe stages map to adjacent
    ranks (intra-node P2P), data-parallel groups span nodes."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """Reference: axes=['pipe','data','model'] for 3D parallelism."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Reference topology.py:251 — axis-world-size/rank queries over a topology.
    On TPU the 'process groups' are mesh axes; this object answers the same
    queries for code written against the reference API."""

    def __init__(self, topology=None, process_group=None):
        import jax
        self.global_rank = jax.process_index() if jax.process_count() > 1 else 0
        if topology is None:
            world = max(1, len(jax.devices()))
            topology = PipeDataParallelTopology(1, world)
        self._topo = topology
        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.slice_parallel_size = self.model_parallel_size
        self.world_size = topology.world_size()

    def get_stage_id(self, rank=None):
        rank = self.global_rank if rank is None else rank
        return getattr(self._topo.get_coord(rank), "pipe", 0)

    def get_data_parallel_id(self, rank=None):
        rank = self.global_rank if rank is None else rank
        return getattr(self._topo.get_coord(rank), "data", 0)

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_data_parallel_rank(self):
        return self.get_data_parallel_id()

    def get_model_parallel_rank(self):
        return getattr(self._topo.get_coord(self.global_rank), "model", 0)

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_global_rank(self):
        return self.global_rank

    def stage_to_global(self, stage_id, **kwargs):
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def topology(self):
        return self._topo

    # first/last stage queries (reference engine uses these constantly)
    @property
    def is_first_stage(self):
        return self.get_stage_id() == 0

    @property
    def is_last_stage(self):
        return self.get_stage_id() == self.pipe_parallel_size - 1
