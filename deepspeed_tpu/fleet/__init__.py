"""Fleet serving: N load-balanced replicas behind one router.

The serving layer (``deepspeed_tpu/serving``) operates ONE engine; this
package is the horizontal layer above it — the PAPER §2.9 disaggregation idea
promoted from MoE experts to whole engine roles:

- :class:`ReplicaManager` — registry + lifecycle for the replica set:
  in-process ``(InferenceEngineV2 + ServingScheduler)`` pairs (tier-1
  CPU-testable) and/or external ``serving/server.py`` processes by URL.
- :class:`FleetRouter` — stdlib-HTTP front-end speaking the single-replica
  wire format: session-affinity rendezvous hashing, health/backpressure-aware
  least-loaded dispatch, retry-on-503 failover, fleet-wide graceful drain.
- Prefill/decode disaggregation: replicas are role-tagged; when both pools
  exist a request prefills (plus first token) on a ``prefill`` replica and its
  KV hands off — a portable bytes payload (``inference/v2/ragged/handoff.py``)
  — to a ``decode`` replica, so TTFT and ITL capacity scale independently.
  ``empty_run`` heartbeats keep idle pool members warm.
- :class:`FleetAutoscaler` — sustained queue-depth / KV-pressure policy loop
  that grows and drains pools through the manager, reusing the elasticity
  subsystem's valid-size / capacity signals.
- Fault tolerance: :class:`ReplicaSupervisor` (``fleet/supervisor.py``) owns
  replica lifecycle — spawn (``bin/dstpu_replica`` processes or in-process
  replicas), ``/healthz``-gated registration, exit/hang detection, backoff
  restarts, crash-loop quarantine; every replica carries a
  :class:`CircuitBreaker` (``fleet/breaker.py``) fed by probes and dispatch
  outcomes; :class:`FaultInjector` (``fleet/faults.py``) drives every
  recovery path deterministically from a seed.

Usage::

    from deepspeed_tpu.fleet import FleetConfig, FleetRouter, ReplicaManager

    manager = ReplicaManager(engine_factory=make_engine, config=FleetConfig())
    for _ in range(2):
        manager.add_local(role="prefill")
        manager.add_local(role="decode")
    router = FleetRouter(manager).start()   # same wire format as ServingServer
    ...                                     # POST router.url + "/v1/generate"
    router.stop()                           # graceful fleet-wide drain
"""

from deepspeed_tpu.fleet.breaker import (BreakerConfig, BreakerState,
                                         CircuitBreaker, backoff_delay)
from deepspeed_tpu.fleet.config import (AutoscaleConfig, FleetConfig,
                                        GlobalQueueConfig, HedgeConfig,
                                        ParkConfig, ReplicaRole,
                                        SupervisorConfig)
from deepspeed_tpu.fleet.faults import FaultConfig, FaultInjector
from deepspeed_tpu.fleet.global_queue import (GlobalQueue, GlobalQueueFull,
                                              QueueWaitExpired)
from deepspeed_tpu.fleet.manager import ReplicaManager
from deepspeed_tpu.fleet.park_store import ParkedSession, ParkStore
from deepspeed_tpu.fleet.metrics import FleetMetrics
from deepspeed_tpu.fleet.policy import FleetAutoscaler
from deepspeed_tpu.fleet.replica import (HttpReplica, Leg, LocalReplica, Replica,
                                         ReplicaDied, ReplicaState,
                                         ReplicaUnavailable)
from deepspeed_tpu.fleet.router import FleetRouter, RoutedRequest, RoutingError
from deepspeed_tpu.fleet.supervisor import ReplicaSlot, ReplicaSupervisor, SlotState

__all__ = [
    "AutoscaleConfig", "BreakerConfig", "BreakerState", "CircuitBreaker",
    "FaultConfig", "FaultInjector", "FleetConfig", "GlobalQueue",
    "GlobalQueueConfig", "GlobalQueueFull", "HedgeConfig", "ParkConfig",
    "ParkStore", "ParkedSession", "QueueWaitExpired", "ReplicaRole",
    "SupervisorConfig", "ReplicaManager", "FleetMetrics", "FleetAutoscaler",
    "HttpReplica", "Leg", "LocalReplica", "Replica", "ReplicaDied",
    "ReplicaState", "ReplicaUnavailable", "FleetRouter", "RoutedRequest",
    "RoutingError", "ReplicaSlot", "ReplicaSupervisor", "SlotState",
    "backoff_delay",
]
