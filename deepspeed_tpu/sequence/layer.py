"""Ulysses sequence parallelism.

Reference: ``deepspeed/sequence/layer.py`` (DistributedAttention:60, _SeqAllToAll:44,
single_all_to_all:15): sequence-sharded activations are all-to-all'd so each rank
holds *all* sequence positions for a *subset of heads*, local attention runs over the
full sequence, and the output is all-to-all'd back.

TPU-native formulation: the two all-to-alls are sharding-constraint flips over the
``seq`` mesh axis — [B, S@seq, H, D] → [B, S, H@seq, D] → attention →
[B, S, H@seq, D] → [B, S@seq, H, D]. GSPMD lowers each flip to exactly one
all-to-all on ICI (the optimal Ulysses communication pattern, SURVEY.md §5.7).
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils import groups


def _constrain(t, spec_axes, mesh=None):
    """Apply a per-dim PartitionSpec (tuple of axis-name-or-None); no-op when the
    named axes are absent or degenerate. Shared by Ulysses and MoE dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        mesh = mesh if mesh is not None else groups.get_mesh()
    except Exception:
        return t
    used = [a for a in spec_axes if a is not None]
    if not used or all(mesh.shape.get(a, 1) <= 1 for a in used):
        return t
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, P(*spec_axes)))


def seq_to_head_sharding(x, seq_axis_name=groups.SEQ_AXIS, seq_dim=1, head_dim=2):
    """single_all_to_all (reference layer.py:15), scatter heads / gather sequence."""
    spec = [None] * x.ndim
    spec[head_dim] = seq_axis_name
    return _constrain(x, spec)


def head_to_seq_sharding(x, seq_axis_name=groups.SEQ_AXIS, seq_dim=1, head_dim=2):
    spec = [None] * x.ndim
    spec[seq_dim] = seq_axis_name
    return _constrain(x, spec)


class DistributedAttention:
    """Reference DistributedAttention:60.

    Args mirror the reference: ``local_attention`` is any callable
    ``(q, k, v, *args, **kwargs) -> out`` operating on [B, S, H, D] tensors;
    ``scatter_idx``/``gather_idx`` pick which dims flip sharding (defaults: heads=2
    scattered, seq=1 gathered).
    """

    def __init__(self, local_attention: Callable, sequence_process_group=None, scatter_idx: int = 2,
                 gather_idx: int = 1):
        self.local_attn = local_attention
        self.seq_axis = sequence_process_group or groups.SEQ_AXIS
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        # in: [B, S(sharded over seq axis), H, D]
        q = seq_to_head_sharding(query, self.seq_axis, self.gather_idx, self.scatter_idx)
        k = seq_to_head_sharding(key, self.seq_axis, self.gather_idx, self.scatter_idx)
        v = seq_to_head_sharding(value, self.seq_axis, self.gather_idx, self.scatter_idx)
        # local attention sees full sequence, heads partitioned
        out = self.local_attn(q, k, v, *args, **kwargs)
        # out: back to sequence sharding
        return head_to_seq_sharding(out, self.seq_axis, self.gather_idx, self.scatter_idx)
