"""Blocked (flash) causal attention.

TPU-native replacement for the reference's attention kernels: the inference-v2
``blocked_flash`` binding (``deepspeed/inference/v2/kernels/ragged_ops/blocked_flash``)
and the training softmax/attention CUDA kernels (``csrc/transformer/softmax_kernels.cu``).

Design:
- Forward: a Pallas kernel, grid over (batch*heads, q_blocks); each program streams
  KV blocks through VMEM with an online-softmax accumulator (flash-attention-2
  schedule). Causal masking skips fully-masked KV blocks.
- Backward: custom VJP that recomputes attention blockwise in pure JAX
  (lax.scan over KV blocks) — O(S) memory like the forward, fused by XLA. A Pallas
  backward kernel is a later optimization; this keeps training memory-correct now.
- CPU (tests): interpret mode.

Layout: q, k, v are [B, S, H, D] (kv may have fewer heads — GQA is expanded by the
caller or here via repeat).
"""

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_cpu():
    return jax.default_backend() == "cpu"


def _fit_block(seq_len, cap):
    """Largest divisor of seq_len that is <= cap (block shapes must tile S)."""
    b = min(cap, seq_len)
    while seq_len % b:
        b -= 1
    return b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, causal, block_q,
                block_k, nkb):
    """Flash-attention-2 schedule: grid (bh, q_blocks, kv_blocks); the kv dim is the
    innermost (sequential) grid axis so Pallas double-buffers the K/V block fetches
    while the scratch accumulators carry the online softmax across iterations."""
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: block fully above the diagonal contributes nothing
    run = (kb * block_k <= q_idx * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[...].astype(jnp.float32)  # [bq, d]
        k_blk = k_ref[...].astype(jnp.float32)  # [bk, d]
        v_blk = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[...][:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_scr[...] = l_scr[...] * alpha + jnp.broadcast_to(jnp.sum(p, axis=1, keepdims=True), l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v_blk, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kb == nkb - 1)
    def _finish():
        l = l_scr[...][:, :1]
        o_ref[...] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q=512, block_k=1024):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    block_q = _fit_block(S, block_q)
    block_k = _fit_block(S, block_k)
    nkb = S // block_k

    qr = q.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
                               block_k=block_k, nkb=nkb)
    on_cpu = _on_cpu()
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # m (lane-broadcast)
        pltpu.VMEM((block_q, 128), jnp.float32),  # l (lane-broadcast)
        pltpu.VMEM((block_q, D), jnp.float32),  # acc
    ]
    kwargs = {}
    if not on_cpu:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q, nkb),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=scratch,
        interpret=on_cpu,
        **kwargs,
    )(qr, kr, vr)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _blockwise_attention_ref(q, k, v, scale, causal, block_k=256):
    """Memory-efficient pure-JAX attention (scan over KV blocks) — used for the
    VJP recompute and as numerical reference."""
    B, S, H, D = q.shape
    block_k = _fit_block(S, block_k)
    nkb = S // block_k
    q32 = q.astype(jnp.float32)
    q_pos = jnp.arange(S)

    def body(carry, kb):
        m, l, acc = carry
        start = kb * block_k
        k_blk = jax.lax.dynamic_slice_in_dim(k, start, block_k, axis=1).astype(jnp.float32)
        v_blk = jax.lax.dynamic_slice_in_dim(v, start, block_k, axis=1).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bqhk", q32, k_blk) * scale
        if causal:
            k_pos = start + jnp.arange(block_k)
            s = jnp.where(q_pos[None, :, None, None] >= k_pos[None, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, S, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    a0 = jnp.zeros((B, S, H, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), jnp.arange(nkb))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _expand_gqa(q, k, v):
    H, KVH = q.shape[2], k.shape[2]
    if KVH != H:
        rep = H // KVH
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale=1.0, causal=True):
    k, v = _expand_gqa(q, k, v)
    return _flash_fwd_pallas(q, k, v, scale, causal)


def _flash_bwd_manual(q, k, v, out, g, scale, causal, block_k=256):
    """Hand-written flash-attention-2 backward (no autodiff): recompute the
    softmax statistics blockwise, then a second blockwise pass produces
    dq/dk/dv. Differentiating the scan instead (the previous implementation)
    made XLA stack per-block residuals — O(S^2/block) memory, OOM at 4k+.
    All inputs [B, S, H, D] (GQA pre-expanded)."""
    B, S, H, D = q.shape
    bk = _fit_block(S, block_k)
    nkb = S // bk
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    q_pos = jnp.arange(S)

    def logits_block(j):
        k_blk = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, k_blk) * scale
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            s = jnp.where(q_pos[None, :, None, None] >= k_pos[None, None, None, :], s, NEG_INF)
        return s, k_blk

    # pass 1: log-sum-exp per query row (running max/sum; no stacked residuals)
    def lse_body(carry, j):
        m, l = carry
        s, _ = logits_block(j)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new[..., None]), axis=-1)
        return (m_new, l), None

    m0 = jnp.full((B, S, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    (m, l), _ = jax.lax.scan(lse_body, (m0, l0), jnp.arange(nkb))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [B, S, H]

    # pass 2: per-block p recomputed and discarded
    def bwd_body(dq, j):
        s, k_blk = logits_block(j)
        v_blk = jax.lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        p = jnp.exp(s - lse[..., None])  # masked entries: exp(NEG_INF - lse) = 0
        dv_j = jnp.einsum("bqhk,bqhd->bkhd", p, gf)
        dp = jnp.einsum("bqhd,bkhd->bqhk", gf, v_blk)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bqhk,bkhd->bqhd", ds, k_blk) * scale
        dk_j = jnp.einsum("bqhk,bqhd->bkhd", ds, qf) * scale
        return dq, (dk_j, dv_j)

    dq, (dk_s, dv_s) = jax.lax.scan(bwd_body, jnp.zeros_like(qf), jnp.arange(nkb))
    dk = jnp.moveaxis(dk_s, 0, 1).reshape(B, S, H, D)
    dv = jnp.moveaxis(dv_s, 0, 1).reshape(B, S, H, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fa_fwd(q, k, v, scale, causal):
    out = flash_attention(q, k, v, scale, causal)
    # `out` is a live activation either way — saving it adds no memory (XLA
    # aliases), and it gives the backward delta = rowsum(dO * O) for free
    return out, (q, k, v, out)


def _fa_bwd(scale, causal, res, g):
    q, k, v, out = res
    kvh = k.shape[2]
    ke, ve = _expand_gqa(q, k, v)
    dq, dke, dve = _flash_bwd_manual(q, ke, ve, out, g, scale, causal)
    if kvh != q.shape[2]:  # fold expanded GQA grads back onto kv heads
        rep = q.shape[2] // kvh
        B, S, _, D = dke.shape
        dk = dke.reshape(B, S, kvh, rep, D).sum(axis=3)
        dv = dve.reshape(B, S, kvh, rep, D).sum(axis=3)
    else:
        dk, dv = dke, dve
    return dq, dk, dv


flash_attention.defvjp(_fa_fwd, _fa_bwd)
