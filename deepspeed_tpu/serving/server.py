"""Stdlib HTTP front-end for the serving scheduler.

In the style of ``telemetry/exporter.py`` (daemon ``ThreadingHTTPServer``,
ephemeral-port support), serving the request lifecycle instead of metrics:

- ``POST /v1/generate`` — JSON body::

      {"prompt": [1, 2, 3],            // token ids (required, non-empty)
       "max_new_tokens": 64,           // optional, server default otherwise
       "temperature": 0.0,             // optional
       "eos_token_id": 2,              // optional
       "deadline_s": 2.0,              // optional per-request deadline
       "seed": 0,                      // optional sampling seed
       "stream": true}                 // optional: SSE token streaming

  Non-streaming responses are one JSON object
  ``{"tokens": [...], "state": "DONE", "finish_reason": "length", ...}``.
  Streaming responses are Server-Sent Events (``text/event-stream``): one
  ``data: {"token": N, "index": I}`` event per generated token as it is
  sampled (TTFT is real), then a final ``data: {"done": true, "state": ...,
  "tokens": [...]}`` event. A dropped connection cancels the request (its KV
  blocks return to the pool on the next scheduler tick).

  Backpressure: queue-full in ``reject`` mode returns **429**; ``block`` mode
  stalls the handler thread until the queue drains. During shutdown new
  requests get **503**.

- ``POST /v1/resume`` — fleet decode-role continuation: the body carries a
  base64 ``payload`` (a peer engine's ``export_sequence`` product) instead of
  a prompt; the sequence enters DECODE directly and streams/returns exactly
  like ``/v1/generate``. A resume body carrying BOTH a payload and a
  ``prompt`` is the *rehydrate* form: the payload is a parked v2 frame whose
  token history the prompt strictly extends — the parked turns' KV imports
  and only the new suffix prefills. Both POST routes accept ``handoff`` and
  ``park`` flags (export this request's state at DONE; the base64 payload is
  returned in the final JSON / SSE ``done`` event as ``handoff`` / ``park``)
  and adopt an upstream trace from the
  ``X-DSTPU-Trace-Id`` / ``X-DSTPU-Parent-Span`` request headers, so the
  fleet router's hop parents the replica's request track.
- ``GET /v1/stats`` — scheduler + engine occupancy JSON: per-request rows
  (uid, state, tenant, cost-to-date, age, trace id), p50/p95/p99
  TTFT/ITL/e2e, the ``usage`` rollup and the predicted-vs-observed ``perf``
  join when telemetry is active.
- ``GET /v1/usage`` — the cost-attribution document: ledger totals, the
  per-tenant rollup, pricing, and the fair-share posture
  (``{"enabled": false}`` with telemetry off). Requests carry a tenant
  identity via the JSON ``tenant`` field or the ``X-DSTPU-Tenant`` header;
  unlabeled traffic bills to the configured default tenant.
- ``GET /healthz`` — liveness (same contract as the telemetry exporter).

With a telemetry session active every request is traced end-to-end: the
``X-DSTPU-Trace-Id`` response header (both response modes) and the ``uid``/
``trace_id`` fields of the final JSON / SSE ``done`` event let a client join
its request against the exported Chrome trace / flight-recorder dump.

``stop()`` drains gracefully: admission stops (503), in-flight requests run to
completion bounded by ``config.drain_timeout_s``, stragglers are CANCELLED,
then the listener shuts down.
"""

import base64
import itertools
import json
import math
import os
import queue
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.ragged.handoff import \
    CONTENT_TYPE as HANDOFF_CONTENT_TYPE
from deepspeed_tpu.serving.config import (DEFAULT_MAX_RESUME_BODY_BYTES,
                                          ServingConfig)
from deepspeed_tpu.serving.overload import validate_priority, validate_tenant
from deepspeed_tpu.serving.request import Request
from deepspeed_tpu.serving.scheduler import (AdmissionRejected, QueueFullError,
                                             SchedulerStopped, ServingScheduler)
from deepspeed_tpu.utils.logging import logger

_MAX_BODY_BYTES = 8 << 20  # an 8 MiB prompt is already ~2M tokens of JSON
# a resume body carries a base64 KV-handoff payload — real-model KV runs to
# hundreds of MB (the fleet handoff histogram buckets reach 1 GiB) and base64
# adds 4/3, so the prompt cap would 400 every non-toy handoff
_MAX_RESUME_BODY_BYTES = DEFAULT_MAX_RESUME_BODY_BYTES


TRACE_HEADER = "X-DSTPU-Trace-Id"
# the fleet router's span id: a replica's request root parents under it so
# router → prefill replica → decode replica renders as ONE Perfetto track
PARENT_SPAN_HEADER = "X-DSTPU-Parent-Span"
# priority class (interactive | batch) — header form; the JSON body's
# "priority" field wins when both are present
PRIORITY_HEADER = "X-DSTPU-Priority"
# cost-attribution tenant identity — header form; the JSON body's "tenant"
# field wins when both are present (same precedence as priority)
TENANT_HEADER = "X-DSTPU-Tenant"
# fleet data motion: the request's steal handle (sent up-front on SSE
# responses so the router can address a live request), the generation params
# riding a binary-transport resume POST, the client's handoff-return
# negotiation ("ref" = stash the frame server-side, return a claim-once
# handoff_ref instead of base64-in-JSON), and the already-streamed token
# count on an exported-steal response
HANDLE_HEADER = "X-DSTPU-Request-Handle"
PARAMS_HEADER = "X-DSTPU-Params"
HANDOFF_TRANSPORT_HEADER = "X-DSTPU-Handoff-Transport"
STEAL_SENT_HEADER = "X-DSTPU-Steal-Sent"


def request_priority(handler, doc: dict) -> Optional[str]:
    """The request's priority class from the JSON ``priority`` field (wins)
    or the ``X-DSTPU-Priority`` header; None = scheduler default. Raises
    ``ValueError`` on an unknown class (callers answer 400)."""
    raw = doc.get("priority") or handler.headers.get(PRIORITY_HEADER) or None
    return validate_priority(raw) if raw is not None else None


def request_tenant(handler, doc: dict) -> Optional[str]:
    """The request's tenant identity from the JSON ``tenant`` field (wins) or
    the ``X-DSTPU-Tenant`` header; None = the scheduler's default tenant.
    Raises ``ValueError`` on a malformed identifier (callers answer 400)."""
    raw = doc.get("tenant") or handler.headers.get(TENANT_HEADER) or None
    return validate_tenant(raw)


def retry_after_header(seconds: float) -> str:
    """HTTP ``Retry-After`` is integer seconds; round up so a client never
    retries before the estimate says there is room."""
    return str(max(1, math.ceil(seconds)))


_PAYLOAD_KEY_RE = re.compile(rb'"payload"\s*:\s*"')
_DECODE_CHUNK = 1 << 20


def read_resume_body(rfile, length: int) -> dict:
    """Stream a base64 ``/v1/resume`` JSON body off the socket, decoding the
    ``payload`` string incrementally so peak memory is ~1x the decoded
    payload — the old read-then-parse-then-decode path held wire bytes
    (4/3x) + the parsed str (4/3x) + the decoded bytes (1x) simultaneously,
    a ~3.7x peak on a multi-hundred-MB handoff. The payload value must be a
    contiguous base64 string with no JSON escapes, which is exactly what
    ``_request_doc`` and the fleet router emit."""
    skeleton = bytearray()  # the JSON doc with the payload value spliced out
    raw = bytearray()       # decoded payload (amortized growth, ~1x)
    b64_tail = b""          # undecoded remainder (4-char alignment carry)
    in_payload = False
    found = False
    remaining = length
    search_from = 0
    while remaining > 0:
        chunk = rfile.read(min(_DECODE_CHUNK, remaining))
        if not chunk:
            raise ValueError("resume body truncated mid-read")
        remaining -= len(chunk)
        while chunk:
            if not in_payload:
                skeleton += chunk
                chunk = b""
                if found:
                    continue
                m = _PAYLOAD_KEY_RE.search(skeleton, search_from)
                if m is None:
                    # the key marker may straddle the next chunk boundary:
                    # back the resume point up by the marker's width
                    search_from = max(0, len(skeleton) - 16)
                    continue
                found = True
                in_payload = True
                chunk = bytes(skeleton[m.end():])
                del skeleton[m.end():]  # keep the opening quote; value moves out
            else:
                end = chunk.find(b'"')
                data, chunk = (chunk, b"") if end < 0 else \
                    (chunk[:end], chunk[end:])  # chunk resumes AT the close quote
                if b64_tail:
                    data = b64_tail + data
                    b64_tail = b""
                if end < 0:
                    cut = len(data) - (len(data) & 3)
                    b64_tail = data[cut:]
                    data = data[:cut]
                else:
                    in_payload = False
                raw += base64.b64decode(data)  # binascii.Error IS a ValueError
    if in_payload or b64_tail:
        raise ValueError("resume body truncated inside the payload string")
    doc = json.loads(bytes(skeleton))
    if not isinstance(doc, dict):
        raise ValueError("resume body must be a JSON object")
    if not found:
        raise KeyError("payload")
    # hand the bytearray over as-is: a bytes() copy here would undo the whole
    # streaming exercise (1x decoded + 1x copy = the 2x peak again); the
    # scheduler treats the payload as immutable and nobody else holds it
    doc["payload"] = raw
    return doc


def parse_request_body(handler, resume: bool, max_bytes: Optional[int] = None) -> dict:
    """Read + validate a ``/v1/generate`` | ``/v1/resume`` JSON body from an
    http.server request handler — the single wire-format authority, shared by
    :class:`ServingServer` and the fleet router (whose contract is that a
    client cannot tell it from a single replica). Returns the parsed doc,
    with ``doc["payload"]`` decoded to bytes for resume. A resume POST with
    ``Content-Type: application/x-dstpu-handoff`` carries the raw frame as
    the whole body (zero-copy: no base64, no JSON buffer) with the
    generation params in the ``X-DSTPU-Params`` header; ``doc["_transport"]``
    records which wire form arrived. Raises ``ValueError``/``KeyError``/
    ``TypeError`` on malformed input (callers answer 400)."""
    if max_bytes is None:
        max_bytes = _MAX_RESUME_BODY_BYTES if resume else _MAX_BODY_BYTES
    length = int(handler.headers.get("Content-Length", 0))
    if not 0 < length <= max_bytes:
        raise ValueError(f"body length {length} out of bounds")
    if resume:
        ctype = (handler.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == HANDOFF_CONTENT_TYPE:
            doc = json.loads(handler.headers.get(PARAMS_HEADER) or "{}")
            if not isinstance(doc, dict):
                raise ValueError(f"{PARAMS_HEADER} must be a JSON object")
            doc["payload"] = handler.rfile.read(length)
            doc["_transport"] = "binary"
            return doc
        # fleet decode-role continuation, base64 compatibility form: the body
        # carries a peer engine's export_sequence payload instead of a prompt
        doc = read_resume_body(handler.rfile, length)
        doc["_transport"] = "base64"
        return doc
    doc = json.loads(handler.rfile.read(length))
    prompt = doc["prompt"]
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) for t in prompt)):
        raise ValueError("'prompt' must be a non-empty list of token ids")
    return doc


def _request_doc(req: Request, raw_handoff: bool = False,
                 handoff_ref: Optional[str] = None) -> dict:
    doc = {
        "uid": req.uid,
        "handle": req.handle,
        "tokens": list(req.tokens),
        "n_tokens": len(req.tokens),
        "cached_tokens": req.cached_tokens,
        "decode_steps": req.decode_steps,
        "state": req.state.name,
        "finish_reason": req.finish_reason,
        "error": req.error,
        "ttft_s": req.ttft_s,
        "e2e_s": req.e2e_s,
        "trace_id": req.trace_id,
        "priority": req.priority,
        "tenant": req.tenant,
    }
    if req.cost is not None:
        # the per-request bill (telemetry active): device-seconds by phase,
        # priced token work, KV block-seconds by tier, wire bytes by channel,
        # and the cache/spec savings — same shape as the /v1/usage rollup rows
        doc["cost"] = req.cost.to_dict()
    if req.spec_drafted:
        # speculative decoding rode this request: drafted/accepted let a
        # client (and the loadgen --spec-demo report) compute acceptance rate
        # and tokens-per-step without scraping /v1/stats; "drafter" is which
        # drafter family served the request (last one used, under auto
        # arbitration) so the loadgen report can split effectiveness by it
        doc["spec"] = {"drafted": req.spec_drafted,
                       "accepted": req.spec_accepted,
                       "drafter": req._spec_last_drafter or "prompt_lookup"}
    if req.degraded_mode:
        # brownout degradations applied to THIS request — never silent
        doc["degraded_mode"] = list(req.degraded_mode)
    if req.retry_after_s is not None:
        # shed disposition: the queue-drain-derived backoff rides the final
        # doc (and the SSE done/error event) so streaming clients see it too
        doc["retry_after_s"] = req.retry_after_s
    if req.handoff_payload is not None:
        # fleet prefill→decode handoff: the exported KV/generation state, for
        # POST /v1/resume on a decode-role peer. An in-process leg (fleet
        # LocalReplica) keeps the bytes raw; a client that negotiated the
        # binary transport gets a claim-once ref (GET /v1/handoff/<ref>
        # returns the raw frame — zero base64 tax); everyone else gets the
        # base64-in-JSON compatibility form.
        if handoff_ref is not None:
            doc["handoff_ref"] = handoff_ref
        else:
            doc["handoff"] = (req.handoff_payload if raw_handoff else
                              base64.b64encode(req.handoff_payload).decode())
    if req.park_payload is not None:
        # tiered KV parking: the v2 park frame, for the router's park store
        # (an in-process fleet leg keeps the bytes raw). A direct client can
        # hold it and rehydrate the next turn via /v1/resume with a prompt.
        doc["park"] = (req.park_payload if raw_handoff else
                       base64.b64encode(req.park_payload).decode())
    if req._rehydrate:
        # the returning-turn receipt: the cached turns' KV was imported (zero
        # prefill for them) from this tier
        doc["rehydrated"] = True
        doc["park_tier"] = req.kv_tier_source
    return doc


class ServingServer:
    """HTTP front-end over a :class:`ServingScheduler` (constructed outside so
    the same scheduler can also be driven programmatically)."""

    def __init__(self, scheduler: ServingScheduler,
                 host: Optional[str] = None, port: Optional[int] = None):
        self._scheduler = scheduler
        cfg: ServingConfig = scheduler._config
        self._host = host if host is not None else cfg.host
        self._port = port if port is not None else cfg.port
        self._server = None
        self._thread = None
        self._draining = threading.Event()
        # claim-once binary handoff returns: a client that negotiated
        # "X-DSTPU-Handoff-Transport: ref" gets a handoff_ref in the final
        # doc and fetches the raw frame from GET /v1/handoff/<ref> — the
        # frame never pays the base64 tax. Bounded so unclaimed refs (a
        # router that died between the done event and the claim) cannot
        # accumulate payload-sized garbage.
        self._handoff_store: dict = {}
        self._handoff_lock = threading.Lock()
        self._handoff_ids = itertools.count()

    def _stash_handoff(self, payload: bytes) -> str:
        with self._handoff_lock:
            ref = f"h{next(self._handoff_ids)}"
            self._handoff_store[ref] = payload
            while len(self._handoff_store) > 32:
                self._handoff_store.pop(next(iter(self._handoff_store)))
        return ref

    def _claim_handoff(self, ref: str) -> Optional[bytes]:
        with self._handoff_lock:
            return self._handoff_store.pop(ref, None)

    @property
    def scheduler(self) -> ServingScheduler:
        return self._scheduler

    @property
    def address(self):
        """(host, port) once started."""
        return self._server.server_address if self._server else None

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # ----------------------------------------------------------------- start --
    def start(self) -> "ServingServer":
        scheduler, draining = self._scheduler, self._draining
        outer = self
        cfg: ServingConfig = scheduler._config

        class Handler(BaseHTTPRequestHandler):

            def _send_bytes(self, code, payload, headers=()):
                self.send_response(code)
                self.send_header("Content-Type", HANDOFF_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def _send_json(self, code, doc, trace_id=None, retry_after=None):
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if trace_id is not None:
                    self.send_header(TRACE_HEADER, trace_id)
                if retry_after is not None:
                    # drain-rate-derived backoff: well-behaved clients retry
                    # proportionally instead of hammering a saturated server
                    self.send_header("Retry-After", retry_after_header(retry_after))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/v1/stats":
                    self._send_json(200, scheduler.stats())
                elif path == "/v1/usage":
                    # cost attribution: ledger totals + per-tenant rollup +
                    # fair-share posture ({"enabled": false} w/o telemetry)
                    self._send_json(200, scheduler.usage())
                elif path.startswith("/v1/handoff/"):
                    # claim-once binary handoff fetch (the "ref" transport's
                    # second half): the raw frame, exactly once
                    payload = outer._claim_handoff(path.rsplit("/", 1)[1])
                    if payload is None:
                        self._send_json(404, {"error": "no such handoff ref "
                                                       "(already claimed?)"})
                    else:
                        self._send_bytes(200, payload)
                elif path == "/healthz":
                    # readiness-gated liveness: "starting" until the scheduler
                    # loop ticks (a supervisor registers a replica only on
                    # "ok" — see fleet/supervisor.py), "draining" on the way
                    # out; fleet probes treat anything but "ok" as
                    # not-dispatchable
                    if draining.is_set():
                        status = "draining"
                    else:
                        status = "ok" if scheduler.ready else "starting"
                    self._send_json(200, {"status": status})
                elif path == "/trace/export":
                    # fleet trace collection: drain this process's span ring
                    # for the router-side TraceCollector (since_us is in OUR
                    # clock; now_us in the reply lets the puller estimate the
                    # offset from its round-trip)
                    since_us = 0
                    query = self.path.partition("?")[2]
                    for part in query.split("&"):
                        if part.startswith("since_us="):
                            try:
                                since_us = int(part.split("=", 1)[1])
                            except ValueError:
                                pass
                    recorder = telemetry.get_span_recorder()
                    if recorder is None:
                        self._send_json(200, {"now_us": telemetry.now_us(),
                                              "pid": os.getpid(),
                                              "dropped": 0, "spans": []})
                    else:
                        self._send_json(200, recorder.export_since(since_us))
                else:
                    self._send_json(404, {"error": f"no route {path}"})

            def _upstream_trace(self):
                """(trace_id, parent_span_id) from the request headers — the
                fleet router's trace context, adopted so router → replica
                renders as one parented Perfetto track."""
                trace_id = self.headers.get(TRACE_HEADER) or None
                parent = self.headers.get(PARENT_SPAN_HEADER)
                try:
                    parent_span_id = int(parent) if parent else None
                except ValueError:
                    parent_span_id = None
                return trace_id, parent_span_id

            def _small_json_body(self, cap: int = 1 << 20) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                if not 0 < length <= cap:
                    raise ValueError(f"body length {length} out of bounds")
                doc = json.loads(self.rfile.read(length))
                if not isinstance(doc, dict):
                    raise ValueError("body must be a JSON object")
                return doc

            def _steal(self):
                """Fleet work-stealing victim side: move the addressed
                request off this replica. An exported continuation goes out
                as the raw binary frame (zero-copy), with the count of
                already-streamed tokens in a header."""
                try:
                    doc = self._small_json_body()
                    handle = doc["handle"]
                    if not isinstance(handle, str):
                        raise ValueError("'handle' must be a string")
                except (KeyError, ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                try:
                    out = scheduler.request_steal(handle)
                except (SchedulerStopped, TimeoutError) as e:
                    self._send_json(503, {"error": str(e)})
                    return
                if out["status"] == "exported":
                    self._send_bytes(200, out["payload"],
                                     headers=((STEAL_SENT_HEADER,
                                               str(out["sent"])),))
                else:
                    self._send_json(200, {"status": out["status"]})

            def _prefix_export(self):
                """Peer prefix-fetch donor side: the deepest cached KV run
                along the posted digest chain, as a raw binary frame."""
                try:
                    doc = self._small_json_body()
                    digests = [bytes.fromhex(d) for d in doc["digests"]]
                    min_blocks = int(doc.get("min_blocks") or 1)
                except (KeyError, ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                try:
                    payload = scheduler.export_prefix(digests,
                                                      min_blocks=min_blocks,
                                                      timeout=2.0)
                except (SchedulerStopped, TimeoutError) as e:
                    self._send_json(503, {"error": str(e)})
                    return
                if payload is None:
                    self._send_json(404, {"error": f"no cached path at least "
                                                   f"{min_blocks} blocks deep"})
                else:
                    self._send_bytes(200, payload)

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                # steal + prefix export stay routable while draining: they
                # move state OUT of this replica, admitting nothing
                if path == "/v1/steal":
                    self._steal()
                    return
                if path == "/v1/prefix/export":
                    self._prefix_export()
                    return
                if path not in ("/v1/generate", "/v1/resume"):
                    self._send_json(404, {"error": f"no route {path}"})
                    return
                if draining.is_set():
                    self._send_json(503, {"error": "server is draining"},
                                    retry_after=scheduler.retry_after_s())
                    return
                trace_id, parent_span_id = self._upstream_trace()
                resume = path == "/v1/resume"
                try:
                    doc = parse_request_body(
                        self, resume=resume,
                        max_bytes=cfg.max_resume_body_bytes if resume else None)
                except (KeyError, ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                try:
                    # wrongly-typed optional fields (string temperature, ...)
                    # raise here and fall through to the 400 below
                    common = dict(max_new_tokens=doc.get("max_new_tokens"),
                                  temperature=float(doc.get("temperature") or 0.0),
                                  eos_token_id=doc.get("eos_token_id"),
                                  deadline_s=doc.get("deadline_s"),
                                  seed=int(doc.get("seed") or 0),
                                  trace_id=trace_id,
                                  parent_span_id=parent_span_id,
                                  handoff=bool(doc.get("handoff")),
                                  park=bool(doc.get("park")),
                                  priority=request_priority(self, doc),
                                  drafter=doc.get("drafter"),
                                  tenant=request_tenant(self, doc))
                    if path == "/v1/resume":
                        # a resume body MAY carry a prompt: the rehydrate form
                        # (parked session returning with its next turn)
                        req = scheduler.submit_resume(doc["payload"],
                                                      prompt=doc.get("prompt"),
                                                      **common)
                    else:
                        req = scheduler.submit(doc["prompt"], **common)
                except AdmissionRejected as e:
                    # overload control said no before any engine work: the
                    # cheap rejection, with the drain-rate-derived backoff
                    self._send_json(429, {"error": str(e),
                                          "retry_after_s": e.retry_after_s},
                                    retry_after=e.retry_after_s)
                    return
                except QueueFullError as e:
                    self._send_json(429, {"error": str(e),
                                          "queue_depth": scheduler.queue_depth},
                                    retry_after=scheduler.retry_after_s())
                    return
                except SchedulerStopped as e:
                    self._send_json(503, {"error": str(e)},
                                    retry_after=scheduler.retry_after_s())
                    return
                except (ValueError, TypeError) as e:
                    # wrongly-typed optional fields (null temperature, string
                    # max_new_tokens, ...) are client errors, not handler crashes
                    self._send_json(400, {"error": str(e)})
                    return
                ref_mode = (self.headers.get(HANDOFF_TRANSPORT_HEADER)
                            or "").strip().lower() == "ref"
                if doc.get("stream"):
                    self._stream_sse(req, ref_mode=ref_mode)
                else:
                    req.wait()  # terminal by deadline/max_new_tokens/cancel
                    if req.shed_reason is not None or (
                            req.retry_after_s is not None and not req.tokens):
                        # shed (or deadline-expired) before any engine work:
                        # to the client this IS an admission rejection — 429
                        self._send_json(429, _request_doc(req),
                                        trace_id=req.trace_id,
                                        retry_after=req.retry_after_s)
                    else:
                        self._send_json(200, self._final_doc(req, ref_mode),
                                        trace_id=req.trace_id)

            def _final_doc(self, req, ref_mode):
                if ref_mode and req.handoff_payload is not None:
                    return _request_doc(
                        req, handoff_ref=outer._stash_handoff(req.handoff_payload))
                return _request_doc(req)

            def _stream_sse(self, req, ref_mode=False):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                if req.trace_id is not None:
                    # the trace id is known at admission, so streaming clients
                    # get it up-front (it repeats in the final `done` event)
                    self.send_header(TRACE_HEADER, req.trace_id)
                # the steal handle goes out before the first token: the fleet
                # router must be able to address a request that is still
                # queued or mid-decode
                self.send_header(HANDLE_HEADER, req.handle)
                self.end_headers()
                try:
                    i = 0
                    while True:
                        try:
                            tok = req.stream.get(timeout=cfg.sse_keepalive_s)
                        except queue.Empty:
                            # no token yet (queue wait, long prefill): an SSE
                            # comment keeps the socket demonstrably alive, so
                            # a fleet router's read budget measures death,
                            # never load (SSE parsers ignore ':' lines)
                            self.wfile.write(b": keepalive\n\n")
                            self.wfile.flush()
                            continue
                        if tok is None:  # stream closed and drained: terminal
                            break
                        self.wfile.write(
                            f"data: {json.dumps({'token': tok, 'index': i})}\n\n".encode())
                        self.wfile.flush()
                        i += 1
                    self.wfile.write(
                        f"data: {json.dumps({'done': True, **self._final_doc(req, ref_mode)})}\n\n".encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # client went away: cancel so the sequence's KV blocks
                    # return to the pool on the next scheduler tick
                    req.cancel()

            def log_message(self, fmt, *args):
                ...  # request logging must not spam the serving log

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dstpu-serving-http", daemon=True)
        self._thread.start()
        logger.info(f"serving: /v1/generate /v1/resume /v1/stats /v1/usage "
                    f"/healthz on {self.url}")
        return self

    # ------------------------------------------------------------------ stop --
    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: stop admitting (503), drain in-flight bounded by
        the drain timeout, then close the listener. Idempotent."""
        if self._server is None:
            return
        self._draining.set()
        self._scheduler.stop(drain=drain, timeout=timeout)
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        self._thread = None

    def __enter__(self):
        return self.start() if self._server is None else self

    def __exit__(self, *exc):
        self.stop(drain=False)
