"""Loss scaling.

Reference: ``deepspeed/runtime/fp16/loss_scaler.py`` (LossScaler:67,
DynamicLossScaler:91). The scale state lives *inside* the jitted step as a small
pytree so overflow-skip and scale adjustment happen on-device with no host sync:

    state = (cur_scale, good_steps, hysteresis_left)

bf16 runs don't need scaling (TPU-native); the engine only threads this state when
fp16 is enabled.
"""

from typing import NamedTuple

import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray  # f32 scalar
    good_steps: jnp.ndarray  # i32 scalar
    hysteresis: jnp.ndarray  # i32 scalar


def static_loss_scale_state(scale: float) -> LossScaleState:
    return LossScaleState(cur_scale=jnp.asarray(scale, jnp.float32),
                          good_steps=jnp.zeros([], jnp.int32),
                          hysteresis=jnp.asarray(1, jnp.int32))


def dynamic_loss_scale_state(initial_scale_power=16, delayed_shift=2) -> LossScaleState:
    return LossScaleState(cur_scale=jnp.asarray(2.0**initial_scale_power, jnp.float32),
                          good_steps=jnp.zeros([], jnp.int32),
                          hysteresis=jnp.asarray(delayed_shift, jnp.int32))


def update_scale(state: LossScaleState,
                 overflow,
                 *,
                 scale_window: int = 1000,
                 scale_factor: float = 2.0,
                 min_scale: float = 1.0,
                 delayed_shift: int = 1,
                 consecutive_hysteresis: bool = False,
                 dynamic: bool = True) -> LossScaleState:
    """Pure update — reference DynamicLossScaler.update_scale semantics."""
    if not dynamic:
        return state
    overflow = jnp.asarray(overflow)

    # reference DynamicLossScaler.update_scale: an overflow either consumes one
    # hysteresis count (delayed_shift>1 and counts remain) or shrinks the scale;
    # hysteresis refills at the scale window (or every good step when
    # consecutive_hysteresis), and the scale grows after scale_window good steps.
    must_shrink = overflow & ((delayed_shift == 1) | (state.hysteresis <= 1))
    shrunk = jnp.maximum(state.cur_scale / scale_factor, min_scale)
    h_on_overflow = jnp.where(must_shrink, state.hysteresis, state.hysteresis - 1)

    window_full = (state.good_steps + 1) % scale_window == 0
    grown = jnp.where(~overflow & window_full, state.cur_scale * scale_factor, state.cur_scale)

    new_scale = jnp.where(must_shrink, shrunk, grown)
    new_good = jnp.where(overflow, 0, jnp.where(window_full, 0, state.good_steps + 1))
    if consecutive_hysteresis:
        h_on_good = jnp.asarray(delayed_shift, jnp.int32)
    else:
        h_on_good = jnp.where(window_full, jnp.asarray(delayed_shift, jnp.int32), state.hysteresis)
    new_h = jnp.where(overflow, h_on_overflow, h_on_good).astype(jnp.int32)
    return LossScaleState(cur_scale=new_scale, good_steps=new_good.astype(jnp.int32), hysteresis=new_h)


class LossScalerBase:
    """Stateful API-parity wrapper (reference LossScalerBase)."""

    def __init__(self, cur_scale):
        self.cur_scale = cur_scale
        self.dynamic = False

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def update_scale(self, overflow):
        pass

    def backward(self, loss, retain_graph=False):
        raise NotImplementedError("Use the engine's backward; JAX has no .backward graphs")


class LossScaler(LossScalerBase):
    """Static scale (reference loss_scaler.py:67)."""

    def __init__(self, scale=1.0):
        super().__init__(scale)

    def has_overflow(self, params):
        return False


class DynamicLossScaler(LossScalerBase):
    """Reference loss_scaler.py:91."""

    def __init__(self,
                 init_scale=2**32,
                 scale_factor=2.0,
                 scale_window=1000,
                 min_scale=1.0,
                 delayed_shift=1,
                 consecutive_hysteresis=False,
                 raise_error_at_min_scale=True,
                 dtype=None):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.raise_error_at_min_scale = raise_error_at_min_scale
        self.dynamic = True

    def update_scale(self, overflow):
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                if self.cur_scale == self.min_scale and self.raise_error_at_min_scale:
                    raise Exception("Current loss scale already at minimum - cannot decrease scale anymore.")
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Reference factory (loss_scaler.py bottom)."""
    import jax.numpy as jnp
    if dtype == jnp.float16 and dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        return DynamicLossScaler(dtype=dtype, **kwargs)
    loss_scale_value = static_loss_scale if dtype == jnp.float16 else 1.0
    return LossScaler(scale=loss_scale_value)
