"""ZeRO-Inference-style weight quantization for the ragged engine.

Reference: the ZeRO-Inference release (reference README.md:17 — "20x faster
inference" via weight quantization + KV-cache offload) and
``deepspeed/inference/quantization`` (per-channel symmetric int8 of the
matmul weights, dequantized on use).

TPU formulation: quantized leaves are stored int8 in HBM with per-output-
channel fp scales; ``dequantize_tree`` runs *inside* the jitted forward, so
XLA fuses the int8→bf16 convert+scale into each weight's consumer — weights
stream from HBM at 1 byte/element (the decode-path win; matmuls stay MXU
bf16). Pytree-native: a quantized leaf becomes a ``{QKEY, SKEY, DKEY}`` dict
subtree, invisible to checkpointing and sharding machinery.
"""

from typing import Any

import numpy as np

QKEY = "__wq_int8__"
SKEY = "__wq_scale__"
DKEY = "__wq_dtype__"


def _quantize_leaf(w):
    import jax.numpy as jnp
    # per-output-channel symmetric int8: reduce the contraction axis (-2),
    # keep leading (expert/stack) dims
    scale = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    # dtype marker as a 0-d array so the subtree stays a pure array pytree
    return {QKEY: q, SKEY: scale, DKEY: jnp.zeros((), w.dtype)}


def is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and QKEY in node


def quantize_tree(params, min_size: int = 4096, bits: int = 8):
    """Quantize every floating leaf with ndim >= 2 and >= ``min_size`` elements
    (norm scales, biases and small tensors stay full precision — the
    reference's exclusion list)."""
    import jax.numpy as jnp
    if bits != 8:
        raise NotImplementedError(f"only int8 weight quantization is implemented (got {bits})")

    def rec(node):
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if (hasattr(node, "ndim") and node.ndim >= 2
                and jnp.issubdtype(node.dtype, jnp.floating)
                and int(np.prod(node.shape)) >= min_size):
            return _quantize_leaf(node)
        return node

    return rec(params)


def dequantize_tree(params):
    """Collapse quantized subtrees back to full-precision arrays. Called inside
    jit: the convert+scale fuses into each weight's consumer, so the at-rest
    representation stays int8."""
    import jax.numpy as jnp

    def rec(node):
        if is_quantized_leaf(node):
            return (node[QKEY].astype(jnp.float32) * node[SKEY]).astype(node[DKEY].dtype)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return node

    return rec(params)


def tree_nbytes(params) -> int:
    """Total array bytes in a (possibly quantized) tree — the memory claim."""
    import jax
    return sum(l.nbytes for l in jax.tree.leaves(params) if hasattr(l, "nbytes"))
