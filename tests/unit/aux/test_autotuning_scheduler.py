"""Launcher-scheduled autotuning experiments (VERDICT r5 ask #3).

Reference: ``deepspeed/autotuning/scheduler.py`` (ResourceManager /
run_experiment) + ``autotuner.py:404`` — every candidate runs as its own
launcher job; the tuner harvests results.json and survives dead children.
These tests spawn REAL experiment processes through
``deepspeed_tpu.launcher.runner`` (local mode).
"""

import json

import pytest


def test_subprocess_experiments_pick_measured_winner(tmp_path):
    """Two real experiment processes run; the tuner picks the measured best."""
    from deepspeed_tpu.autotuning import Autotuner

    base = {"train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
            "zero_optimization": {"stage": 0},
            "autotuning": {
                "tuner_type": "gridsearch", "max_experiments": 4,
                "model_factory": "deepspeed_tpu.autotuning.model_factories:tiny_llama",
                "experiment_timeout": 600}}
    tuner = Autotuner(base_config=base,
                      space={"train_micro_batch_size_per_gpu": [2, 4]},
                      steps=2, warmup=1, results_dir=str(tmp_path))
    assert tuner.exec_mode == "subprocess"
    best = tuner.tune()
    assert best["throughput_samples_per_sec"] > 0

    # both candidates ran as separate processes with their own exp dir,
    # exp.json (the materialized candidate config) and harvested results.json
    for i in (1, 2):
        exp = json.loads((tmp_path / f"exp_{i}" / "exp.json").read_text())
        assert "autotuning" not in exp["config"]
        res = json.loads((tmp_path / f"exp_{i}" / "results.json").read_text())
        assert res["throughput_samples_per_sec"] > 0
        assert (tmp_path / f"exp_{i}" / "stderr.log").exists()

    # the winner is the measured max, recorded in the summary results.json
    summary = json.loads((tmp_path / "results.json").read_text())
    tputs = [r["throughput_samples_per_sec"] for r in summary["experiments"]]
    assert len(tputs) == 2
    assert best["throughput_samples_per_sec"] == max(tputs)
    micros = {r["config"]["train_micro_batch_size_per_gpu"] for r in summary["experiments"]}
    assert micros == {2, 4}


def test_subprocess_survives_hard_killed_experiment(tmp_path):
    """A candidate whose process dies WITHOUT writing results.json (the OOM
    kill the in-process tuner could never survive) fails alone; the search
    continues and still picks a winner from the survivors."""
    from deepspeed_tpu.autotuning import Autotuner

    base = {"train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
            "zero_optimization": {"stage": 0},
            "autotuning": {
                "tuner_type": "gridsearch", "max_experiments": 4,
                "model_factory":
                    "deepspeed_tpu.autotuning.model_factories:tiny_llama_fragile",
                "experiment_timeout": 600}}
    tuner = Autotuner(base_config=base,
                      space={"train_micro_batch_size_per_gpu": [2, 4]},
                      steps=2, warmup=1, results_dir=str(tmp_path))
    best = tuner.tune()
    # micro=4 hard-died (os._exit(137), no results.json); micro=2 won
    assert best["config"]["train_micro_batch_size_per_gpu"] == 2
    summary = json.loads((tmp_path / "results.json").read_text())
    by_micro = {r["config"]["train_micro_batch_size_per_gpu"]: r
                for r in summary["experiments"]}
    assert by_micro[4]["throughput_samples_per_sec"] is None
    assert by_micro[2]["throughput_samples_per_sec"] > 0


def test_subprocess_mode_requires_model_factory():
    from deepspeed_tpu.autotuning import Autotuner

    with pytest.raises(ValueError, match="model_factory"):
        Autotuner(base_config={"autotuning": {"exec_mode": "subprocess"}})
