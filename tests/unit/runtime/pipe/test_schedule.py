"""Schedule instruction-stream tests (reference: tests/unit/runtime/pipe/
test_pipe_schedule.py)."""

import pytest

from deepspeed_tpu.runtime.pipe import schedule as S


def _flat(sched):
    return [cmd for step in sched for cmd in step]


def test_inference_schedule_counts():
    sched = S.InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    cmds = _flat(sched)
    fwd = [c for c in cmds if isinstance(c, S.ForwardPass)]
    assert len(fwd) == 4
    sends = [c for c in cmds if isinstance(c, S.SendActivation)]
    assert len(sends) == 4  # stage 0 sends every microbatch


def test_train_schedule_each_mb_fwd_and_bwd_once():
    for stages in (2, 4):
        for stage_id in range(stages):
            sched = S.TrainSchedule(micro_batches=8, stages=stages, stage_id=stage_id)
            cmds = _flat(sched)
            fwd = [c.buffer_id for c in cmds if isinstance(c, S.ForwardPass)]
            bwd = [c.buffer_id for c in cmds if isinstance(c, S.BackwardPass)]
            assert len(fwd) == 8, f"stage {stage_id}/{stages}"
            assert len(bwd) == 8
            # single optimizer step at the very end
            steps = [c for c in cmds if isinstance(c, S.OptimizerStep)]
            assert len(steps) == 1
            assert isinstance(cmds[-1], S.OptimizerStep)


def test_train_schedule_fwd_before_bwd():
    sched = S.TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for step in sched:
        for cmd in step:
            if isinstance(cmd, S.ForwardPass):
                seen_fwd.add(cmd.buffer_id)
            if isinstance(cmd, S.BackwardPass):
                assert cmd.buffer_id in seen_fwd  # backward only after its forward


def test_train_schedule_1f1b_inflight_bound():
    """In-flight microbatches never exceed the remaining pipeline depth."""
    stages, mb = 4, 16
    for stage_id in range(stages):
        sched = S.TrainSchedule(micro_batches=mb, stages=stages, stage_id=stage_id)
        inflight = 0
        peak = 0
        for step in sched:
            for cmd in step:
                if isinstance(cmd, S.ForwardPass):
                    inflight += 1
                if isinstance(cmd, S.BackwardPass):
                    inflight -= 1
                peak = max(peak, inflight)
        assert peak <= stages - stage_id + 1


def test_num_pipe_buffers():
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    assert sched.num_pipe_buffers() == 4
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    assert sched.num_pipe_buffers() == 2


def test_instruction_repr_and_eq():
    a = S.ForwardPass(buffer_id=1)
    b = S.ForwardPass(buffer_id=1)
    c = S.ForwardPass(buffer_id=2)
    assert a == b and a != c
    assert "ForwardPass" in repr(a)
