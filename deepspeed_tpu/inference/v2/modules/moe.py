"""Ragged MoE for inference, with expert parallelism (the fork's core feature).

Reference: ``deepspeed/inference/v2/modules/implementations/moe/cutlass_multi_gemm.py``
(DSMultiGemmMoE:28) and the fork's ``cutlass_multi_gemm_ep.py`` (DSMultiGemmMoEEp:32)
— top-k gating → moe_scatter → [EP: variable all_to_all x2 for counts+tokens] →
grouped GEMM → moe_gather → [EP: all_to_all back], with ``empty_run`` participation.

TPU translation: XLA collectives are shape-static, so the fork's *variable-size*
all-to-alls become fixed-capacity ``lax.all_to_all`` over the ``expert`` mesh axis
(capacity = ceil(T * k / E) * factor). Dispatch packs each expert's tokens into its
capacity slots (the reference's moe_scatter), the all_to_all exchanges expert-major
buffers across EP ranks, each rank runs its local experts' grouped GEMM, and the
reverse all_to_all + combine weights reproduce moe_gather. ``empty_run`` is a
forward with zero live tokens: every rank still enters the same collectives —
exactly the deadlock-avoidance contract of the fork (engine_v2.py:308).

Simulated gating (fork ``top_k_gating/expert_probs.py``): when enabled, router
logits are replaced by a per-layer synthetic distribution with a temperature knob,
decoupling load-balance experiments from real router weights. The reference ships
measured Mixtral expert-count tables; we synthesize a skewed per-layer
distribution from a seeded Dirichlet instead (same knob semantics, no dataset
dependency), sharpened/flattened by ``softmax(log(p)/temperature)``.
"""

from typing import Optional

import numpy as np

from deepspeed_tpu.utils import groups

_SIMULATED_GATING = {"enabled": False, "temperature": 1.0}


def enable_simulated_gating(temperature: float = 1.0) -> None:
    _SIMULATED_GATING["enabled"] = True
    _SIMULATED_GATING["temperature"] = float(temperature)


def disable_simulated_gating() -> None:
    _SIMULATED_GATING["enabled"] = False


def simulated_gating_enabled() -> bool:
    return _SIMULATED_GATING["enabled"]


def simulated_expert_probs(layer_id: int, num_experts: int, temperature: Optional[float] = None):
    """Per-layer synthetic expert distribution (seeded, deterministic)."""
    import jax.numpy as jnp
    if temperature is None:
        temperature = _SIMULATED_GATING["temperature"]
    rng = np.random.default_rng(1000 + layer_id)
    p = rng.dirichlet(np.full(num_experts, 2.0))
    logp = np.log(np.maximum(p, 1e-9)) / max(temperature, 1e-6)
    e = np.exp(logp - logp.max())
    return jnp.asarray(e / e.sum(), jnp.float32)


class RaggedMoE:
    """Functional top-k MoE over flat tokens [T, M] with optional EP sharding."""

    def __init__(self, num_experts: int, top_k: int = 2, capacity_factor: float = 2.0,
                 expert_axis: str = groups.EXPERT_AXIS, layer_id: int = 0):
        assert top_k in (1, 2), "ragged MoE supports top-1/top-2"
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.expert_axis = expert_axis
        self.layer_id = layer_id

    def _router_probs(self, h, gate_w):
        import jax
        import jax.numpy as jnp
        if simulated_gating_enabled():
            # Load-testing mode: every token draws from the synthetic per-layer
            # distribution; token index seeds the draw so batches are diverse.
            probs = simulated_expert_probs(self.layer_id, self.num_experts)
            T = h.shape[0]
            u = jax.random.uniform(jax.random.PRNGKey(self.layer_id), (T, self.num_experts))
            # Gumbel trick over the fixed distribution
            logits = jnp.log(probs)[None, :] - jnp.log(-jnp.log(jnp.maximum(u, 1e-9)))
            return jax.nn.softmax(logits, axis=-1)
        logits = h.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        return jax.nn.softmax(logits, axis=-1)

    def __call__(self, h, gate_w, wi, wo, token_valid=None, activation=None, mesh=None):
        """h: [T, M]; gate_w: [M, E]; wi: [E, M, F]; wo: [E, F, M] (the training
        ExpertFFN bank layout — EP-shards on the leading dim)."""
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.sequence.layer import _constrain

        if activation is None:
            activation = jax.nn.silu
        T, M = h.shape
        E = self.num_experts
        C = max(4, int(np.ceil(T * self.top_k / E * self.capacity_factor)))

        probs = self._router_probs(h, gate_w)  # [T, E]
        if token_valid is not None:
            probs = probs * token_valid[:, None]

        # top-k assignment with capacity packing (reference moe_scatter)
        combine = jnp.zeros((T, E, C), jnp.float32)
        dispatch = jnp.zeros((T, E, C), h.dtype)
        topk_p, topk_e = jax.lax.top_k(probs, self.top_k)  # [T, k]
        if self.top_k == 2:
            denom = jnp.maximum(topk_p.sum(-1, keepdims=True), 1e-9)
            topk_p = topk_p / denom  # Mixtral renormalizes over the chosen 2
        # Slot counters are SHARED across the k choices (reference top2gating:
        # locations2 += sum(mask1)) — otherwise a first-choice and a
        # second-choice token land in the same capacity slot and their hidden
        # states sum in the expert buffer.
        base = jnp.zeros((E, ), jnp.int32)
        for j in range(self.top_k):
            e_j = topk_e[:, j]  # [T]
            if token_valid is not None:
                # invalid tokens must not consume capacity slots: route them OOB
                e_j = jnp.where(token_valid, e_j, E)
            onehot = jax.nn.one_hot(e_j, E, dtype=jnp.int32)  # [T, E]; OOB -> all-zero
            slot = jnp.cumsum(onehot, axis=0) * onehot - 1  # position within expert
            slot_t = slot.max(axis=1) + (onehot @ base)  # [T]; -1 for OOB tokens
            ok = (slot_t < C) & (slot_t >= 0)
            t_idx = jnp.arange(T)
            slot_c = jnp.where(ok, slot_t, C)  # OOB slot -> dropped by scatter
            combine = combine.at[t_idx, e_j, slot_c].add(
                jnp.where(ok, topk_p[:, j], 0.0), mode="drop")
            dispatch = dispatch.at[t_idx, e_j, slot_c].add(
                jnp.where(ok, 1.0, 0.0).astype(h.dtype), mode="drop")
            base = base + onehot.sum(axis=0)

        # dispatch: [E, C, M] expert-major buffer -> the (fixed-capacity) a2a
        buf = jnp.einsum("tec,tm->ecm", dispatch, h)

        def expert_sharded(t):
            return _constrain(t, (self.expert_axis, ) + (None, ) * (t.ndim - 1), mesh)

        buf = expert_sharded(buf)  # a2a #2 analog: tokens to expert shards
        hpre = jnp.einsum("ecm,emf->ecf", buf, wi.astype(buf.dtype))
        if wi.shape[-1] == 2 * wo.shape[-2]:  # fused (gate|up) SwiGLU bank
            from deepspeed_tpu.moe.layer import gated_expert_act
            hmid = gated_expert_act(hpre, activation)
        else:
            hmid = activation(hpre)
        out = jnp.einsum("ecf,efm->ecm", hmid, wo.astype(buf.dtype))
        out = expert_sharded(out)  # a2a #3 analog: results back
        return jnp.einsum("tec,ecm->tm", combine.astype(h.dtype), out)
