"""Fleet trace collector: Dapper-style cross-process span assembly.

A request routed through the fleet carries ONE trace id, but its spans land
in N per-process ring buffers (the router's recorder plus one per replica
subprocess). The :class:`TraceCollector` pulls those rings together:

- the router's own :class:`SpanRecorder` (and any LocalReplica, which shares
  the same process-global recorder — deduplicated by recorder identity) is
  read in-process at offset zero;
- each subprocess replica is drained over the wire via
  ``GET /trace/export?since_us=`` with a clock-offset estimate from the pull
  round-trip: spans are stamped with the *remote* ``now_us()`` (a
  per-process monotonic clock), so the collector samples its own clock
  before (t0) and after (t1) the pull and corrects every remote timestamp
  by ``offset = remote_now - (t0 + t1) / 2``.

Merged spans are stored keyed by trace id (bounded, oldest trace evicted)
and export as one Chrome-trace/Perfetto document in which each source keeps
its own ``pid`` — the first place a router→prefill→handoff→decode timeline
is visible across real process boundaries.
"""

import os
import threading
from collections import OrderedDict

from deepspeed_tpu.telemetry.spans import now_us

# re-pull lookback: a span is recorded at its *end*, so a pull at T can miss
# spans that started before T and finish after; the next pull re-reads this
# far behind the remote high-water mark and dedupe-by-span-id absorbs the
# overlap
LOOKBACK_US = 10_000_000


class TraceCollector:
    """Merges per-process span rings into one per-trace store."""

    def __init__(self, max_traces=512, metrics=None):
        self.max_traces = int(max_traces)
        self._metrics = metrics  # FleetMetrics or None (telemetry disabled)
        self._lock = threading.Lock()
        # trace_id -> {(pid, span_id): event dict (corrected, chrome-trace)}
        self._traces = OrderedDict()
        self._sources = {}  # source key -> {"since_us", "offset_us", "pid"}
        self.spans_collected = 0
        self.collections = 0

    # ------------------------------------------------------------- pulling --
    def collect(self, recorder=None, replicas=()):
        """One collection round: drain the local recorder plus every replica.

        ``replicas`` is an iterable of fleet Replica objects exposing
        ``collect_spans(since_us)``; local ones that share ``recorder``'s
        ring are skipped (their spans are already in it).
        """
        seen_recorders = set()
        if recorder is not None:
            seen_recorders.add(id(recorder))
            self._ingest("local", recorder.export_since(
                self._next_since("local")), offset_us=0)
        for replica in replicas:
            shared = getattr(replica, "span_recorder", None)
            if shared is not None and id(shared) in seen_recorders:
                continue
            if shared is not None:
                seen_recorders.add(id(shared))
            key = f"replica:{replica.id}"
            t0 = now_us()
            try:
                doc = replica.collect_spans(self._next_since(key))
            except Exception:
                continue  # an unreachable replica skips this round
            t1 = now_us()
            if not doc:
                continue
            offset = 0
            if shared is None and "now_us" in doc:
                offset = int(doc["now_us"]) - (t0 + t1) // 2
            self._ingest(key, doc, offset_us=offset)
        with self._lock:
            self.collections += 1
        if self._metrics is not None:
            self._metrics.trace_collections.inc()

    def _next_since(self, key):
        source = self._sources.get(key)
        return source["since_us"] if source else 0

    def _ingest(self, key, doc, offset_us):
        spans = doc.get("spans") or []
        pid = int(doc.get("pid", os.getpid()))
        ingested = 0
        with self._lock:
            self._sources[key] = {
                "since_us": max(0, int(doc.get("now_us", 0)) - LOOKBACK_US),
                "offset_us": offset_us,
                "pid": pid,
                "dropped": int(doc.get("dropped", 0)),
            }
            for span in spans:
                trace_id = span.get("trace_id")
                if trace_id is None:
                    continue  # only request traces are assembled fleet-wide
                store = self._traces.get(trace_id)
                if store is None:
                    store = self._traces[trace_id] = {}
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)
                event = {"name": span["name"], "cat": span.get("cat", "default"),
                         "ph": "X", "ts": int(span["ts_us"]) - offset_us,
                         "dur": int(span.get("dur_us", 0)), "pid": pid,
                         "args": dict(span.get("args") or {},
                                      trace_id=trace_id,
                                      span_id=span.get("span_id"),
                                      parent_id=span.get("parent_id"),
                                      source=key)}
                dedupe = (pid, span.get("span_id"))
                if dedupe not in store:
                    ingested += 1
                store[dedupe] = event
            self.spans_collected += ingested
        if ingested and self._metrics is not None:
            self._metrics.trace_spans_collected.inc(ingested)

    # -------------------------------------------------------------- export --
    def trace_ids(self):
        with self._lock:
            return list(self._traces)

    def spans_for(self, trace_id):
        """Corrected events of one trace, sorted by timestamp."""
        with self._lock:
            store = self._traces.get(trace_id, {})
            return sorted((dict(e) for e in store.values()),
                          key=lambda e: e["ts"])

    def chrome_trace(self, trace_id=None):
        """Merged Chrome-trace doc (``/v1/fleet/trace``): every source keeps
        its own pid so Perfetto shows one track group per process; per-trace
        tids give each request a named thread within each process."""
        with self._lock:
            traces = ({trace_id: self._traces.get(trace_id, {})}
                      if trace_id is not None else dict(self._traces))
            events = [dict(e) for store in traces.values()
                      for e in store.values()]
            sources = {key: dict(s) for key, s in self._sources.items()}
        events.sort(key=lambda e: e["ts"])
        trace_tids, pids = {}, {}
        for event in events:
            tid = trace_tids.setdefault(event["args"]["trace_id"],
                                        len(trace_tids) + 1)
            event["tid"] = tid
            pids.setdefault(event["pid"], event["args"].get("source"))
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": source or f"pid {pid}"}}
                for pid, source in pids.items()]
        meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                  "args": {"name": f"request {tid_trace}"}}
                 for pid in pids
                 for tid_trace, tid in trace_tids.items()]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "collector": {"sources": sources,
                              "spans_collected": self.spans_collected,
                              "collections": self.collections,
                              "traces": len(traces)}}

    def describe(self):
        with self._lock:
            return {"traces": len(self._traces),
                    "spans_collected": self.spans_collected,
                    "collections": self.collections,
                    "sources": {k: dict(s) for k, s in self._sources.items()}}
