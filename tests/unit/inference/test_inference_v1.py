"""Inference v1 engine tests (reference: tests/unit/inference/test_inference.py —
here exercised with a flax module instead of HF torch models)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def mesh():
    groups.initialize_mesh(force=True)
    yield


def _tiny_mlp():
    import flax.linen as nn
    import jax

    class MLP(nn.Module):

        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.gelu(x)
            return nn.Dense(8)(x)

    model = MLP()
    x = np.ones((2, 8), np.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    return model, params, x


def test_init_inference_forward():
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params}, dtype="float32")
    out = engine(x)
    assert out.shape == (2, 8)
    # matches the raw module
    import jax
    ref = model.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_init_inference_bf16_cast():
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params}, dtype="bfloat16")
    import jax.numpy as jnp
    leaf = next(iter(engine.params["Dense_0"].values()))
    assert leaf.dtype == jnp.bfloat16
    out = engine(x)
    assert out.shape == (2, 8)


def test_generate_without_module_support_raises():
    """Non-causal-LM modules (no [B,S,V] logits) keep the explicit error."""
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params})
    with pytest.raises(NotImplementedError):
        engine.generate(np.ones((2, 8), np.int32))


def _tiny_llama():
    import jax
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(vocab_size=64, hidden_size=32, intermediate_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4)
    model = LlamaModel(cfg)
    ids = np.ones((2, 4), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return model, params, ids


def test_generate_greedy():
    """v1 autoregressive loop (reference engine.py:613): greedy decode must be
    deterministic and each emitted token must equal the argmax of a fresh
    forward over the running prefix."""
    import jax.numpy as jnp

    model, params, ids = _tiny_llama()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params},
                                          dtype="float32")
    out = engine.generate(ids, max_new_tokens=5)
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), ids)
    out2 = engine.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    # cross-check one step against a fresh forward
    prefix = np.asarray(out[:, :5])
    logits = model.apply({"params": engine.params}, jnp.asarray(prefix))
    np.testing.assert_array_equal(np.asarray(out[:, 5]),
                                  np.argmax(np.asarray(logits[:, -1]), axis=-1))


def test_generate_sampling_and_eos():
    import jax

    model, params, ids = _tiny_llama()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params},
                                          dtype="float32")
    a = engine.generate(ids, max_new_tokens=6, do_sample=True, temperature=1.0,
                        rng=jax.random.PRNGKey(1))
    b = engine.generate(ids, max_new_tokens=6, do_sample=True, temperature=1.0,
                        rng=jax.random.PRNGKey(2))
    assert a.shape == (2, 10)
    assert not np.array_equal(np.asarray(a), np.asarray(b)), "different keys, different samples"

    # eos halts a sequence: whatever greedy emits first becomes the eos token
    greedy = engine.generate(ids, max_new_tokens=4)
    eos = int(np.asarray(greedy)[0, 4])
    halted = engine.generate(ids, max_new_tokens=4, eos_token_id=eos)
    assert np.asarray(halted)[0, 5] == 0, "post-eos positions must stay padding"
