"""Evoformer attention (DS4Sci).

Reference: ``deepspeed/ops/deepspeed4science/evoformer_attn.py``
(DS4Sci_EvoformerAttention:88 over the CUTLASS kernels in
``csrc/deepspeed4science/evoformer_attn/``): attention over AlphaFold2
evoformer shapes ``[*, seq, heads, dim]`` with up to two additive biases —
bias1 broadcast over rows (MSA mask, ``[B, N, 1, 1, S]``) and bias2 the pair
representation (``[B, 1, H, S, S]``) — computed in bf16/fp16.

TPU formulation: one einsum-softmax-einsum chain; XLA fuses the bias adds and
the softmax into the MXU matmuls, which is exactly what the hand-written CUDA
kernel exists to do. The scale is 1/√d applied to Q (reference _attention).
"""

from typing import List, Optional

import numpy as np


def evoformer_attention(q, k, v, bias1=None, bias2=None):
    """q/k/v: [..., S, H, D] (AlphaFold layout, heads after sequence);
    biases broadcast against [..., H, S_q, S_k]. Returns [..., S, H, D]."""
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = 1.0 / float(np.sqrt(d))
    # [..., S, H, D] -> [..., H, S, D]
    qh = jnp.swapaxes(q, -2, -3) * scale
    kh = jnp.swapaxes(k, -2, -3)
    vh = jnp.swapaxes(v, -2, -3)
    scores = jnp.einsum("...qd,...kd->...qk", qh, kh)
    if bias1 is not None:
        scores = scores + bias1
    if bias2 is not None:
        scores = scores + bias2
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("...qk,...kd->...qd", probs, vh)
    return jnp.swapaxes(out, -2, -3)


def DS4Sci_EvoformerAttention(Q, K, V, biases: Optional[List] = None):
    """Reference-named entry (evoformer_attn.py:88): validates the two-bias
    contract and dispatches to :func:`evoformer_attention`."""
    biases = [b for b in (biases or []) if b is not None]
    if len(biases) > 2:
        raise ValueError("DS4Sci_EvoformerAttention supports at most two biases")
    bias1 = biases[0] if len(biases) >= 1 else None
    bias2 = biases[1] if len(biases) == 2 else None
    return evoformer_attention(Q, K, V, bias1=bias1, bias2=bias2)
