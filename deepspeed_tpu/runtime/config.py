"""Master JSON config.

Reference: ``deepspeed/runtime/config.py`` — ``DeepSpeedConfig:696`` with ~80
accessors and the batch-size triangle validation
(``train_batch_size = micro_batch * gradient_accumulation_steps * dp_world_size``).

The JSON schema is the reference's; unknown keys are preserved (pydantic extra=allow)
so user configs written for the reference parse unchanged.
"""

import json
import os
from typing import Optional, Union

from pydantic import Field

from deepspeed_tpu.comm.config import CommsConfig
from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig
from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel, dict_raise_error_on_duplicate_keys
from deepspeed_tpu.runtime.precision_config import BF16Config, FP16Config
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    """Reference: runtime/config.py:94."""


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "adamw"
    params: dict = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: dict = {}


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference: activation_checkpointing/config.py. On TPU these map onto
    ``jax.checkpoint`` policies; partition_activations maps to sharding the
    saved residuals over the model axis."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class GradientCompressionConfig(DeepSpeedConfigModel):
    enabled: bool = False


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: dict = {}

    # -- crash consistency (ISSUE 11; checkpoint_engine/engine.py) --
    keep_last_k: int = Field(0, ge=0)
    """Retention: keep only the newest K checkpoint tags after each commit
    (0 = unlimited). The newest manifest-sealed tag is NEVER deleted, even
    when older than the window — retention cannot eat the last good one."""

    array_checksums: bool = True
    """Record per-array CRC32s in the manifest at save (a synchronous host
    snapshot per leaf — the training-state ``kv_crc32``)."""

    verify_on_load: bool = True
    """Verify the manifest (file sizes + CRC32s) before restoring; a torn or
    corrupt tag falls back loudly to the newest verified-good one."""

    verify_arrays_on_load: bool = False
    """Additionally re-checksum every restored array against the manifest's
    per-array CRC32s (catches corruption below the file layer; costs one
    host pass over the restored state)."""

    preemption_grace_s: float = Field(30.0, gt=0)
    """Budget between a preemption signal (SIGTERM) and process exit: the
    engine finishes the in-flight step, drains any async save, and writes
    the final synchronous checkpoint inside this window
    (``engine.install_preemption_handler``)."""

    gang_seal_timeout_s: float = Field(60.0, gt=0)
    """Multi-process commit atomicity: how long rank 0 waits for every
    rank's shard seal before abandoning the commit (the tag stays torn — a
    peer that died mid-save must never be sealed over)."""


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class AioConfig(DeepSpeedConfigModel):
    """Reference: csrc/aio config block."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class DeepSpeedConfig:
    """Parse + validate a config dict/path. Accessor attribute names follow the
    reference so engine code reads identically."""

    def __init__(self, config: Union[str, dict], mpu=None, mesh=None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"Expected a string path to an existing deepspeed config, got {config}")
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = config
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path or dict, got {type(config)}")

        self.mesh = mesh
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # -- parsing -------------------------------------------------------------------
    def _initialize_params(self, pd: dict):
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, False)

        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig(**pd.get(C.ZERO_OPTIMIZATION, {}))
        self.bfloat16_config = BF16Config(**pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {})))
        self.fp16_config = FP16Config(**pd.get(C.FP16, {}))
        if self.fp16_config.enabled and self.bfloat16_config.enabled:
            raise DeepSpeedConfigError("bf16 and fp16 modes cannot be simultaneously enabled")

        opt = pd.get(C.OPTIMIZER)
        self.optimizer_config = OptimizerConfig(**opt) if opt else None
        sched = pd.get(C.SCHEDULER)
        self.scheduler_config = SchedulerConfig(**sched) if sched else None
        # reference-style raw accessors
        self.optimizer_name = self.optimizer_config.type.lower() if self.optimizer_config else None
        self.optimizer_params = self.optimizer_config.params if self.optimizer_config else None
        self.optimizer_legacy_fusion = self.optimizer_config.legacy_fusion if self.optimizer_config else False
        self.scheduler_name = self.scheduler_config.type if self.scheduler_config else None
        self.scheduler_params = self.scheduler_config.params if self.scheduler_config else None

        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **pd.get("activation_checkpointing", {}))
        self.monitor_config = DeepSpeedMonitorConfig(**pd.get("monitor", pd))
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(**pd.get("flops_profiler", {}))
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngineConfig
        self.hybrid_engine_config = DeepSpeedHybridEngineConfig(**pd.get("hybrid_engine", {}))
        self.comms_config = CommsConfig(**pd.get("comms_logger", {}))
        from deepspeed_tpu.telemetry.config import TelemetryConfig
        self.telemetry_config = TelemetryConfig(**pd.get("telemetry", {}))
        self.checkpoint_config = CheckpointConfig(**pd.get(C.CHECKPOINT, {}))
        from deepspeed_tpu.runtime.sentinel import AnomalySentinelConfig
        self.anomaly_sentinel_config = AnomalySentinelConfig(**pd.get("anomaly_sentinel", {}))
        self.data_types_config = DataTypesConfig(**pd.get(C.DATA_TYPES, {}))
        self.aio_config = AioConfig(**pd.get("aio", {}))
        self.elasticity_config = ElasticityConfig(**pd.get("elasticity", {}))

        self.checkpoint_tag_validation_enabled = self.checkpoint_config.tag_validation != "Ignore"
        self.checkpoint_tag_validation_fail = self.checkpoint_config.tag_validation == "Fail"
        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage
        self.grad_accum_dtype = self.data_types_config.grad_accum_dtype

        # parallel sizes (TPU addition: declared in config instead of mpu objects)
        self.pipeline_parallel_size = pd.get(C.PIPELINE_PARALLEL_SIZE, 1)
        self.sequence_parallel_size = pd.get(C.SEQUENCE_PARALLEL_SIZE, 1)
        self.tensor_parallel_size = pd.get(C.TENSOR_PARALLEL_SIZE, 1)
        self.expert_parallel_size = pd.get(C.EXPERT_PARALLEL_SIZE, 1)

        self.pipeline = pd.get(C.PIPELINE, {})
        self.use_data_before_expert_parallel_ = pd.get(C.USE_DATA_BEFORE_EXPERT_PARALLEL,
                                                       C.USE_DATA_BEFORE_EXPERT_PARALLEL_DEFAULT)

        # aux subsystems parsed lazily by their owners
        self.compression_config = pd.get("compression_training", {})
        self.data_efficiency_config = pd.get("data_efficiency", {})
        self.autotuning_config = pd.get("autotuning", {})
        self.nebula_config = pd.get("nebula", {})
        self.curriculum_enabled_legacy = bool(pd.get("curriculum_learning", {}).get("enabled", False))
        self.curriculum_params_legacy = pd.get("curriculum_learning", {})

        # safe-mode sanity checks (SURVEY.md §5.2): debug_nans re-runs failing
        # ops un-jitted; check_finite_grads validates every backward (host sync
        # per microstep — a debug mode, like the reference's anomaly detection)
        sanity = pd.get("sanity_checks", {})
        self.debug_nans = bool(sanity.get("debug_nans", False))
        self.check_finite_grads = bool(sanity.get("check_finite_grads", False))

        self.eigenvalue_enabled = bool(pd.get("eigenvalue", {}).get("enabled", False))
        self.progressive_layer_drop = pd.get("progressive_layer_drop", {})
        self.pld_enabled = bool(self.progressive_layer_drop.get("enabled", False))

    # -- batch triangle ------------------------------------------------------------
    def _data_parallel_size(self):
        from deepspeed_tpu.utils import groups
        if self.mesh is not None:
            dp = 1
            for ax in ("data", "hpz", "expert"):
                dp *= self.mesh.shape.get(ax, 1)
            return dp
        if groups.mesh_is_initialized():
            return groups.get_data_parallel_world_size()
        try:
            import jax
            n = len(jax.devices())
        except Exception:
            n = 1
        return max(1, n // (self.tensor_parallel_size * self.pipeline_parallel_size * self.sequence_parallel_size))

    def _configure_train_batch_size(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = self._data_parallel_size()

        if all(v is not None for v in (train_batch, micro_batch, grad_acc)):
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp
            grad_acc = max(1, grad_acc)
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp
            micro_batch //= grad_acc
            micro_batch = max(1, micro_batch)
        elif micro_batch is not None and grad_acc is not None:
            train_batch = micro_batch * grad_acc * dp
        elif train_batch is not None:
            grad_acc = 1
            micro_batch = max(1, train_batch // dp)
        elif micro_batch is not None:
            train_batch = micro_batch * dp
            grad_acc = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        self.train_batch_size = train_batch
        self.train_micro_batch_size_per_gpu = micro_batch
        self.gradient_accumulation_steps = grad_acc

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = self._data_parallel_size()
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * dp, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {dp}")

    def _do_sanity_check(self):
        self._batch_assertion()
        if self.zero_config.stage > 0 and not (self.fp16_config.enabled or self.bfloat16_config.enabled):
            logger.warning("ZeRO enabled without fp16/bf16; running fp32 sharded state")

    def print_user_config(self):
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4, separators=(",", ":"))))

    def print(self, name):
        logger.info(f"{name}:")
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                logger.info(f"  {arg} {'.' * (29 - len(arg))} {getattr(self, arg)}")
        self.print_user_config()
