"""Pallas paged-attention kernel vs dense reference (reference:
tests for blocked_flash / ragged_ops kernels, run as Pallas-vs-jnp
comparisons per SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.paged_attention import paged_attention_update


def _dense_reference(q, cache, li, table, token_seq, token_pos, token_valid):
    """Per-token dense attention over the block-table history (cache already
    contains every token's K/V, including the queries' own)."""
    T, H, D = q.shape
    L, _, NB, KVH, bs, _ = cache.shape
    S, MB = table.shape
    rep = H // KVH
    out = np.zeros((T, H, D), np.float32)
    for t in range(T):
        if not token_valid[t]:
            continue
        s, pos = int(token_seq[t]), int(token_pos[t])
        n = pos + 1
        k = np.zeros((n, KVH, D), np.float32)
        v = np.zeros((n, KVH, D), np.float32)
        for p in range(n):
            bid = int(table[s, p // bs])
            k[p] = np.asarray(cache[li, 0, bid, :, p % bs], np.float32)
            v[p] = np.asarray(cache[li, 1, bid, :, p % bs], np.float32)
        for h in range(H):
            kv = h // rep
            logits = (np.asarray(q[t, h], np.float32) @ k[:, kv].T) / np.sqrt(D)
            w = np.exp(logits - logits.max())
            w /= w.sum()
            out[t, h] = w @ v[:, kv]
    return out


@pytest.mark.parametrize("kvh", [4, 2])  # MHA and GQA
def test_paged_attention_matches_dense(kvh):
    rng = np.random.default_rng(0)
    L, NB, bs, D, H = 2, 12, 16, 128, 4
    S, MB = 3, 4
    cache0 = rng.normal(size=(L, 2, NB, kvh, bs, D)).astype(np.float32)
    # per-seq block tables with distinct blocks
    perm = rng.permutation(NB)[:S * MB].reshape(S, MB)
    table = jnp.asarray(perm, jnp.int32)

    # token mix: decode token for seq0 (pos 20), mid-prefill token for seq1,
    # fresh token for seq2, one padding row
    token_seq = jnp.asarray([0, 1, 2, 3], jnp.int32)
    token_pos = jnp.asarray([20, 7, 0, 0], jnp.int32)
    token_valid = jnp.asarray([1, 1, 1, 0], jnp.int32)
    q = jnp.asarray(rng.normal(size=(4, H, D)), jnp.float32)
    k_new = jnp.asarray(rng.normal(size=(4, kvh, D)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(4, kvh, D)), jnp.float32)

    # expected cache: each valid token's K/V written at its (block, offset)
    exp_cache = cache0.copy()
    for li in range(L):
        for t in range(4):
            if not int(token_valid[t]):
                continue
            s, pos = int(token_seq[t]), int(token_pos[t])
            bid = int(perm[s, pos // bs])
            exp_cache[li, 0, bid, :, pos % bs] = np.asarray(k_new[t])
            exp_cache[li, 1, bid, :, pos % bs] = np.asarray(v_new[t])

    cache = jnp.asarray(cache0)
    for li in range(L):
        got, cache = paged_attention_update(q, k_new, v_new, cache, li, table,
                                            token_seq, token_pos, token_valid)
        want = _dense_reference(q, jnp.asarray(exp_cache), li, np.asarray(table),
                                np.asarray(token_seq), np.asarray(token_pos),
                                np.asarray(token_valid))
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cache), exp_cache, rtol=0, atol=0)

    # all-invalid batch: no output, no cache mutation
    out2, cache2 = paged_attention_update(q, k_new, v_new, jnp.asarray(exp_cache), 0,
                                          table, token_seq, token_pos,
                                          jnp.zeros(4, jnp.int32))
    assert not np.any(np.asarray(out2))
    np.testing.assert_allclose(np.asarray(cache2), exp_cache, rtol=0, atol=0)


def test_padding_tokens_never_corrupt_last_block():
    """Regression (code-review r3): -1 scatter indices WRAP in jax; padding
    tokens must route to a positive OOB sentinel or they overwrite block NB-1
    on the XLA gather path."""
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, init_params
    from deepspeed_tpu.utils import groups

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = init_params(cfg)
    mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=8),
                               max_context=128)
    eng = build_engine(params, cfg, RaggedInferenceEngineConfig(
        state_manager=mgr, kv_block_size=16, use_paged_kernel=False))
    # decode bucket pads 1 token -> 8: 7 padding tokens per forward
    eng.put([0], [np.asarray([1, 2, 3], np.int64)])
    last_block_before = np.asarray(eng._state_manager.kv_cache.cache[:, :, -1])
    eng.put([0], [np.asarray([4], np.int64)])
    last_block_after = np.asarray(eng._state_manager.kv_cache.cache[:, :, -1])
    np.testing.assert_array_equal(last_block_after, last_block_before)


def test_engine_kernel_vs_dense_path():
    """Full engine equivalence: forcing the Pallas kernel must reproduce the
    XLA gather path's logits through prefill + decode."""
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, init_params
    from deepspeed_tpu.utils import groups

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = init_params(cfg)

    def ecfg(kernel):
        mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                              size=64), max_context=512)
        return RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16,
                                           use_paged_kernel=kernel)

    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 21)

    outs = {}
    for kernel in (False, True):
        eng = build_engine(params, cfg, ecfg(kernel))
        logits = [np.asarray(eng.put([0], [prompt]))]
        for _ in range(3):
            nxt = int(np.argmax(logits[-1][0]))
            logits.append(np.asarray(eng.put([0], [np.asarray([nxt])])))
        outs[kernel] = logits
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_allclose(a, b, rtol=3e-5, atol=3e-5)


def test_decode_loop_kernel_vs_gather_path():
    """engine.decode_loop (the on-device scan) must generate identical greedy
    tokens whichever attention implementation runs inside the scan."""
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, init_params
    from deepspeed_tpu.utils import groups

    groups.initialize_mesh(force=True)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = init_params(cfg)

    def ecfg(kernel):
        mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                              size=64), max_context=512)
        return RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16,
                                           use_paged_kernel=kernel)

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, 19)
    toks = {}
    for kernel in (False, True):
        eng = build_engine(params, cfg, ecfg(kernel))
        first = int(np.argmax(np.asarray(eng.put([0], [prompt]))[0]))
        toks[kernel] = eng.decode_loop([0], [np.asarray([first])], 4)
    np.testing.assert_array_equal(toks[False], toks[True])
