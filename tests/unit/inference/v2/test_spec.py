"""Speculative-decoding mechanism units: the model-free drafter (n-gram
self-lookup + prefix-trie continuation mining), the engine's multi-token
verify feed (per-position logits, exact parity with sequential single-step
decode), and the write-then-truncate KV rollback.

The serving-layer integration (adaptive k, brownout, handoff, CPU perf
gates) lives in tests/unit/serving/test_speculative.py.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.spec import PromptLookupDrafter


# ----------------------------------------------------------------- drafter --
def test_self_lookup_longest_ngram_most_recent_match():
    d = PromptLookupDrafter(min_ngram=1, max_ngram=3)
    # suffix [1,2,3] occurred at position 0; continuation follows it
    assert d.draft([1, 2, 3, 4, 5, 1, 2, 3], 4).tolist() == [4, 5, 1, 2]
    # two earlier [1,2] occurrences: the most recent one wins
    assert d.draft([1, 2, 9, 1, 2, 7, 1, 2], 3).tolist() == [7, 1, 2]


def test_self_lookup_no_pattern_returns_empty():
    d = PromptLookupDrafter()
    assert d.draft([7, 8, 9, 10], 4).size == 0
    assert d.draft([5], 4).size == 0          # too short for any n-gram
    assert d.draft([1, 2, 3, 1, 2, 3], 0).size == 0  # k=0 never proposes


def test_self_lookup_caps_at_k():
    d = PromptLookupDrafter()
    out = d.draft([1, 2, 3, 4, 5, 6, 1, 2], 2)
    assert out.tolist() == [3, 4]


def test_drafter_validates_ngram_bounds():
    with pytest.raises(ValueError):
        PromptLookupDrafter(min_ngram=3, max_ngram=2)
    with pytest.raises(ValueError):
        PromptLookupDrafter(min_ngram=0)


# -------------------------------------------------------------- trie mining --
@pytest.fixture
def trie():
    from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   KVCacheConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.inference.v2.ragged.prefix_cache import PrefixCache
    kv = BlockedKVCache(
        KVCacheConfig(block_size=4, cache_shape=(1, 1, 4), cache_dtype="float32",
                      max_blocks_per_allocation_group=64),
        MemoryConfig(mode=AllocationMode.ALLOCATE, size=32))
    return PrefixCache(kv), kv


def test_trie_lookup_continuation_mid_and_at_block_boundary(trie):
    pc, kv = trie
    hist = np.arange(100, 114, dtype=np.int32)  # 3 full blocks of 4 committed
    pc.publish(hist, kv.reserve(3), committed_tokens=12)
    # mid-block tail: [100..105] extends the indexed path
    assert pc.lookup_continuation(np.arange(100, 106), 5).tolist() == \
        [106, 107, 108, 109, 110]
    # exactly at a block boundary
    assert pc.lookup_continuation(np.arange(100, 108), 3).tolist() == [108, 109, 110]
    # past the committed region: nothing to mine
    assert pc.lookup_continuation(np.arange(100, 112), 3).size == 0


def test_trie_lookup_divergent_history_is_empty(trie):
    pc, kv = trie
    pc.publish(np.arange(100, 112, dtype=np.int32), kv.reserve(3),
               committed_tokens=12)
    assert pc.lookup_continuation([100, 101, 102, 103, 999], 4).size == 0
    assert pc.lookup_continuation([55, 56, 57, 58, 59], 4).size == 0


def test_trie_lookup_takes_no_references_and_leaves_lru_untouched(trie):
    pc, kv = trie
    blocks = kv.reserve(2)
    pc.publish(np.arange(8, dtype=np.int32), blocks, committed_tokens=8)
    touches = {n.digest: n.last_touch for n in pc._by_digest.values()}
    refs = {int(b): kv.ref_count(int(b)) for b in blocks}
    assert pc.lookup_continuation(np.arange(5), 3).tolist() == [5, 6, 7]
    assert {n.digest: n.last_touch for n in pc._by_digest.values()} == touches
    assert {int(b): kv.ref_count(int(b)) for b in blocks} == refs


def test_drafter_prefers_trie_over_self_lookup(trie):
    pc, kv = trie
    # the history's own repetition would propose 2 again; the trie knows the
    # published continuation is 50
    hist = np.asarray([1, 2, 3, 1, 2, 3, 1, 2], np.int32)
    pc.publish(np.asarray([1, 2, 3, 1, 2, 3, 1, 2, 50, 60, 70, 80], np.int32),
               kv.reserve(3), committed_tokens=12)
    d = PromptLookupDrafter(prefix_cache=pc)
    assert d.draft(hist, 2).tolist() == [50, 60]


# -------------------------------------------------- descriptor rollback unit --
def test_sequence_descriptor_rollback_bounds():
    from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import \
        DSSequenceDescriptor
    seq = DSSequenceDescriptor(0)
    seq.pre_forward(5)
    with pytest.raises(RuntimeError):  # in-flight tokens: not rollbackable
        seq.rollback(1)
    seq.post_forward()
    seq.rollback(2)
    assert seq.seen_tokens == 3
    with pytest.raises(ValueError):
        seq.rollback(4)  # more than committed
    with pytest.raises(ValueError):
        seq.rollback(-1)
    seq.rollback(0)
    assert seq.seen_tokens == 3


# ------------------------------------------------------------- engine verify --
@pytest.fixture(scope="module")
def spec_engine_setup():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    params = {"model": model.init(jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.int32))["params"]}

    def make():
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=64),
            max_context=512)
        return build_engine(params, cfg,
                            RaggedInferenceEngineConfig(state_manager=mgr,
                                                        kv_block_size=16))
    return cfg, make


def _greedy_reference(engine, prompt, n):
    logits = engine.put([0], [prompt])
    out = [int(np.argmax(np.asarray(logits)[0]))]
    while len(out) < n:
        logits = engine.put([0], [[out[-1]]])
        out.append(int(np.argmax(np.asarray(logits)[0])))
    return out


def test_verify_fully_accepted_feed_matches_sequential_decode(spec_engine_setup):
    """One verify pass over [x0, d1..dk] with oracle drafts emits exactly the
    sequential greedy continuation — k+1 tokens per dispatch — and
    seen_tokens lands where sequential decode would put it."""
    cfg, make = spec_engine_setup
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, 24)
    ref = _greedy_reference(make(), prompt, 9)

    engine = make()
    logits = engine.put([0], [prompt])
    out = [int(np.argmax(np.asarray(logits)[0]))]
    seq = engine._state_manager.get_sequence(0)
    k = 3
    while len(out) < 9:
        drafts = ref[len(out):len(out) + k]
        feed = np.asarray([out[-1]] + drafts, np.int32)
        seen0 = seq.seen_tokens
        rows = engine.verify([0], [feed])[0]
        assert rows.shape == (feed.size, cfg.vocab_size)
        emitted = [int(np.argmax(rows[j])) for j in range(feed.size)]
        # oracle drafts: every position verifies, k+1 tokens emitted
        engine.rollback(0, 0)
        assert seq.seen_tokens == seen0 + feed.size
        out.extend(emitted)
    assert out[:9] == ref


def test_verify_rejection_rolls_back_and_continues_exactly(spec_engine_setup):
    cfg, make = spec_engine_setup
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, 24)
    ref = _greedy_reference(make(), prompt, 3)

    engine = make()
    logits = engine.put([0], [prompt])
    t1 = int(np.argmax(np.asarray(logits)[0]))
    assert t1 == ref[0]
    # garbage drafts: only the next-input position survives
    bad = np.asarray([t1, (ref[1] + 1) % cfg.vocab_size, 7, 9], np.int32)
    rows = engine.verify([0], [bad])[0]
    emitted = int(np.argmax(rows[0]))
    engine.rollback(0, bad.size - 1)  # truncate the 3 rejected positions
    seq = engine._state_manager.get_sequence(0)
    assert seq.seen_tokens == prompt.size + 1
    assert emitted == ref[1]
    # single-step decode over the rolled-back positions stays bit-identical:
    # the stale KV is overwritten by the correct token's write
    logits = engine.put([0], [[emitted]])
    assert int(np.argmax(np.asarray(logits)[0])) == ref[2]


def test_verify_batches_multiple_sequences_with_ragged_widths(spec_engine_setup):
    cfg, make = spec_engine_setup
    rng = np.random.default_rng(1)
    engine = make()
    p0 = rng.integers(0, cfg.vocab_size, 20)
    p1 = rng.integers(0, cfg.vocab_size, 12)
    logits = np.asarray(engine.put([0, 1], [p0, p1]))
    n0, n1 = (int(np.argmax(logits[0])), int(np.argmax(logits[1])))
    rows = engine.verify([0, 1], [np.asarray([n0, 1, 2], np.int32),
                                  np.asarray([n1], np.int32)])
    assert rows[0].shape == (3, cfg.vocab_size)
    assert rows[1].shape == (1, cfg.vocab_size)
    s0 = engine._state_manager.get_sequence(0)
    s1 = engine._state_manager.get_sequence(1)
    assert s0.seen_tokens == p0.size + 3
    assert s1.seen_tokens == p1.size + 1


def test_decode_loop_multi_token_feed_contract(spec_engine_setup):
    """The generalized decode_loop: multi-token entries run the greedy verify
    feed (list of per-position argmax arrays); single-token entries keep the
    scan path; misuse raises."""
    cfg, make = spec_engine_setup
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, 24)
    ref = _greedy_reference(make(), prompt, 4)

    engine = make()
    logits = engine.put([0], [prompt])
    t1 = int(np.argmax(np.asarray(logits)[0]))
    out = engine.decode_loop([0], [np.asarray([t1] + ref[1:3], np.int32)], 1)
    assert isinstance(out, list) and out[0].shape == (3,)
    assert out[0].tolist() == ref[1:4]  # oracle drafts: the greedy continuation
    engine.rollback(0, 0)

    with pytest.raises(ValueError, match="one step"):
        engine.decode_loop([0], [np.asarray([1, 2], np.int32)], 2)
    with pytest.raises(ValueError, match="greedy"):
        engine.decode_loop([0], [np.asarray([1, 2], np.int32)], 1,
                           temperature=0.5, rng=np.zeros(2))
    with pytest.raises(ValueError, match="at least one"):
        engine.decode_loop([0], [np.asarray([], np.int32)], 1)
    engine.flush(0)


def test_engine_rollback_validates_uid(spec_engine_setup):
    _, make = spec_engine_setup
    engine = make()
    with pytest.raises(ValueError, match="unknown uid"):
        engine.rollback(404, 1)
    engine.rollback(404, 0)  # 0 is a no-op even for unknown uids
