"""Adagrad.

Reference: ``deepspeed/ops/adagrad/cpu_adagrad.py`` over ``csrc/adagrad/cpu_adagrad.cpp``.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum_sq: any


class DeepSpeedCPUAdagrad(TpuOptimizer):

    name = "adagrad"
    offload = True  # reference CPU-Adagrad state always lives in host memory

    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.eps = eps

    def init(self, params):
        return AdagradState(step=jnp.zeros([], jnp.int32), sum_sq=_tree_zeros_like(params))

    def update(self, grads, state, params, lr):
        wd = self.weight_decay

        def upd(p, g, s):
            g = g.astype(p.dtype)
            if wd != 0.0:
                g = g + wd * p
            s = s + g * g
            return p - lr * g / (jnp.sqrt(s) + self.eps), s

        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        s_flat = treedef.flatten_up_to(state.sum_sq)
        out = [upd(p, g, s) for p, g, s in zip(p_flat, g_flat, s_flat)]
        return (jax.tree.unflatten(treedef, [o[0] for o in out]),
                AdagradState(step=state.step + 1, sum_sq=jax.tree.unflatten(treedef, [o[1] for o in out])))


FusedAdagrad = DeepSpeedCPUAdagrad
