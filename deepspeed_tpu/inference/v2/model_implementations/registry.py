"""Inference-v2 model policy registry.

Reference: ``deepspeed/inference/v2/engine_factory.py:66-120`` — the
``model_type``→policy dispatch table covering llama / mistral / mixtral / opt
/ falcon / phi / qwen. Registered here by model-config class AND by the HF
``model_type`` string, so both ``build_engine(params, config)`` and
``build_hf_engine(path)`` resolve through one table.
"""

from typing import Dict, Tuple, Type

_BY_CONFIG: Dict[type, type] = {}
_BY_NAME: Dict[str, Tuple[type, type]] = {}


def register_policy(model_type: str, config_cls, model_cls) -> None:
    _BY_NAME[model_type] = (config_cls, model_cls)
    # config-class dispatch falls back on model_type when one config class
    # serves several model types (llama family)
    _BY_CONFIG.setdefault(config_cls, model_cls)


def model_cls_for(model_config) -> type:
    mt = getattr(model_config, "model_type", None)
    if mt in _BY_NAME:
        return _BY_NAME[mt][1]
    for cfg_cls, model_cls in _BY_CONFIG.items():
        if isinstance(model_config, cfg_cls):
            return model_cls
    raise ValueError(f"no inference-v2 policy for {type(model_config).__name__} "
                     f"(model_type={mt!r}); known: {sorted(_BY_NAME)}")


def supported_model_types():
    return sorted(_BY_NAME)


def _register_builtin():
    from deepspeed_tpu.models.decoder import DecoderConfig
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.models.mixtral import MixtralConfig
    from deepspeed_tpu.inference.v2.model_implementations.decoder_v2 import DecoderV2Model
    from deepspeed_tpu.inference.v2.model_implementations.llama_v2 import (LlamaV2Model,
                                                                           MistralV2Model,
                                                                           Qwen2V2Model)
    from deepspeed_tpu.inference.v2.model_implementations.mixtral_v2 import MixtralV2Model

    register_policy("llama", LlamaConfig, LlamaV2Model)
    register_policy("mistral", LlamaConfig, MistralV2Model)
    register_policy("qwen2", LlamaConfig, Qwen2V2Model)
    register_policy("mixtral", MixtralConfig, MixtralV2Model)
    register_policy("opt", DecoderConfig, DecoderV2Model)
    register_policy("falcon", DecoderConfig, DecoderV2Model)
    register_policy("phi", DecoderConfig, DecoderV2Model)
    register_policy("gptj", DecoderConfig, DecoderV2Model)
    register_policy("gpt_neox", DecoderConfig, DecoderV2Model)
    # bloom (alibi) deliberately unregistered: DecoderV2Model raises with a
    # pointer at the v1 path rather than serving wrong logits


_register_builtin()
