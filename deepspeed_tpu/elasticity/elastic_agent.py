"""Elastic agent: supervise a launched gang, detect dead AND wedged ranks,
shrink-to-fit and restart on failure.

Reference: ``deepspeed/elasticity/elastic_agent.py`` (DSElasticAgent:28 — a
torch-elastic LocalElasticAgent subclass that restarts worker groups on
membership change, re-rendezvousing through the store).

TPU formulation: JAX's coordination service fixes world membership at
``jax.distributed.initialize``, so recovery is restart-shaped by construction —
exactly what this agent does. It spawns the per-process group, watches exits
AND train-loop heartbeats, and on failure tears the whole gang down
(SIGTERM → bounded grace → SIGKILL → reap), recomputes a *valid* world size
from the elasticity config (v0.1 batch math — the set of chip counts that
keep the global batch constant), and relaunches with ``DSTPU_NUM_PROCESSES``
set to it.

Gang fault tolerance (ISSUE 12):

- **Rank watchdog** — a crashed rank is caught by ``poll``; a *wedged* rank
  (alive but stuck — the hung-collective signature) is caught by its stale
  train-loop heartbeat (``elasticity/gang.py``, armed via ``gang_dir`` +
  ``hang_timeout_s``). Either way the remaining ranks are torn down instead
  of blocking forever inside a collective.
- **Preemption contract** — a rank exiting 143 (``TrainingPreempted``: its
  final checkpoint committed) DRAINS the gang — peers get SIGTERM so their
  preemption handlers run — and the agent exits 143 without counting a
  crash or restarting (the PR-11 supervisor contract at gang scope).
- **Shrink-to-fit** — ``max_crashes`` crashes inside ``crash_window_s`` at a
  given world size mean that world is not currently viable: the agent
  recomputes the next valid *smaller* world (elasticity batch math when
  enabled, world-1 otherwise) and relaunches there. Resume is the
  checkpoint reshard-on-load path — the manifest records the world shape,
  orbax reshards into the new mesh, and a global ``train_batch_size`` keeps
  the effective batch constant (micro-batch is re-derived per world). When
  ``capacity_fn`` reports recovered capacity on a later restart, the world
  grows back the same way.
- **Inspectability** — the agent maintains ``gang_state.json`` in the gang
  dir (per-rank liveness, crash history, current/valid worlds, last shrink);
  render it with ``bin/dstpu_report --gang <dir>``.
"""

import os
import signal
import subprocess
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity import gang as gang_mod
from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger

PREEMPT_EXIT_CODE = 143  # TrainingPreempted.EXIT_CODE without importing jax


class ElasticAgentError(RuntimeError):
    pass


def _metrics():
    """Gang counter/gauge families; None when telemetry is disabled."""
    from deepspeed_tpu import telemetry
    if not telemetry.is_active():
        return None
    reg = telemetry.get_registry()
    return {
        "crashes": reg.counter("train_gang_crashes_total",
                               "Rank crashes observed by the gang watchdog"),
        "hangs": reg.counter("train_gang_hangs_total",
                             "Wedged ranks detected via stale heartbeat"),
        "teardowns": reg.counter("train_gang_teardowns_total",
                                 "Whole-gang teardowns (SIGTERM-grace-SIGKILL)"),
        "relaunches": reg.counter("train_gang_relaunches_total",
                                  "Gang relaunches by the elastic agent"),
        "shrinks": reg.counter("train_gang_shrinks_total",
                               "Crash-budget shrinks to a smaller world size"),
        "world": reg.gauge("train_gang_world_size",
                           "Current gang world size (processes)"),
    }


def _count(name, value=None):
    m = _metrics()
    if m is None:
        return
    if value is not None:
        m[name].set(value)
    else:
        m[name].inc()


class DSElasticAgent:

    def __init__(self, cmd: List[str], num_processes: int, ds_config: Optional[dict] = None,
                 env: Optional[Dict[str, str]] = None, max_restarts: int = 3,
                 monitor_interval: float = 0.5,
                 capacity_fn: Optional[Callable[[], int]] = None,
                 restart_backoff_base_s: float = 0.0,
                 restart_backoff_cap_s: float = 30.0,
                 restart_jitter_frac: float = 0.1, seed: int = 0,
                 gang_dir: Optional[str] = None,
                 hang_timeout_s: Optional[float] = None,
                 boot_timeout_s: Optional[float] = None,
                 term_grace_s: float = 5.0,
                 max_crashes: int = 0, crash_window_s: float = 300.0):
        """``cmd`` is launched once per process with DSTPU_NUM_PROCESSES /
        DSTPU_PROCESS_ID exported (the contract ``comm.init_distributed``
        reads). ``capacity_fn`` reports how many processes can be spawned for
        the next attempt (defaults to the last world size — a failed process is
        assumed recoverable; pass a probe for real node-loss handling).
        ``restart_backoff_base_s`` > 0 spaces restarts with the fleet's shared
        bounded-jitter ``backoff_delay`` policy (0 = immediate, the legacy
        behavior).

        ``gang_dir`` arms the rank watchdog: it is exported as
        ``DSTPU_GANG_DIR`` (ranks heartbeat from the train loop) and holds
        ``gang_state.json``. ``hang_timeout_s`` is the staleness deadline — a
        rank that has beaten at least once this life and then goes quiet for
        longer, while its process is alive, is *wedged* and the gang is torn
        down (set it above the worst-case step+save+compile time).
        ``boot_timeout_s`` bounds the pre-first-heartbeat window: a launched
        rank that never beats within it (e.g. the whole gang wedged inside
        ``jax.distributed.initialize``) counts as hung — arming the watchdog
        asserts the children DO heartbeat (the engine does automatically when
        ``DSTPU_GANG_DIR`` is exported). Defaults to
        ``max(10 × hang_timeout_s, 120)`` when the watchdog is armed.
        ``max_crashes`` > 0 arms the shrink budget: that many crashes inside
        ``crash_window_s`` at one world size shrink the next launch to the
        largest valid world strictly below it."""
        self.cmd = list(cmd)
        self.num_processes = int(num_processes)
        self.ds_config = ds_config or {}
        self.env = dict(env if env is not None else os.environ)
        self.max_restarts = int(max_restarts)
        self.monitor_interval = monitor_interval
        self.capacity_fn = capacity_fn
        self.restart_count = 0
        self.restart_backoff_base_s = float(restart_backoff_base_s)
        self.restart_backoff_cap_s = float(restart_backoff_cap_s)
        self.restart_jitter_frac = float(restart_jitter_frac)
        self.gang_dir = gang_dir
        self.hang_timeout_s = None if hang_timeout_s is None else float(hang_timeout_s)
        if boot_timeout_s is not None:
            self.boot_timeout_s = float(boot_timeout_s)
        else:
            self.boot_timeout_s = None if self.hang_timeout_s is None \
                else max(10.0 * self.hang_timeout_s, 120.0)
        self._spawned_at = 0.0
        self.term_grace_s = float(term_grace_s)
        self.max_crashes = int(max_crashes)
        self.crash_window_s = float(crash_window_s)
        self.world = self.num_processes
        self.crashes: deque = deque()  # (monotonic, world) window-pruned
        self.events: List[dict] = []   # crash/hang/preempt/shrink history
        self.last_shrink: Optional[dict] = None
        import random as _random
        self._backoff_rng = _random.Random(f"{seed}:elastic_agent")
        self._owns_gang_dir = False
        if self.gang_dir is None and (self.hang_timeout_s is not None
                                      or self.boot_timeout_s is not None):
            import tempfile
            self.gang_dir = tempfile.mkdtemp(prefix="dstpu_gang_")
            self._owns_gang_dir = True  # reaped on clean exit (run())
        # per-agent job nonce: scopes monitored_barrier's file rendezvous so
        # a later gang on the same coordinator never matches our leftovers
        self._job_id = f"agent.{os.getpid()}.{time.time():.0f}"

    # -- world-size policy -------------------------------------------------------
    def next_world_size(self, capacity: int) -> int:
        """Largest elasticity-valid world size ≤ capacity (or capacity itself
        when elasticity is off)."""
        if not self.ds_config.get("elasticity", {}).get("enabled", False):
            if capacity < 1:
                raise ElasticAgentError("no capacity left to restart into")
            return capacity
        _, valid = compute_elastic_config(self.ds_config)
        fitting = [n for n in valid if n <= capacity]
        if not fitting:
            raise ElasticAgentError(
                f"no elasticity-valid world size fits the surviving capacity {capacity} "
                f"(valid: {sorted(valid)[:10]}...)")
        return max(fitting)

    def valid_world_sizes(self) -> List[int]:
        """Every world size a relaunch may land on, for the gang state
        document: the elastic set when elasticity is on (grow-back via
        ``capacity_fn`` may exceed the initial world), [1..initial] when
        off (shrink-only: ``next_world_size`` returns the capacity itself)."""
        if not self.ds_config.get("elasticity", {}).get("enabled", False):
            return list(range(1, self.num_processes + 1))
        _, valid = compute_elastic_config(self.ds_config)
        return sorted(valid)

    # -- process control ---------------------------------------------------------
    def _spawn(self, world_size: int) -> List[subprocess.Popen]:
        if self.gang_dir is not None:
            # one life's staleness must never indict the next life's ranks,
            # and one life's barrier rendezvous files must never satisfy the
            # next life's barriers
            gang_mod.clear_heartbeats(self.gang_dir)
            import shutil
            shutil.rmtree(os.path.join(self.gang_dir, "barriers"),
                          ignore_errors=True)
        procs = []
        for rank in range(world_size):
            env = dict(self.env)
            env["DSTPU_NUM_PROCESSES"] = str(world_size)
            env["DSTPU_PROCESS_ID"] = str(rank)
            env["DSTPU_JOB_ID"] = self._job_id
            env["DSTPU_ELASTIC_RESTART"] = str(self.restart_count)
            # the training chaos injector keys its one-shot kill/sigterm
            # points on this (runtime/faults.first_life) — without it a
            # deterministic kill replays on every relaunch and crash-loops
            env["DSTPU_RESTART_COUNT"] = str(self.restart_count)
            if self.gang_dir is not None:
                env["DSTPU_GANG_DIR"] = self.gang_dir
            procs.append(subprocess.Popen(self.cmd, env=env))
        self._spawned_at = time.monotonic()
        _count("world", world_size)
        return procs

    def _kill(self, procs: List[subprocess.Popen]):
        """Whole-gang teardown with escalation: SIGTERM every survivor (their
        preemption handlers may commit a final checkpoint), give the gang a
        bounded grace, SIGKILL the stragglers, then REAP everything — no
        zombie outlives the teardown."""
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.term_grace_s
        for p in procs:
            try:
                p.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        for p in procs:  # reap the SIGKILLed stragglers too
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:  # pragma: no cover - kernel-stuck
                logger.error(f"elastic agent: pid {p.pid} unreapable after SIGKILL")
        _count("teardowns")

    def _stale_ranks(self, procs: List[subprocess.Popen]):
        """Wedged-rank detection, two windows: (a) a rank that has beaten
        this life (``_spawn`` cleared the previous life's files) and then
        went quiet past ``hang_timeout_s``; (b) a rank that NEVER beat within
        ``boot_timeout_s`` of launch — the gang wedged at boot (e.g. inside
        the coordination-service rendezvous), which exit polling and
        staleness can't see. Returns ``(ranks, detail)`` or ``([], None)``."""
        if self.gang_dir is None or (self.hang_timeout_s is None
                                     and self.boot_timeout_s is None):
            return [], None
        beats = gang_mod.read_heartbeats(self.gang_dir)
        if self.hang_timeout_s is not None:
            stale = [rank for rank, doc in sorted(beats.items())
                     if rank < len(procs) and procs[rank].poll() is None
                     and doc["age_s"] > self.hang_timeout_s]
            if stale:
                return stale, (f"rank(s) {stale} wedged: heartbeat stale "
                               f"> {self.hang_timeout_s:.1f}s with process alive")
        if self.boot_timeout_s is not None and \
                time.monotonic() - self._spawned_at > self.boot_timeout_s:
            unborn = [rank for rank in range(len(procs))
                      if rank not in beats and procs[rank].poll() is None]
            if unborn:
                return unborn, (f"rank(s) {unborn} wedged at boot: no "
                                f"heartbeat within {self.boot_timeout_s:.1f}s "
                                f"of launch")
        return [], None

    def _monitor(self, procs: List[subprocess.Popen]):
        """Watch one gang life. Returns ``("done", None)``, ``("preempt",
        rc)``, ``("crash", detail)`` or ``("hang", detail)``; every non-done
        outcome has already torn the whole gang down."""
        while True:
            codes = [p.poll() for p in procs]
            preempted = [r for r, c in enumerate(codes) if c == PREEMPT_EXIT_CODE]
            if preempted:
                # PR-11 preemption contract at gang scope: the rank committed
                # its final checkpoint and exited 143 — drain the peers
                # (SIGTERM runs their preemption handlers) without counting
                # a crash, and surface 143 to the caller
                logger.warning(f"elastic agent: rank(s) {preempted} exited "
                               f"preempted (143); draining the gang")
                self._kill(procs)
                return "preempt", PREEMPT_EXIT_CODE
            crashed = [(r, c) for r, c in enumerate(codes)
                       if c not in (None, 0, PREEMPT_EXIT_CODE)]
            if crashed:
                self._kill(procs)
                _count("crashes")
                return "crash", (f"rank(s) {[r for r, _ in crashed]} exited "
                                 f"{[c for _, c in crashed]}")
            if all(c == 0 for c in codes):
                return "done", None
            stale, detail = self._stale_ranks(procs)
            if stale:
                # the collective-hang signature: a rank (or the peers a dead/
                # stuck one wedged inside a collective) is alive but has made
                # no train-loop progress past the deadline
                self._kill(procs)
                _count("hangs")
                return "hang", detail
            time.sleep(self.monitor_interval)

    # -- state document ----------------------------------------------------------
    def _write_state(self, phase: str, procs: Optional[List[subprocess.Popen]] = None):
        if self.gang_dir is None:
            return
        ranks = {}
        beats = gang_mod.read_heartbeats(self.gang_dir)
        for rank in range(self.world):
            doc = {"alive": None, "exit_code": None}
            if procs is not None and rank < len(procs):
                rc = procs[rank].poll()
                doc = {"alive": rc is None, "exit_code": rc,
                       "pid": procs[rank].pid}
            doc["heartbeat"] = beats.get(rank)
            ranks[str(rank)] = doc
        try:
            gang_mod.write_gang_state(self.gang_dir, {
                "phase": phase,
                "world": self.world,
                "initial_world": self.num_processes,
                "valid_worlds": self.valid_world_sizes(),
                "restart_count": self.restart_count,
                "max_restarts": self.max_restarts,
                "crashes_in_window": len(self.crashes),
                "max_crashes": self.max_crashes,
                "crash_window_s": self.crash_window_s,
                "hang_timeout_s": self.hang_timeout_s,
                "last_shrink": self.last_shrink,
                "events": self.events[-50:],
                "ranks": ranks,
            })
        except OSError:  # state reporting must never kill supervision
            pass

    def _record_event(self, kind: str, detail) -> None:
        self.events.append({"kind": kind, "world": self.world,
                            "life": self.restart_count,
                            "detail": detail, "unix": time.time()})

    # -- main loop ---------------------------------------------------------------
    def _next_world_after_failure(self) -> int:
        """Crash-budget shrink-to-fit: inside the budget, relaunch at the
        capacity the probe reports (same world by default — and a recovered
        capacity GROWS the world back); budget exhausted at this world means
        it is not viable — shrink to the largest valid world strictly below
        it and start a fresh window there."""
        now = time.monotonic()
        while self.crashes and now - self.crashes[0][0] > self.crash_window_s:
            self.crashes.popleft()
        capacity = self.capacity_fn() if self.capacity_fn is not None else self.world
        budget_spent = self.max_crashes > 0 and len(
            [1 for _, w in self.crashes if w == self.world]) >= self.max_crashes
        if budget_spent:
            if self.world <= 1:
                raise ElasticAgentError(
                    f"crash budget exhausted at world_size=1 "
                    f"({self.max_crashes} crashes in {self.crash_window_s:.0f}s) "
                    f"— no smaller world to shrink to")
            capacity = min(capacity, self.world - 1)
            new_world = self.next_world_size(capacity)
            self.last_shrink = {"from": self.world, "to": new_world,
                                "crashes": len(self.crashes),
                                "life": self.restart_count, "unix": time.time()}
            self._record_event("shrink", self.last_shrink)
            self.crashes.clear()  # fresh budget at the new world
            _count("shrinks")
            logger.warning(f"elastic agent: crash budget exhausted at "
                           f"world_size={self.world} ({self.max_crashes} in "
                           f"{self.crash_window_s:.0f}s); shrinking to "
                           f"{new_world} (resume = checkpoint reshard-on-load)")
            return new_world
        return self.next_world_size(capacity)

    def run(self) -> int:
        self.world = self.num_processes
        while True:
            logger.info(f"elastic agent: launching world_size={self.world} "
                        f"(attempt {self.restart_count + 1})")
            procs = self._spawn(self.world)
            self._write_state("running", procs)
            outcome, detail = self._monitor(procs)
            if outcome == "done":
                self._record_event("done", None)
                self._write_state("done", procs)
                logger.info("elastic agent: job finished cleanly")
                if self._owns_gang_dir:
                    # auto-created tempdir: nothing left to inspect after a
                    # clean finish (failures keep it for dstpu_report --gang)
                    import shutil
                    shutil.rmtree(self.gang_dir, ignore_errors=True)
                return 0
            if outcome == "preempt":
                self._record_event("preempt", detail)
                self._write_state("preempted", procs)
                logger.warning("elastic agent: gang preempted (final "
                               "checkpoint committed); exiting 143 without "
                               "counting a crash")
                return PREEMPT_EXIT_CODE
            # crash or hang: both consume the restart + crash budgets
            self._record_event(outcome, detail)
            self.crashes.append((time.monotonic(), self.world))
            logger.warning(f"elastic agent: gang failure ({outcome}): {detail}")
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                self._write_state("failed", procs)
                raise ElasticAgentError(f"job failed after {self.max_restarts} restarts")
            try:
                self.world = self._next_world_after_failure()
            except ElasticAgentError:
                # no world to restart into (budget spent at world=1, or no
                # valid size fits the capacity): terminal — the state doc
                # must say so, not read as a live gang forever
                self._write_state("failed", procs)
                raise
            _count("relaunches")
            delay = 0.0
            if self.restart_backoff_base_s > 0.0:
                # the fleet's one backoff formula (fleet/breaker.backoff_delay):
                # exponential, capped, bounded jitter, deterministic in seed
                from deepspeed_tpu.fleet.breaker import backoff_delay
                delay = backoff_delay(self.restart_count - 1,
                                      self.restart_backoff_base_s,
                                      self.restart_backoff_cap_s,
                                      self.restart_jitter_frac,
                                      self._backoff_rng.random())
            logger.warning(f"elastic agent: restarting with "
                           f"world_size={self.world}"
                           f"{f', backoff {delay:.2f}s' if delay else ''}")
            self._write_state("backoff", procs)
            if delay:
                time.sleep(delay)
