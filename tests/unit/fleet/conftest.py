import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet import FleetConfig, ReplicaManager
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
from deepspeed_tpu.serving import ServingConfig


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Telemetry state is process-global (same contract as tests/unit/serving)."""
    telemetry.shutdown()
    telemetry.state.registry = None
    yield
    telemetry.shutdown()
    telemetry.state.registry = None


@pytest.fixture(scope="package")
def llama_setup():
    # package scope: one model init for the whole fleet suite, not one per
    # test file — the params are read-only inputs to every engine build
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = {"model": model.init(jax.random.PRNGKey(0), ids)["params"]}
    return cfg, model, params


@pytest.fixture
def make_engine(llama_setup):
    """Engine factory with identical KV geometry across calls (the handoff
    transport's requirement); every engine is closed at teardown unless a
    replica drain already closed it."""
    cfg, _, params = llama_setup
    engines = []

    def _make(num_blocks=64, block_size=16, **mgr_kw):
        mgr_kw.setdefault("max_context", 512)
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=num_blocks),
            **mgr_kw)
        engine = build_engine(params, cfg,
                              RaggedInferenceEngineConfig(state_manager=mgr,
                                                          kv_block_size=block_size))
        engines.append(engine)
        return engine

    yield _make
    for engine in engines:
        engine.close()


@pytest.fixture
def make_fleet(make_engine):
    """Fleet factory: a ReplicaManager over the shared engine factory, with
    probe caching off (probe_ttl_s=0: every dispatch sees fresh state — the
    deterministic formulation for tests). Managers are closed at teardown."""
    managers = []

    def _make(roles=("mixed",), config=None, serving_config=None, **engine_kw):
        manager = ReplicaManager(
            engine_factory=lambda: make_engine(**engine_kw),
            config=config or FleetConfig(probe_ttl_s=0.0, drain_timeout_s=10.0),
            serving_config=serving_config or ServingConfig())
        for role in roles:
            manager.add_local(role=role)
        managers.append(manager)
        return manager

    yield _make
    for manager in managers:
        manager.close()
