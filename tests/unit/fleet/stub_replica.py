"""Stdlib-only stub replica server for supervisor process tests.

Speaks just enough of the ``serving/server.py`` surface for the supervisor's
readiness gate and hang detection (``GET /healthz``, ``GET /v1/stats``) and
honors the ``--port-file`` announcement protocol — without importing jax, so
a spawn costs ~100ms and the tier-1 suite can exercise real process
supervision (exit detection, SIGKILL, restart, crash-loop quarantine).

Modes:

- ``serve`` (default) — healthy forever;
- ``exit`` — exit(1) immediately (before announcing): the launch-failure path;
- ``exit-after-ready`` — announce, serve healthy, then exit(1) after
  ``--ttl-s``: the crash-after-ready path;
- ``never-ready`` — announce and serve, but ``/healthz`` stays ``starting``:
  the readiness-timeout path;
- ``hang-after-ready`` — healthy for ``--ttl-s``, then every request blocks:
  the hang-detection path.
"""

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--port-file", required=True)
    p.add_argument("--mode", default="serve",
                   choices=("serve", "exit", "exit-after-ready", "never-ready",
                            "hang-after-ready"))
    p.add_argument("--ttl-s", type=float, default=0.5)
    args = p.parse_args(argv)

    if args.mode == "exit":
        sys.exit(1)

    t0 = time.monotonic()

    class Handler(BaseHTTPRequestHandler):

        def _send(self, doc):
            data = json.dumps(doc).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if args.mode == "hang-after-ready" and \
                    time.monotonic() - t0 > args.ttl_s:
                time.sleep(3600)  # wedged, not dead
            if self.path.startswith("/healthz"):
                status = "starting" if args.mode == "never-ready" else "ok"
                self._send({"status": status})
            elif self.path.startswith("/v1/stats"):
                self._send({"queue_depth": 0, "active": {"total": 0},
                            "counters": {"heartbeats": 0},
                            "engine": {"free_blocks": 1, "capacity_blocks": 1},
                            "draining": False})
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, fmt, *a):
            ...

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True).start()
    host, port = server.server_address
    tmp = f"{args.port_file}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
    os.replace(tmp, args.port_file)

    if args.mode == "exit-after-ready":
        time.sleep(args.ttl_s)
        sys.exit(1)
    while True:
        time.sleep(1.0)


if __name__ == "__main__":
    main()
