"""Standalone activation-checkpointing API + safe-mode sanity checks
(reference runtime/activation_checkpointing/checkpointing.py, SURVEY.md §5.2).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches


@pytest.fixture(autouse=True)
def _reset_ckpt_config():
    checkpointing.reset()
    yield
    checkpointing.reset()


def _fn(w, x):
    h = jnp.tanh(x @ w)
    return jnp.sum(jnp.tanh(h @ w))


def test_checkpoint_preserves_value_and_grad():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)

    checkpointing.configure(deepspeed_config={"train_micro_batch_size_per_gpu": 1})
    assert checkpointing.is_configured()

    direct_v, direct_g = jax.value_and_grad(_fn)(w, x)
    ck_v, ck_g = jax.value_and_grad(lambda w, x: checkpointing.checkpoint(_fn, w, x))(w, x)
    # remat re-executes the forward under a different fusion plan, so the
    # recomputed activations can differ from the saved ones by a few fp32
    # ulps (observed 2e-6 relative across XLA releases) — value parity, not
    # bit parity, is the contract
    np.testing.assert_allclose(np.asarray(ck_v), np.asarray(direct_v), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ck_g), np.asarray(direct_g), rtol=1e-5)


def test_checkpoint_reduces_saved_residuals():
    """nothing_saveable must leave no tanh residuals in the jaxpr — remat for
    real, not a passthrough."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    checkpointing.configure(deepspeed_config={"train_micro_batch_size_per_gpu": 1})

    plain = str(jax.make_jaxpr(jax.grad(_fn))(w, x))
    remat = str(jax.make_jaxpr(jax.grad(lambda w, x: checkpointing.checkpoint(_fn, w, x)))(w, x))
    assert "remat" not in plain
    assert "remat" in remat, "checkpointed backward must carry the remat primitive"


def test_checkpoint_partition_activations_policy():
    checkpointing.configure(deepspeed_config={
        "train_micro_batch_size_per_gpu": 1,
        "activation_checkpointing": {"partition_activations": True}})
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
    v, g = jax.value_and_grad(lambda w, x: checkpointing.checkpoint(_fn, w, x))(w, x)
    dv, dg = jax.value_and_grad(_fn)(w, x)
    # same ulp headroom as above: remat recomputation is value-, not
    # bit-identical across XLA fusion plans
    np.testing.assert_allclose(np.asarray(g), np.asarray(dg), rtol=1e-5)


def test_configure_flag_overrides():
    checkpointing.configure(deepspeed_config={"train_micro_batch_size_per_gpu": 1},
                            partition_activations=True, checkpoint_in_cpu=True,
                            num_checkpoints=2)
    from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import _CONFIG
    assert _CONFIG.partition_activations and _CONFIG.cpu_checkpointing
    assert _CONFIG.number_checkpoints == 2


# ------------------------------------------------------------------- safe mode --
def test_find_nonfinite_names_leaves():
    from deepspeed_tpu.utils.debug import assert_all_finite, find_nonfinite

    tree = {"a": jnp.ones((3, )), "b": {"c": jnp.asarray([1.0, np.nan, np.inf])}}
    bad = find_nonfinite(tree, "grads")
    assert len(bad) == 1 and "'b'" in bad[0] and "2/3" in bad[0]
    with pytest.raises(FloatingPointError):
        assert_all_finite(tree)
    assert_all_finite({"a": jnp.ones((3, ))})  # clean tree passes


def test_engine_check_finite_grads_raises():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=16, batch_size=16)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
           "sanity_checks": {"check_finite_grads": True}}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)
    b = random_batches(1, 16, 16)[0]
    loss = eng.forward(b)
    eng.backward(loss)  # clean grads pass
    eng.step()
    bad = jax.tree.map(lambda l: np.where(np.isfinite(l), np.inf, l).astype(l.dtype), b)
    loss = eng.forward(bad)
    with pytest.raises(FloatingPointError):
        eng.backward(loss)
