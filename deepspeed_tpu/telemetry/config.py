"""Telemetry config block (``"telemetry": {...}`` in the master JSON config).

New subsystem (no single reference analog): unifies the knobs that the
reference scatters over ``comms_logger`` / ``monitor`` / ``flops_profiler``
into one switch for the metrics registry, span recorder and HTTP exporter.
"""

from typing import Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class TelemetryHTTPConfig(DeepSpeedConfigModel):
    """Serving endpoint for scrapes: ``/metrics`` (Prometheus text),
    ``/healthz`` (liveness) and ``/trace`` (Chrome-trace JSON)."""

    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0
    """0 = ephemeral; the bound port is logged and available on the session."""


class TelemetryConfig(DeepSpeedConfigModel):
    enabled: bool = False

    jsonl_path: Optional[str] = None
    """Append-mode JSONL event sink (one JSON object per line; see README
    Observability for the schema). None = no file sink."""

    trace_path: Optional[str] = None
    """Chrome-trace (``chrome://tracing`` / Perfetto) JSON written on
    ``flush()`` / session close. None = spans stay scrape-only (``/trace``)."""

    max_spans: int = 65536
    """Span ring-buffer capacity; oldest spans are dropped beyond this."""

    all_ranks: bool = False
    """Metrics/spans always record on every rank; file sinks and the HTTP
    endpoint open on process 0 only unless this is set (give each rank its
    own paths/ephemeral port when you do)."""

    http: TelemetryHTTPConfig = {}
