"""bin/dstpu_loadgen against a live ServingServer (CLI smoke, in the style of
tests/unit/launcher/test_cli_tools.py)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.serving import (PrefixCacheConfig, ServingConfig,
                                   ServingScheduler, ServingServer,
                                   SpeculativeConfig)

BIN = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))), "bin")


@pytest.fixture
def server(make_engine):
    srv = ServingServer(ServingScheduler(make_engine(), ServingConfig())).start()
    yield srv
    srv.stop(drain=False)


def _loadgen(*args, timeout=300):
    return subprocess.run([sys.executable, os.path.join(BIN, "dstpu_loadgen"), *args],
                          capture_output=True, text=True, timeout=timeout)


def test_loadgen_closed_loop_streaming(server, llama_setup):
    cfg, _, _ = llama_setup
    r = _loadgen("--url", server.url, "--requests", "4", "--mode", "closed",
                 "--concurrency", "2", "--prompt-len", "8",
                 "--max-new-tokens", "4", "--vocab-size", str(cfg.vocab_size))
    assert r.returncode == 0, r.stderr[-800:]
    assert "ok=4 err=0" in r.stdout
    for metric in ("throughput", "ttft", "itl", "e2e"):
        assert metric in r.stdout, r.stdout
    assert server.scheduler.stats()["counters"]["completed"] == 4


def test_loadgen_open_loop_lognormal(server, llama_setup):
    cfg, _, _ = llama_setup
    r = _loadgen("--url", server.url, "--requests", "3", "--mode", "open",
                 "--rate", "50", "--prompt-len", "6", "--prompt-len-dist",
                 "lognormal", "--max-new-tokens", "3",
                 "--vocab-size", str(cfg.vocab_size))
    assert r.returncode == 0, r.stderr[-800:]
    assert "ok=3 err=0" in r.stdout


def test_loadgen_shared_prefix_reports_cache_effectiveness(make_engine, llama_setup):
    """--shared-prefix against a cache-enabled server: sequential requests over
    2 prompt groups hit after each group's first miss; the report carries hit
    rate, prefill-tokens-saved, and the hit/miss TTFT split."""
    cfg, _, _ = llama_setup
    sched = ServingScheduler(
        make_engine(),
        ServingConfig(prefix_cache=PrefixCacheConfig(enabled=True)))
    srv = ServingServer(sched).start()
    try:
        r = _loadgen("--url", srv.url, "--requests", "8", "--mode", "closed",
                     "--concurrency", "1", "--shared-prefix", "32:2",
                     "--prompt-len", "8", "--max-new-tokens", "4",
                     "--vocab-size", str(cfg.vocab_size))
        assert r.returncode == 0, r.stderr[-800:]
        assert "ok=8 err=0" in r.stdout
        assert "# prefix cache: hits=" in r.stdout, r.stdout
        assert "ttft (hit)" in r.stdout and "ttft (miss)" in r.stdout, r.stdout
        # 2 groups -> at most 2 cold publishers; everything after hits, so a
        # 32-token prefix over 40-token prompts saves >= 50% of prefill
        hits = int(r.stdout.split("# prefix cache: hits=")[1].split("/")[0])
        assert hits >= 6
        saved = int(r.stdout.split("prefill_tokens_saved=")[1].split("/")[0])
        assert saved >= hits * 31
        pc = sched.stats()["prefix_cache"]
        assert pc["hits"] == hits and pc["lookups"] == 8
    finally:
        srv.stop(drain=False)


def test_loadgen_spec_demo_reports_acceptance(make_engine, llama_setup):
    """--spec-demo against a speculation-enabled server: each group's first
    request publishes the trie, repeats decode off mined drafts; the report
    carries acceptance rate, tokens/step, and the first/repeat ITL split."""
    cfg, _, _ = llama_setup
    sched = ServingScheduler(
        make_engine(block_size=4),
        ServingConfig(prefix_cache=PrefixCacheConfig(enabled=True),
                      speculative=SpeculativeConfig(enabled=True,
                                                    max_draft_tokens=4)))
    srv = ServingServer(sched).start()
    try:
        r = _loadgen("--url", srv.url, "--requests", "6", "--mode", "closed",
                     "--concurrency", "1", "--spec-demo", "16:2",
                     "--max-new-tokens", "10",
                     "--vocab-size", str(cfg.vocab_size))
        assert r.returncode == 0, r.stderr[-800:]
        assert "ok=6 err=0" in r.stdout
        assert "# speculative: accept_rate=" in r.stdout, r.stdout
        accepted = int(r.stdout.split("accept_rate=")[1]
                       .split("(")[1].split("/")[0])
        assert accepted > 0  # repeats really decoded off accepted drafts
        spec = sched.stats()["speculative"]
        assert spec["accepted"] == accepted
        assert spec["verify_steps"] > 0
    finally:
        srv.stop(drain=False)


def test_loadgen_shared_prefix_arg_validation():
    r = _loadgen("--url", "http://127.0.0.1:1", "--requests", "1",
                 "--shared-prefix", "0:2")
    assert r.returncode == 2
    assert "--shared-prefix takes" in r.stderr


def test_loadgen_reports_connection_errors():
    r = _loadgen("--url", "http://127.0.0.1:1", "--requests", "2",
                 "--concurrency", "1", "--timeout", "2")
    assert r.returncode == 1
    assert "err=2" in r.stdout
