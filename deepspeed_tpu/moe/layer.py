"""MoE layer (flax).

Reference: ``deepspeed/moe/layer.py`` (MoE:16 — wrapper creating EP groups and
wiring TopKGate + MOELayer + local Experts) and ``deepspeed/moe/experts.py``.

The flax module owns the gate weight and a *stacked* expert FFN parameter bank of
shape [num_local_experts * ep, ...] sharded over the expert mesh axis; expert
compute is a vmap over that leading dim, so each chip runs only its local experts
(the reference's ``Experts:10`` ModuleList of per-rank experts).
"""

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import TopKGate, moe_dispatch_combine
from deepspeed_tpu.utils import groups


def gated_expert_act(h, activation):
    """SwiGLU-family expert activation over a fused [.., 2H] projection laid out
    as (gate | up) halves — Mixtral's w1/w3 fused into one bank."""
    gate, up = jnp.split(h, 2, axis=-1)
    return activation(gate) * up


class ExpertFFN(nn.Module):
    """Stacked expert MLPs: params have a leading expert dim (sharded over EP).
    ``gated=True`` uses a fused (gate|up) wi bank of width 2*d_hidden (Mixtral's
    SwiGLU experts, HF w1/w3); otherwise a plain 2-matrix MLP."""
    num_experts: int
    d_model: int
    d_hidden: int
    activation: Callable = nn.gelu
    dtype: jnp.dtype = jnp.float32
    gated: bool = False

    @nn.compact
    def __call__(self, x):  # x: [E, C, M]
        wi_h = 2 * self.d_hidden if self.gated else self.d_hidden
        wi = self.param("wi", nn.initializers.lecun_normal(), (self.num_experts, self.d_model, wi_h),
                        self.dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(), (self.num_experts, self.d_hidden, self.d_model),
                        self.dtype)
        h = jnp.einsum("ecm,emh->ech", x, wi.astype(x.dtype))
        h = gated_expert_act(h, self.activation) if self.gated else self.activation(h)
        return jnp.einsum("ech,ehm->ecm", h, wo.astype(x.dtype))


class MoE(nn.Module):
    """Reference MoE:16 API surface as a flax module.

    Call with x: [..., M] (flattened to tokens internally); returns
    (output, l_aux, exp_counts) exactly like the reference forward.
    """
    hidden_size: int
    num_experts: int = 1
    ffn_hidden_size: Optional[int] = None
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    use_residual: bool = False
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    activation: Callable = nn.gelu
    dtype: jnp.dtype = jnp.float32
    gated: bool = False

    @nn.compact
    def __call__(self, x, used_token=None, training: bool = True):
        M = self.hidden_size
        orig_shape = x.shape
        tokens = x.reshape(-1, M)

        gate = TopKGate(M, self.num_experts, self.k, self.capacity_factor, self.eval_capacity_factor,
                        self.min_capacity, self.noisy_gate_policy, self.drop_tokens, self.use_rts)
        wg = self.param("gate", nn.initializers.lecun_normal(), (M, self.num_experts), jnp.float32)
        rng = self.make_rng("gating") if self.has_rng("gating") else None
        l_aux, combine, dispatch, exp_counts = gate(wg, tokens, rng=rng, used_token=used_token, training=training)

        experts = ExpertFFN(self.num_experts, M, self.ffn_hidden_size or 4 * M, self.activation, self.dtype,
                            gated=self.gated)
        out = moe_dispatch_combine(tokens, combine, dispatch, experts)

        if self.use_residual:
            # PR-MoE (reference layer.py use_residual): dense MLP + learned mix
            mlp_out = nn.Dense(self.ffn_hidden_size or 4 * M, dtype=x.dtype)(tokens)
            mlp_out = self.activation(mlp_out)
            mlp_out = nn.Dense(M, dtype=x.dtype)(mlp_out)
            coef = nn.Dense(2, dtype=x.dtype)(tokens)
            coef = jax.nn.softmax(coef, axis=-1)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]

        return out.reshape(orig_shape), l_aux, exp_counts


def expert_param_specs(params, expert_axis=groups.EXPERT_AXIS):
    """PartitionSpec tree for an MoE module's params: expert banks sharded on their
    leading (expert) dim, everything else replicated. Feed to
    ``deepspeed_tpu.initialize(param_specs=...)`` (the reference marks expert params
    with ``allreduce=False`` + EP groups; here placement is the whole story)."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
        if any(n in ("wi", "wo") for n in names) and leaf.ndim >= 1:
            return P(expert_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
