"""ZeRO-Offload: optimizer states in pinned host memory.

Reference semantics: ``deepspeed/runtime/zero/stage3.py:1816`` +
``swap_tensor/partitioned_optimizer_swapper.py:29`` — optimizer state lives
off-accelerator; numerics are unchanged. On the virtual CPU mesh, host and
device DRAM are physically one, so the residency assertion is the *placement*
fact XLA acts on for real TPUs: every optimizer-state leaf carries the
backend's host memory kind at rest (``pinned_host`` on TPU; CPU backends
expose only the ``unpinned_host`` alias — ``host_memory_kind()`` resolves
it), so HBM holds no copy between steps."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from deepspeed_tpu.runtime.zero.offload import host_memory_kind

from ..simple_model import make_simple_model, random_batches

HIDDEN = 16


def _cfg(stage, offload=True, optimizer="AdamW", fp16=False):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": optimizer, "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": {"stage": stage},
    }
    if offload:
        cfg["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
    if fp16:
        cfg["fp16"] = {"enabled": True, "loss_scale": 0.0, "initial_scale_power": 8}
    return cfg


def _opt_leaves(opt_state):
    import jax
    return [l for l in jax.tree.leaves(opt_state) if hasattr(l, "sharding")]


def _train(engine, batches, fused=False):
    if fused:
        for b in batches:
            engine.train_batch(batch=b)
    else:
        for b in batches:
            loss = engine.forward(b)
            engine.backward(loss)
            engine.step()


@pytest.mark.parametrize("stage", [0, 2])
@pytest.mark.parametrize("fused", [False, True])
def test_offload_parity_and_placement(stage, fused):
    """offload_optimizer:{device:cpu} must keep states in pinned host memory at
    rest and produce the exact params of the non-offloaded run."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    ref, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage, offload=False))
    _train(ref, batches, fused)

    groups.initialize_mesh(force=True)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(stage, offload=True))
    for leaf in _opt_leaves(eng.opt_state):
        assert leaf.sharding.memory_kind == host_memory_kind(), leaf.sharding
    _train(eng, batches, fused)
    for leaf in _opt_leaves(eng.opt_state):
        assert leaf.sharding.memory_kind == host_memory_kind(), "state must return to host after step"

    for g, w in zip(jax.tree.leaves(jax.device_get(eng.params)),
                    jax.tree.leaves(jax.device_get(ref.params))):
        np.testing.assert_allclose(g, w, rtol=1e-6, atol=1e-6)


def test_cpuadam_implies_offload():
    """A config saying cpuadam must NOT silently train fully in HBM (VERDICT r2
    missing #1): the optimizer itself turns the offload plan on."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(1, offload=False, optimizer="cpuadam"))
    assert eng._offload.enabled
    for leaf in _opt_leaves(eng.opt_state):
        assert leaf.sharding.memory_kind == host_memory_kind()
    _train(eng, random_batches(2, 16, HIDDEN))


def test_offload_fp16_overflow_skip():
    """Overflow-gated stepping still works with offloaded states (the select
    runs wherever the update runs)."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(2, offload=True, fp16=True))
    params_before = jax.device_get(eng.params)
    bad = {"x": np.full((2, HIDDEN), np.inf, np.float32), "y": np.zeros((2, ), np.int32)}
    b0 = random_batches(1, 16, HIDDEN)[0]
    bad = jax.tree.map(lambda l: np.where(np.isfinite(l), np.inf, l).astype(l.dtype), b0)
    loss = eng.forward(bad)
    eng.backward(loss)
    eng.step()
    assert eng.skipped_steps == 1
    for g, w in zip(jax.tree.leaves(jax.device_get(eng.params)), jax.tree.leaves(params_before)):
        np.testing.assert_array_equal(g, w)


def test_offload_with_pipeline_engine():
    """PipelineEngine.train_batch must honor the staging choreography too
    (code-review r3 finding #1)."""
    import jax
    import flax.linen as nn
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            return x + nn.tanh(nn.Dense(HIDDEN)(x))

    class Out(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = PipelineModule(layers=[LayerSpec(Block), LayerSpec(Block), LayerSpec(Out)],
                            num_stages=2,
                            loss_fn=lambda out, y: jnp.mean((out.squeeze(-1) - y)**2))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, HIDDEN)).astype(np.float32)
    y = rng.normal(size=(16, )).astype(np.float32)
    cfg = {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2,
           "optimizer": {"type": "cpuadam", "params": {"lr": 0.01}},
           "zero_optimization": {"stage": 0}}
    eng, _, _, _ = deepspeed_tpu.initialize(model=module, config=cfg, example_batch=(x, y))
    assert eng._offload.enabled
    l0 = float(eng.train_batch(batch=(x, y)))
    l1 = float(eng.train_batch(batch=(x, y)))
    assert l1 < l0
    for leaf in _opt_leaves(eng.opt_state):
        assert leaf.sharding.memory_kind == host_memory_kind()


def test_offload_checkpoint_roundtrip(tmp_path):
    """Save/load with offloaded states: restore lands back in pinned host."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(2, offload=True))
    _train(eng, random_batches(3, 16, HIDDEN))
    eng.save_checkpoint(str(tmp_path), tag="t1")

    groups.initialize_mesh(force=True)
    eng2, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                             config=_cfg(2, offload=True))
    eng2.load_checkpoint(str(tmp_path), tag="t1")
    for leaf in _opt_leaves(eng2.opt_state):
        assert leaf.sharding.memory_kind == host_memory_kind()
    for g, w in zip(jax.tree.leaves(jax.device_get(eng2.opt_state)),
                    jax.tree.leaves(jax.device_get(eng.opt_state))):
        np.testing.assert_allclose(g, w, rtol=0, atol=0)
