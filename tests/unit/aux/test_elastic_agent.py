"""Elastic agent: restart-on-failure with elasticity-valid world shrink
(reference deepspeed/elasticity/elastic_agent.py DSElasticAgent)."""

import os
import sys
import textwrap

import pytest

from deepspeed_tpu.elasticity import DSElasticAgent, ElasticAgentError

ELASTIC_CFG = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                              "micro_batch_sizes": [2, 4, 6], "min_gpus": 1,
                              "max_gpus": 64, "version": 0.1}}


def _worker_script(tmp_path, fail_first: bool):
    """Rank 0 fails on the first attempt (before any flag exists), then
    succeeds — the restart path."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, sys, pathlib
        flag = pathlib.Path({str(repr(str(tmp_path / 'attempted')))})
        rank = os.environ["DSTPU_PROCESS_ID"]
        world = os.environ["DSTPU_NUM_PROCESSES"]
        log = pathlib.Path({str(repr(str(tmp_path)))}) / f"rank{{rank}}_restart{{os.environ['DSTPU_ELASTIC_RESTART']}}.txt"
        log.write_text(world)
        if {fail_first!r} and rank == "0" and not flag.exists():
            flag.write_text("1")
            sys.exit(3)
        sys.exit(0)
    """))
    return str(path)


def test_agent_clean_run(tmp_path):
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=False)],
                           num_processes=2, max_restarts=1, monitor_interval=0.05)
    assert agent.run() == 0
    assert agent.restart_count == 0
    assert (tmp_path / "rank1_restart0.txt").exists()


def test_agent_restarts_after_failure(tmp_path):
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=True)],
                           num_processes=2, max_restarts=2, monitor_interval=0.05)
    assert agent.run() == 0
    assert agent.restart_count == 1
    assert (tmp_path / "rank0_restart1.txt").exists(), "second attempt must have run"


def test_agent_exports_restart_count_for_chaos_one_shot(tmp_path):
    """DSTPU_RESTART_COUNT drives the training chaos injector's one-shot
    kill/sigterm suppression (runtime/faults.first_life): every relaunch
    must see its life number or a deterministic kill replays forever."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, pathlib, sys
        d = pathlib.Path({str(repr(str(tmp_path)))})
        life = os.environ["DSTPU_ELASTIC_RESTART"]
        (d / f"rc{{life}}").write_text(os.environ.get("DSTPU_RESTART_COUNT", "missing"))
        sys.exit(3 if life == "0" else 0)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=1,
                           max_restarts=2, monitor_interval=0.05)
    assert agent.run() == 0
    assert (tmp_path / "rc0").read_text() == "0"
    assert (tmp_path / "rc1").read_text() == "1"


def test_agent_gives_up_after_max_restarts(tmp_path):
    path = tmp_path / "always_fail.py"
    path.write_text("import sys; sys.exit(1)")
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=1,
                           max_restarts=1, monitor_interval=0.05)
    with pytest.raises(ElasticAgentError, match="after 1 restarts"):
        agent.run()


def test_agent_shrinks_to_valid_world(tmp_path):
    """After a node loss the new world size must come from the elastic set."""
    agent = DSElasticAgent(["true"], num_processes=8, ds_config=ELASTIC_CFG,
                           max_restarts=1)
    from deepspeed_tpu.elasticity import compute_elastic_config
    _, valid = compute_elastic_config(ELASTIC_CFG)
    w = agent.next_world_size(capacity=7)
    assert w in valid and w <= 7
    # larger capacity → at least as large a world
    assert agent.next_world_size(capacity=64) >= w


def test_agent_no_valid_world_raises():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 2000,
                          "micro_batch_sizes": [2], "min_gpus": 40, "max_gpus": 64,
                          "version": 0.1}}
    agent = DSElasticAgent(["true"], num_processes=64, ds_config=cfg, max_restarts=1)
    with pytest.raises(ElasticAgentError, match="fits the surviving capacity"):
        agent.next_world_size(capacity=2)


def test_kill_escalation_sigterm_grace_sigkill_reap(tmp_path):
    """A worker that ignores SIGTERM must be SIGKILLed within the grace
    budget and reaped — teardown can never wait forever on a wedged rank."""
    import subprocess
    import time

    path = tmp_path / "stubborn.py"
    path.write_text(textwrap.dedent("""
        import signal, time
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        print("armed", flush=True)
        time.sleep(600)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=1,
                           term_grace_s=0.5)
    proc = subprocess.Popen([sys.executable, str(path)],
                            stdout=subprocess.PIPE)
    proc.stdout.readline()  # SIGTERM handler installed
    t0 = time.monotonic()
    agent._kill([proc])
    assert time.monotonic() - t0 < 5.0, "escalation must be bounded by grace"
    assert proc.poll() is not None, "the straggler must be reaped"
    assert proc.returncode == -9, "SIGTERM ignored -> SIGKILL"


def test_preempt_143_drains_gang_without_counting_a_crash(tmp_path):
    """One rank exiting 143 (TrainingPreempted: final checkpoint committed)
    drains the peers via SIGTERM — their preemption handlers run — and the
    agent exits 143 with zero restarts (the PR-11 contract at gang scope)."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import os, pathlib, signal, sys, time
        d = pathlib.Path({str(repr(str(tmp_path)))})
        rank = os.environ["DSTPU_PROCESS_ID"]
        if rank == "0":
            time.sleep(0.3)
            sys.exit(143)
        def on_term(signum, frame):
            (d / f"drained{{rank}}").write_text("1")
            sys.exit(0)
        signal.signal(signal.SIGTERM, on_term)
        time.sleep(600)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=2,
                           max_restarts=3, monitor_interval=0.05,
                           term_grace_s=5.0)
    assert agent.run() == 143
    assert agent.restart_count == 0, "preemption is not a crash"
    assert (tmp_path / "drained1").exists(), \
        "the surviving rank's preemption handler must have run"


def test_crash_budget_shrinks_then_succeeds_at_smaller_world(tmp_path):
    """max_crashes at world=2 exhausts the budget -> relaunch at world=1
    (elasticity off: world-1), where the workers succeed."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent("""
        import os, sys
        sys.exit(7 if os.environ["DSTPU_NUM_PROCESSES"] == "2" else 0)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=2,
                           max_restarts=5, monitor_interval=0.05,
                           max_crashes=2, crash_window_s=600.0,
                           gang_dir=str(tmp_path / "gang"))
    assert agent.run() == 0
    assert agent.restart_count == 2 and agent.world == 1
    assert agent.last_shrink == {**agent.last_shrink, "from": 2, "to": 1}
    from deepspeed_tpu.elasticity.gang import read_gang_state
    state = read_gang_state(agent.gang_dir)
    assert state["phase"] == "done" and state["world"] == 1
    assert [ev["kind"] for ev in state["events"]].count("crash") == 2


def test_watchdog_detects_stale_heartbeat_and_relaunches(tmp_path):
    """A rank that beats once and then wedges (process alive, no train-loop
    progress) is detected via heartbeat staleness; the gang is torn down and
    the relaunch succeeds. Pure stdlib workers — the watchdog mechanism is
    independent of JAX."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(f"""
        import json, os, pathlib, sys, time
        d = pathlib.Path(os.environ["DSTPU_GANG_DIR"])
        rank = os.environ["DSTPU_PROCESS_ID"]
        life = os.environ["DSTPU_RESTART_COUNT"]
        tmp = d / f"rank{{rank}}.hb.tmp"
        tmp.write_text(json.dumps({{"rank": int(rank), "unix": time.time(),
                                    "step": 1, "phase": "step"}}))
        os.replace(tmp, d / f"rank{{rank}}.hb")
        if life == "0" and rank == "1":
            time.sleep(600)  # wedged: alive, never beats again
        sys.exit(0)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=2,
                           max_restarts=2, monitor_interval=0.05,
                           gang_dir=str(tmp_path / "gang"),
                           hang_timeout_s=0.6, term_grace_s=0.5)
    assert agent.run() == 0
    assert agent.restart_count == 1
    from deepspeed_tpu.elasticity.gang import read_gang_state
    state = read_gang_state(agent.gang_dir)
    hangs = [ev for ev in state["events"] if ev["kind"] == "hang"]
    assert hangs and "rank(s) [1]" in hangs[0]["detail"]


def test_watchdog_boot_deadline_catches_never_beaten_gang(tmp_path):
    """A gang wedged BEFORE its first heartbeat (e.g. stuck inside the
    coordination-service rendezvous) is invisible to exit polling and to
    staleness; the boot deadline bounds it."""
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ["DSTPU_RESTART_COUNT"] == "0":
            time.sleep(600)  # wedged at boot: alive, never heartbeats
        sys.exit(0)
    """))
    agent = DSElasticAgent([sys.executable, str(path)], num_processes=2,
                           max_restarts=2, monitor_interval=0.05,
                           gang_dir=str(tmp_path / "gang"),
                           hang_timeout_s=5.0, boot_timeout_s=0.8,
                           term_grace_s=0.5)
    assert agent.run() == 0
    assert agent.restart_count == 1
    from deepspeed_tpu.elasticity.gang import read_gang_state
    state = read_gang_state(agent.gang_dir)
    hangs = [ev for ev in state["events"] if ev["kind"] == "hang"]
    assert hangs and "wedged at boot" in hangs[0]["detail"]


def test_agent_restart_shrinks_world_end_to_end(tmp_path):
    """Failure + reduced capacity → relaunch with a *smaller, valid* world;
    workers observe the shrunken DSTPU_NUM_PROCESSES."""
    caps = iter([3])  # after the failure, only 3 slots survive
    agent = DSElasticAgent([sys.executable, _worker_script(tmp_path, fail_first=True)],
                           num_processes=4, ds_config=ELASTIC_CFG, max_restarts=2,
                           monitor_interval=0.05, capacity_fn=lambda: next(caps))
    assert agent.run() == 0
    from deepspeed_tpu.elasticity import compute_elastic_config
    _, valid = compute_elastic_config(ELASTIC_CFG)
    observed = int((tmp_path / "rank0_restart1.txt").read_text())
    assert observed <= 3 and observed in valid
