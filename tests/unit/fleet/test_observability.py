"""Fleet observability plane (ISSUE acceptance): the router's
``/v1/fleet/{trace,timeseries,slo}`` surface, the flagship CPU gate (a
supervised two-subprocess fleet rendering ONE merged cross-process trace),
and the SLO gate (a seeded overload burst drives the TTFT fast-window burn
over threshold while the fault-free control at the identical seed stays
below)."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet import (FaultConfig, FleetConfig, FleetRouter,
                                 ReplicaManager, SupervisorConfig)
from deepspeed_tpu.fleet.config import GlobalQueueConfig
from deepspeed_tpu.fleet.supervisor import ReplicaSupervisor
from deepspeed_tpu.telemetry import TelemetryConfig

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

TTFT_OBJECTIVE = {"name": "ttft", "metric": "ttft", "target_s": 0.06,
                  "target_ratio": 0.9, "fast_window_s": 30.0,
                  "slow_window_s": 90.0, "burn_threshold": 2.0}


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _post_generate(url, doc, timeout=120):
    req = urllib.request.Request(url + "/v1/generate",
                                 data=json.dumps(doc).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# the HTTP surface over a local fleet
# ---------------------------------------------------------------------------
def test_fleet_observability_endpoints(make_fleet, tmp_path):
    """One request through a telemetry-enabled fleet surfaces on every new
    endpoint: the merged trace, the time-series rollup (router + per-replica
    probe docs), the SLO status, and the scheduler's /v1/stats blocks."""
    telemetry.configure(TelemetryConfig(
        enabled=True,
        timeseries={"enabled": True, "interval_s": 60.0},
        slo={"enabled": True, "objectives": [TTFT_OBJECTIVE]}))
    fleet = make_fleet(roles=("mixed",))
    router = FleetRouter(fleet).start()
    try:
        final = _post_generate(router.url,
                               {"prompt": (np.arange(7) % 64).tolist(),
                                "max_new_tokens": 2})
        assert final["state"] == "DONE"
        trace_id = final["trace_id"]
        telemetry.get_timeseries().tick()  # one sample -> snapshots have points

        doc = _get(router.url + "/v1/fleet/trace")
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"
               and e["args"]["trace_id"] == trace_id]
        assert {"route", "request"} <= {e["name"] for e in evs}
        assert doc["collector"]["collections"] >= 1
        assert "local" in doc["collector"]["sources"]
        only = _get(router.url + f"/v1/fleet/trace?trace_id={trace_id}")
        assert {e["args"]["trace_id"] for e in only["traceEvents"]
                if e.get("ph") == "X"} == {trace_id}
        # the merged doc is exactly what dstpu_report --trace consumes
        from deepspeed_tpu.env_report import trace_report
        path = tmp_path / "fleet_trace.json"
        path.write_text(json.dumps(doc))
        assert trace_report(str(path)) == 0

        ts_doc = _get(router.url + "/v1/fleet/timeseries")
        assert ts_doc["router"]["ticks"] >= 1
        assert "serving_ttft_seconds" in ts_doc["router"]["series"]
        # per-replica rollup rides the probe doc (LocalReplica shares the
        # process store here; the shape is what HttpReplica ships)
        assert set(ts_doc["replicas"]) == {r.id for r in fleet.replicas()}

        slo_doc = _get(router.url + "/v1/fleet/slo")
        assert slo_doc["enabled"] is True and not slo_doc["in_breach"]
        assert [o["name"] for o in slo_doc["objectives"]] == ["ttft"]

        # the scheduler's own stats doc carries the same engine + store
        stats = fleet.replicas()[0].scheduler.stats()
        assert isinstance(stats["timeseries"], dict)
        assert stats["slo"]["objectives"][0]["name"] == "ttft"
    finally:
        router.stop(drain=False)


def test_observability_surface_without_telemetry_is_inert(make_fleet):
    """Telemetry off (ISSUE acceptance): every surface answers a well-formed
    'nothing' instead of crashing, the router never builds a collector, and
    a full routed request plus every observability read costs ZERO registry
    calls — the disabled paths are one None/boolean check each."""
    fleet = make_fleet(roles=("mixed",))
    router = FleetRouter(fleet)
    final = router.route({"prompt": (np.arange(7) % 64).tolist(),
                          "max_new_tokens": 2}).result()
    assert final["state"] == "DONE" and final["trace_id"] is None
    assert router._collector is None
    assert router.collect_traces() is None
    assert router.fleet_trace() == {"traceEvents": [], "displayTimeUnit": "ms",
                                    "collector": None}
    ts_doc = router.fleet_timeseries()
    assert ts_doc["router"] is None and ts_doc["replicas"] == {}
    assert router.fleet_slo() == {"enabled": False, "objectives": [],
                                  "in_breach": False}
    stats = fleet.replicas()[0].scheduler.stats()
    assert stats["timeseries"] is None and stats["slo"] is None
    router.fleet_stats()
    # the zero-cost contract, extended to the collector/time-series/SLO
    # hooks: nothing above touched the registry
    assert telemetry.get_registry().api_calls == 0


# ---------------------------------------------------------------------------
# the flagship CPU gate: one trace across three real processes
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_flagship_cross_process_fleet_trace():
    """A supervised two-subprocess fleet (prefill + decode roles, real
    ``bin/dstpu_replica`` processes with ``--telemetry``) serves one traced
    request; ``/v1/fleet/trace`` then renders a SINGLE merged Perfetto doc:
    router span + prefill leg + decode leg from three distinct pids, all
    under one trace id, leg spans nested inside the router span after
    clock-offset correction."""
    pytest.importorskip("jax")
    telemetry.configure(TelemetryConfig(enabled=True))
    cmd = [sys.executable, os.path.join(REPO, "bin", "dstpu_replica"),
           "--port-file", "{port_file}", "--vocab-size", "64",
           "--num-blocks", "32", "--max-context", "64", "--telemetry"]
    manager = ReplicaManager(config=FleetConfig(
        probe_ttl_s=0.0, connect_timeout_s=5.0, read_timeout_s=180.0))
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=2, crash_window_s=120.0, poll_interval_s=0.1,
        ready_timeout_s=300.0, restart_backoff_base_s=0.1,
        restart_backoff_cap_s=0.5, restart_jitter_frac=0.0))
    slots = [supervisor.add_process(cmd, role=role,
                                    env={"JAX_PLATFORMS": "cpu"})
             for role in ("prefill", "decode")]
    supervisor.start()
    try:
        assert supervisor.wait_ready(timeout=480.0), \
            [s.describe() for s in slots]
        router = FleetRouter(manager)
        routed = router.route({"prompt": (np.arange(9) % 64).tolist(),
                               "max_new_tokens": 3})
        final = dict(routed.result())
        assert final["state"] == "DONE"
        assert [leg["kind"] for leg in final["legs"]] == ["prefill", "decode"]
        trace_id = final["trace_id"]

        doc = router.fleet_trace(trace_id)
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"
               and e["args"]["trace_id"] == trace_id]
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)

        # three DISTINCT processes under the one trace id
        pids = {e["pid"] for e in evs}
        assert len(pids) == 3 and os.getpid() in pids

        (route, ) = by_name["route"]
        (hop_prefill, ) = by_name["dispatch:prefill"]
        (hop_decode, ) = by_name["dispatch:decode"]
        assert route["pid"] == os.getpid()
        for hop in (hop_prefill, hop_decode):
            assert hop["pid"] == os.getpid()
            assert hop["args"]["parent_id"] == route["args"]["span_id"]

        requests = by_name["request"]
        assert len(requests) == 2
        leg_pids = {r["pid"] for r in requests}
        assert len(leg_pids) == 2 and os.getpid() not in leg_pids
        assert {r["args"]["parent_id"] for r in requests} == \
            {hop_prefill["args"]["span_id"], hop_decode["args"]["span_id"]}
        assert {r["args"]["source"] for r in requests} == \
            {f"replica:{r.id}" for r in manager.replicas()}

        # the offset-corrected leg spans NEST inside the router span (the
        # pull round-trip bounds the residual error; allow a little slack)
        slack = 150_000  # us
        t0, t1 = route["ts"], route["ts"] + route["dur"]
        for r in requests:
            assert r["ts"] >= t0 - slack, (r["ts"], t0)
            assert r["ts"] + r["dur"] <= t1 + slack, (r["ts"] + r["dur"], t1)

        # the Perfetto metadata names each process track
        sources = {m["args"]["name"]
                   for m in doc["traceEvents"]
                   if m.get("ph") == "M" and m["name"] == "process_name"}
        assert "local" in sources
        assert {f"replica:{r.id}" for r in manager.replicas()} <= sources
    finally:
        supervisor.stop()


# ---------------------------------------------------------------------------
# the SLO gate: seeded overload burst vs fault-free control, identical seed
# ---------------------------------------------------------------------------
def _slo_arm(make_fleet, tmp_path, tag, mean_gap_s, faults):
    """One gate arm: a fresh telemetry session + single-slot fleet, the
    PR-14-style seeded open-loop workload (Poisson arrivals, seed 7 in both
    arms — ``mean_gap_s`` scales the identical schedule), manual window
    ticks. Returns (slo status, flight-dump count, breach-counter delta)."""
    dump_dir = str(tmp_path / tag)
    session = telemetry.configure(TelemetryConfig(
        enabled=True,
        flight_recorder={"enabled": True, "dir": dump_dir,
                         "watchdog_enabled": False, "signal_enabled": False},
        timeseries={"interval_s": 3600.0},
        slo={"enabled": True, "objectives": [TTFT_OBJECTIVE]}))
    # the registry (and slo_breaches_total) persists across the two arms'
    # sessions: read the counter as a per-arm delta
    breach_base = telemetry.get_registry().counter("slo_breaches_total").value
    try:
        manager = make_fleet(
            roles=(),
            config=FleetConfig(
                probe_ttl_s=0.0, drain_timeout_s=10.0,
                global_queue=GlobalQueueConfig(max_inflight_per_replica=8,
                                               capacity=256)),
            max_tracked_sequences=1)
        manager.add_local(role="mixed", replica_id="r0")
        router = FleetRouter(manager)
        prompt = (np.arange(9) % 64).tolist()
        # warm OUTSIDE the window and BEFORE the fault arm arms: compiles
        # must not read as overload
        for _ in range(2):
            assert router.route({"prompt": prompt, "max_new_tokens": 24,
                                 "seed": 0}).result()["state"] == "DONE"
        if faults is not None:
            router.set_faults(faults)
        store = telemetry.get_slo_engine().store
        store.tick(now=0.0)  # the measurement window opens here
        # PR-14-style open loop: Poisson arrivals from one seed; the burst
        # arm compresses the IDENTICAL schedule past the single-slot
        # replica's capacity, so requests pile up in the scheduler queue and
        # the queue wait lands in their TTFT. The control's spacing keeps
        # every request finishing before the next arrives.
        rng = np.random.default_rng(7)
        offsets = np.cumsum(rng.exponential(mean_gap_s, 8))
        finals = [None] * len(offsets)
        t0 = time.monotonic()

        def _one(i, at):
            delay = at - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            finals[i] = dict(router.route({"prompt": prompt,
                                           "max_new_tokens": 24,
                                           "seed": 0}).result())

        threads = [threading.Thread(target=_one, args=(i, at))
                   for i, at in enumerate(offsets)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert all(f is not None and f["state"] == "DONE" for f in finals)
        if faults is not None:
            # the chaos point fired: phantom admissions rode the real burst
            gq = router.fleet_stats()["router"]["global_queue"]
            assert gq["phantoms_injected"] > 0
        store.tick(now=1.0)  # close the window: on_tick evaluates the SLO
        status = telemetry.get_slo_engine().status()
        dumps = ([f for f in os.listdir(dump_dir) if "slo_breach" in f]
                 if os.path.isdir(dump_dir) else [])
        breaches = (telemetry.get_registry()
                    .counter("slo_breaches_total").value - breach_base)
        return status, len(dumps), breaches
    finally:
        session.close()


@pytest.mark.slow
def test_slo_gate_burst_breaches_while_control_stays_below(make_fleet,
                                                           tmp_path):
    """The SLO gate (ISSUE acceptance): under the PR-14 seeded overload
    burst — the identical seed-7 open-loop schedule compressed past the
    single-slot replica's capacity, with the ``overload_burst`` chaos point
    armed — the TTFT SLO's fast-window burn rate crosses its alert
    threshold: breach counted, flight dump fired. The fault-free control
    run at the identical seed, spaced within capacity, stays below."""
    control, control_dumps, control_breaches = _slo_arm(
        make_fleet, tmp_path, "control", mean_gap_s=0.6, faults=None)
    burst, burst_dumps, burst_breaches = _slo_arm(
        make_fleet, tmp_path, "burst", mean_gap_s=0.02,
        faults=FaultConfig(enabled=True, seed=3, overload_burst_p=1.0,
                           overload_burst_requests=4,
                           overload_burst_hold_s=0.5))

    ctrl_obj = control["objectives"][0]
    burst_obj = burst["objectives"][0]
    assert burst_obj["fast_burn"] >= burst_obj["burn_threshold"], burst_obj
    assert burst_obj["in_breach"] and burst["in_breach"]
    assert burst_breaches == 1 and burst_dumps == 1

    assert ctrl_obj["fast_burn"] < ctrl_obj["burn_threshold"], ctrl_obj
    assert not control["in_breach"]
    assert control_breaches == 0 and control_dumps == 0
    # the separation is real, not a threshold graze
    assert burst_obj["fast_burn"] > 2 * max(ctrl_obj["fast_burn"], 0.1)
