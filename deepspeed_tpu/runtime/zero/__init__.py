from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.partition_parameters import (GatheredParameters, Init,
                                                             register_external_parameter,
                                                             unregister_external_parameter)
from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy
from deepspeed_tpu.runtime.zero.tiling import TiledLinear

__all__ = ["DeepSpeedZeroConfig", "GatheredParameters", "Init", "TiledLinear",
           "ZeroShardingPolicy", "register_external_parameter",
           "unregister_external_parameter"]
