"""qwZ weight-gather wiring: `zero_quantized_weights` must put int8 on the
ZeRO-3 parameter all-gather wire (reference ZeRO++,
partition_parameters.py:1152 all_gather_coalesced quantized path +
CUDAQuantizer:731).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches

HIDDEN = 64


def _cfg(qwz, stage=3, gas=1):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": {"stage": stage, "zero_quantized_weights": bool(qwz),
                              "stage3_param_persistence_threshold": 0},
    }


def test_qwz_hlo_has_int8_all_gather():
    """The compiled gradient program must all-gather an s8 payload — wire
    compression for real, not a numerics-only decoration."""
    import jax
    import jax.numpy as jnp

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=_cfg(qwz=True))
    assert eng._qwz
    batch = eng.shard_batch(random_batches(1, 16, HIDDEN)[0])
    hlo = eng._grad_fn().lower(eng.params, batch, jax.random.PRNGKey(0),
                               jnp.float32(1.0)).compile().as_text()
    assert "all-gather" in hlo
    import re
    assert re.search(r"s8\[[\d,]*\][^=]* all-gather", hlo), \
        "the all-gather payload must be int8 on the wire"


def test_qwz_trains_close_to_exact():
    """int8-gathered weights track the exact run closely on a smooth problem —
    and are NOT bit-identical (the quantizer really ran)."""
    import jax

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    losses = {}
    params = {}
    for qwz in (False, True):
        groups.initialize_mesh(force=True)
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                                config=_cfg(qwz=qwz))
        ls = [float(eng.train_batch(batch=b)) for b in batches]
        losses[qwz] = ls
        params[qwz] = jax.tree.leaves(jax.device_get(eng.params))

    # same trajectory within quantization tolerance
    np.testing.assert_allclose(losses[True], losses[False], rtol=0.05)
    for a, b in zip(params[True], params[False]):
        np.testing.assert_allclose(a, b, atol=0.05)
    assert any(not np.array_equal(a, b) for a, b in zip(params[True], params[False])), \
        "bit-identical params mean the quantizer never ran"


def test_qwz_requires_stage3():
    """A config knob that cannot be honored must raise, not be swallowed."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    with pytest.raises(ValueError, match="requires ZeRO stage 3"):
        deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                 config=_cfg(qwz=True, stage=2))


def test_qwz_nontrainable_knob_rejected():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    cfg = _cfg(qwz=True)
    cfg["zero_optimization"]["zero_quantized_nontrainable_weights"] = True
    with pytest.raises(NotImplementedError, match="nontrainable"):
        deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)


def test_qwz_small_and_replicated_leaves_cast_exactly():
    """Leaves under the threshold (or not ZeRO-sharded) keep the exact cast:
    the eval loss with qwZ on equals the fp eval loss when every leaf is
    below the quantization threshold."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=8, batch_size=16)  # all tiny leaves
    batches = random_batches(1, 16, 8)
    outs = {}
    for qwz in (False, True):
        groups.initialize_mesh(force=True)
        cfg = _cfg(qwz=qwz)
        cfg["train_micro_batch_size_per_gpu"] = 16
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                                config=cfg)
        eng.eval()
        outs[qwz] = float(eng.forward(batches[0]))
    assert outs[True] == outs[False]


def test_qwz_bf16_grads_keep_master_dtype():
    """Straight-through vjp must hand back MASTER-dtype cotangents: with bf16
    compute the gradient of an fp32 master weight stays fp32 (regression:
    bwd returned the bf16 cotangent unchanged)."""
    import jax
    import jax.numpy as jnp

    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    cfg = _cfg(qwz=True)
    cfg["bf16"] = {"enabled": True}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                            config=cfg)
    loss = eng.forward(random_batches(1, 16, HIDDEN)[0])
    eng.backward(loss)
    for g in jax.tree.leaves(eng.acc_grads):
        assert g.dtype == jnp.float32, g.dtype
