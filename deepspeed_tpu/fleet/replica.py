"""Replica abstractions for the fleet layer.

A *replica* is one independently-schedulable serving engine. Two kinds behind
one dispatch interface, so the router never cares which it is talking to:

- :class:`LocalReplica` — an ``(InferenceEngineV2 + ServingScheduler)`` pair
  living in this process. The tier-1 CPU-testable formulation: a 4-replica
  disaggregated fleet is four tiny engines and four scheduler threads, no
  sockets between router and engine.
- :class:`HttpReplica` — an external ``serving/server.py`` process addressed
  by URL; dispatch is ``POST /v1/generate`` / ``POST /v1/resume`` over the
  wire (SSE upstream, so admission errors surface before generation and
  tokens arrive live), probing is ``GET /healthz`` + ``GET /v1/stats``.

Dispatch returns a :class:`Leg` — a uniform handle the router iterates for
live tokens and joins for the final result doc (which carries the KV-handoff
payload as raw bytes when the leg was dispatched with ``handoff=True``).

A replica that cannot admit right now (queue full, draining, connection
refused) raises :class:`ReplicaUnavailable` at dispatch — the router's
failover signal; client errors (bad payload geometry, invalid parameters)
raise ``ValueError`` and are NOT retried elsewhere.
"""

import base64
import itertools
import json
import threading
import time
import urllib.error
import urllib.request
from enum import Enum
from typing import Iterator, Optional

from deepspeed_tpu.serving import (QueueFullError, SchedulerStopped, ServingConfig,
                                   ServingScheduler)
from deepspeed_tpu.serving.request import Request
from deepspeed_tpu.serving.server import PARENT_SPAN_HEADER, TRACE_HEADER
from deepspeed_tpu.utils.logging import logger

_REPLICA_IDS = itertools.count()


class ReplicaState(Enum):
    UP = 0
    DRAINING = 1
    DOWN = 2


class ReplicaUnavailable(RuntimeError):
    """This replica cannot admit the request right now (429/503/unreachable);
    the router fails over to the next candidate."""

    def __init__(self, message: str, status: int = 503):
        super().__init__(message)
        self.status = status


class Leg:
    """One dispatched request leg: iterate for live tokens, ``result()`` for
    the terminal doc (``serving/server._request_doc`` shape, with the handoff
    payload — when requested — as raw bytes under ``"handoff"``)."""

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def result(self, timeout: Optional[float] = None) -> dict:
        raise NotImplementedError

    def cancel(self) -> None:
        raise NotImplementedError


class Replica:
    """Base replica: identity, role, rotation state, probe caching, and the
    router-maintained dispatch counters."""

    def __init__(self, role: str = "mixed", replica_id: Optional[str] = None):
        self.id = replica_id if replica_id else f"{role}-{next(_REPLICA_IDS)}"
        self.role = role
        self.state = ReplicaState.UP
        self.dispatches = 0   # legs the router sent here (router thread)
        self.failures = 0     # legs that raised ReplicaUnavailable here
        self._probe_lock = threading.Lock()
        self._probe_at = 0.0
        self._probe_doc: Optional[dict] = None

    @property
    def available(self) -> bool:
        """In rotation: the router only dispatches to available replicas."""
        return self.state is ReplicaState.UP

    # ------------------------------------------------------------------ probe --
    def probe(self, max_age_s: float = 0.0) -> dict:
        """Health + load snapshot, cached up to ``max_age_s`` (the router's
        ``probe_ttl_s``): ``healthy`` / ``draining`` / ``queue_depth`` /
        ``active`` / ``kv_free_frac`` / ``heartbeats``.

        A ``_probe()`` against a blackholed HTTP upstream can block for its
        full socket timeout, so a stale doc is served rather than queueing
        every router handler thread behind the one doing the refresh — only
        the very first probe (no doc yet) waits."""
        doc = self._probe_doc
        if doc is not None and time.monotonic() - self._probe_at <= max_age_s:
            return doc
        if not self._probe_lock.acquire(blocking=doc is None):
            return doc  # a peer thread is refreshing; stale beats stalled
        try:
            now = time.monotonic()
            if self._probe_doc is None or now - self._probe_at > max_age_s:
                try:
                    self._probe_doc = self._probe()
                except Exception as e:
                    self._probe_doc = {"healthy": False, "draining": False,
                                       "queue_depth": 0, "active": 0,
                                       "kv_free_frac": 0.0, "heartbeats": 0,
                                       "error": f"{type(e).__name__}: {e}"}
                self._probe_at = now
            return self._probe_doc
        finally:
            self._probe_lock.release()

    def _probe(self) -> dict:
        raise NotImplementedError

    @property
    def load(self) -> int:
        """Least-loaded ordering key from the last probe (queued + in-flight)."""
        doc = self._probe_doc or {}
        return int(doc.get("queue_depth", 0)) + int(doc.get("active", 0))

    # --------------------------------------------------------------- dispatch --
    def dispatch(self, doc: dict, resume: bool = False,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[int] = None) -> Leg:
        """Admit one request leg. ``doc`` is the client-wire JSON body
        (``prompt`` for generate, ``payload`` bytes for resume, plus the
        optional sampling/deadline fields and the ``handoff`` flag). Raises
        :class:`ReplicaUnavailable` when this replica cannot admit."""
        raise NotImplementedError

    # ------------------------------------------------------------- lifecycle --
    def drain(self, timeout: Optional[float] = None) -> None:
        """Leave rotation, let in-flight requests finish (bounded), then stop."""
        raise NotImplementedError

    def close(self) -> None:
        self.drain(timeout=0.0)

    def describe(self) -> dict:
        """/v1/fleet/stats row."""
        return {"id": self.id, "role": self.role, "state": self.state.name,
                "url": getattr(self, "url", None),
                "dispatches": self.dispatches, "failures": self.failures,
                "probe": self._probe_doc}


# ---------------------------------------------------------------------------
# in-process replica
# ---------------------------------------------------------------------------
class _LocalLeg(Leg):

    def __init__(self, req: Request):
        self.request = req

    def __iter__(self):
        return iter(self.request.stream)

    def result(self, timeout: Optional[float] = None) -> dict:
        req = self.request
        if not req.wait(timeout):
            raise TimeoutError(f"leg {req.uid} not finished within {timeout}s")
        from deepspeed_tpu.serving.server import _request_doc
        return _request_doc(req, raw_handoff=True)

    def cancel(self) -> None:
        self.request.cancel()


class LocalReplica(Replica):
    """An in-process ``engine + scheduler`` replica. The engine is owned:
    ``drain()``/``close()`` stop the scheduler and close the engine.

    ``serving_config`` defaults to heartbeating while idle (``empty_run``)
    regardless of expert parallelism — a fleet pool member must stay warm (and,
    under EP, in collective lock-step) while its peers take traffic.
    """

    def __init__(self, engine, role: str = "mixed",
                 serving_config: Optional[ServingConfig] = None,
                 replica_id: Optional[str] = None):
        super().__init__(role=role, replica_id=replica_id)
        self.engine = engine
        if serving_config is None:
            serving_config = ServingConfig(heartbeat_enabled=True)
        elif serving_config.heartbeat_enabled is None:
            # the pool-member warmth contract holds for custom configs too:
            # only an explicit False opts a replica out of idle empty_run
            serving_config = serving_config.model_copy(
                update={"heartbeat_enabled": True})
        self.scheduler = ServingScheduler(engine, serving_config)
        self._capacity_blocks = engine._state_manager.kv_cache.num_blocks

    def _probe(self) -> dict:
        sched = self.scheduler
        free = self.engine.free_blocks
        return {
            "healthy": self.state is ReplicaState.UP and not sched._stopping,
            "draining": self.state is ReplicaState.DRAINING or sched._stopping,
            "queue_depth": sched.queue_depth,
            "active": sched.n_active,
            "kv_free_frac": free / self._capacity_blocks if self._capacity_blocks else 0.0,
            "heartbeats": sched._counters["heartbeats"],
        }

    def dispatch(self, doc: dict, resume: bool = False,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[int] = None) -> Leg:
        if not self.available:
            raise ReplicaUnavailable(f"replica {self.id} is {self.state.name}")
        kwargs = dict(max_new_tokens=doc.get("max_new_tokens"),
                      temperature=float(doc.get("temperature") or 0.0),
                      eos_token_id=doc.get("eos_token_id"),
                      deadline_s=doc.get("deadline_s"),
                      seed=int(doc.get("seed") or 0),
                      trace_id=trace_id, parent_span_id=parent_span_id,
                      handoff=bool(doc.get("handoff")))
        try:
            if resume:
                req = self.scheduler.submit_resume(doc["payload"], **kwargs)
            else:
                req = self.scheduler.submit(doc["prompt"], **kwargs)
        except QueueFullError as e:
            raise ReplicaUnavailable(str(e), status=429) from e
        except SchedulerStopped as e:
            raise ReplicaUnavailable(str(e), status=503) from e
        return _LocalLeg(req)

    def drain(self, timeout: Optional[float] = None) -> None:
        if self.state is ReplicaState.DOWN:
            return
        self.state = ReplicaState.DRAINING  # out of rotation immediately
        self.scheduler.stop(drain=timeout != 0.0, timeout=timeout)
        self.engine.close()
        self.state = ReplicaState.DOWN


# ---------------------------------------------------------------------------
# HTTP upstream replica
# ---------------------------------------------------------------------------
class _HttpLeg(Leg):
    """SSE leg against a ``serving/server.py`` upstream. The upstream is
    always dispatched streaming, so admission status arrives before any
    generation and tokens can be forwarded live; ``result()`` drains the
    stream and returns the final ``done`` doc."""

    def __init__(self, resp):
        self._resp = resp
        self._final: Optional[dict] = None
        self._lock = threading.Lock()

    def __iter__(self):
        for line in self._resp:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            event = json.loads(line[len("data: "):])
            if event.get("done"):
                if "handoff" in event:
                    event["handoff"] = base64.b64decode(event["handoff"])
                with self._lock:
                    self._final = event
                return
            yield int(event["token"])

    def result(self, timeout: Optional[float] = None) -> dict:
        with self._lock:
            final = self._final
        if final is None:
            for _ in self:  # drain to the done event
                pass
            with self._lock:
                final = self._final
        if final is None:
            raise RuntimeError("upstream stream ended without a done event")
        return final

    def cancel(self) -> None:
        # dropping the connection cancels upstream (serving/server.py contract)
        try:
            self._resp.close()
        except Exception:  # pragma: no cover - best effort
            pass


class HttpReplica(Replica):
    """An external ``serving/server.py`` process addressed by base URL."""

    def __init__(self, url: str, role: str = "mixed",
                 replica_id: Optional[str] = None, timeout_s: float = 120.0):
        super().__init__(role=role, replica_id=replica_id)
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def _get_json(self, path: str, timeout: float) -> dict:
        with urllib.request.urlopen(self.url + path, timeout=timeout) as resp:
            return json.loads(resp.read())

    def _probe(self) -> dict:
        health = self._get_json("/healthz", timeout=min(self.timeout_s, 5.0))
        stats = self._get_json("/v1/stats", timeout=min(self.timeout_s, 5.0))
        engine = stats.get("engine", {})
        capacity = engine.get("capacity_blocks") or 0
        free = engine.get("free_blocks") or 0
        return {
            "healthy": health.get("status") == "ok" and self.state is ReplicaState.UP,
            "draining": health.get("status") == "draining"
                        or self.state is ReplicaState.DRAINING
                        or bool(stats.get("draining")),
            "queue_depth": int(stats.get("queue_depth", 0)),
            "active": int(stats.get("active", {}).get("total", 0)),
            "kv_free_frac": free / capacity if capacity else 1.0,
            "heartbeats": int(stats.get("counters", {}).get("heartbeats", 0)),
        }

    def dispatch(self, doc: dict, resume: bool = False,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[int] = None) -> Leg:
        if not self.available:
            raise ReplicaUnavailable(f"replica {self.id} is {self.state.name}")
        body = dict(doc)
        body["stream"] = True  # SSE upstream: early admission status, live tokens
        if resume:
            body["payload"] = base64.b64encode(doc["payload"]).decode()
        headers = {"Content-Type": "application/json"}
        if trace_id is not None:
            headers[TRACE_HEADER] = trace_id
        if parent_span_id is not None:
            headers[PARENT_SPAN_HEADER] = str(parent_span_id)
        path = "/v1/resume" if resume else "/v1/generate"
        req = urllib.request.Request(self.url + path,
                                     data=json.dumps(body).encode(),
                                     headers=headers)
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                pass
            if e.code in (429, 503):
                raise ReplicaUnavailable(
                    f"replica {self.id}: HTTP {e.code} {detail}", status=e.code) from e
            raise ValueError(f"replica {self.id}: HTTP {e.code} {detail}") from e
        except urllib.error.URLError as e:
            raise ReplicaUnavailable(f"replica {self.id}: {e.reason}") from e
        return _HttpLeg(resp)

    def drain(self, timeout: Optional[float] = None) -> None:
        # the upstream process is not ours to stop: drain = leave rotation
        # for good (its own operator runs server.stop()). DOWN, not DRAINING —
        # a permanently-DRAINING replica would count as live capacity in the
        # fleet_replicas gauge and /v1/fleet/stats forever
        if self.state is not ReplicaState.DOWN:
            logger.info(f"fleet: upstream replica {self.id} out of rotation")
            self.state = ReplicaState.DOWN
