"""Config key names and defaults (reference: deepspeed/runtime/constants.py)."""

# batch triangle
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

# optimizer / scheduler
OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
TYPE = "type"
PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
LION_OPTIMIZER = "lion"
MUADAM_OPTIMIZER = "muadam"
MUADAMW_OPTIMIZER = "muadamw"
MUSGD_OPTIMIZER = "musgd"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, LION_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER
]

# precision
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"

# grads
GRADIENT_CLIPPING = "gradient_clipping"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"

# logging / misc
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"
MEMORY_BREAKDOWN = "memory_breakdown"

# parallelism
ZERO_OPTIMIZATION = "zero_optimization"
PIPELINE = "pipeline"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
TENSOR_PARALLEL_SIZE = "tensor_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
STEPS_PER_PRINT_DEFAULT = 10
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
SPARSE_GRADIENTS_DEFAULT = False
WALL_CLOCK_BREAKDOWN_DEFAULT = False
DUMP_STATE_DEFAULT = False

# checkpoint
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

# data types
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

USE_DATA_BEFORE_EXPERT_PARALLEL = "use_data_before_expert_parallelism"
USE_DATA_BEFORE_EXPERT_PARALLEL_DEFAULT = False
