"""Chaos suite (ISSUE tentpole): the deterministic FaultInjector drives every
recovery path the fault-tolerance subsystem claims — failover + breaker
cycles on injected 5xx, decode-leg re-dispatch on mid-stream death, pristine
retry on corrupted handoffs, kill + failover, graceful degradation — plus
bounded upstream socket budgets and the seeded chaos soak (slow)."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.fleet import (BreakerConfig, BreakerState, FaultConfig,
                                 FaultInjector, FleetConfig, FleetRouter,
                                 HttpReplica, ReplicaDied, ReplicaState,
                                 ReplicaUnavailable, RoutingError,
                                 SupervisorConfig)
from deepspeed_tpu.fleet.supervisor import ReplicaSupervisor, SlotState


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def _fleet_config(**kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("retry_backoff_base_s", 0.0)  # deterministic test retries
    kw.setdefault("breaker", BreakerConfig(failure_threshold=2,
                                           open_cooldown_s=0.1))
    return FleetConfig(**kw)


def _snapshot(name):
    series = telemetry.get_registry().snapshot().get(name, [])
    return sum(v for _, v in series)


# ---------------------------------------------------------------------------
# the injector itself
# ---------------------------------------------------------------------------
def test_schedule_is_deterministic_and_matches_live_fires():
    cfg = FaultConfig(enabled=True, seed=42, connect_reset_p=0.25,
                      http_5xx_p=0.2, http_5xx_burst=3,
                      park_store_corrupt_p=0.3, demote_race_p=0.3)
    a, b = FaultInjector(cfg), FaultInjector(cfg)
    for point, scope in (("connect_reset", "r0"), ("http_5xx", "r1"),
                         ("http_5xx", None), ("park_store_corrupt", "sess-0"),
                         ("demote_race", "r0")):
        live = [n for n in (a.fire(point, scope) for _ in range(200))
                if n is not None]
        assert live == a.schedule(point, 200, scope)      # live == pure oracle
        assert live == b.schedule(point, 200, scope)      # fresh instance agrees
        assert live, f"nothing fired at {point} in 200 events — p rotted?"
    # a different seed is a different schedule
    other = FaultInjector(FaultConfig(enabled=True, seed=43,
                                      connect_reset_p=0.25))
    assert (other.schedule("connect_reset", 200, "r0")
            != a.schedule("connect_reset", 200, "r0"))
    # bursts produce consecutive runs (what trips a breaker)
    sched = a.schedule("http_5xx", 500, "burst-scope")
    runs = sum(1 for i in range(1, len(sched)) if sched[i] == sched[i - 1] + 1)
    assert runs > 0, "burst=3 never produced consecutive faults"
    with pytest.raises(ValueError):
        a.fire("not_a_point")
    report = a.report()
    assert report["seed"] == 42 and report["fired"]


def test_router_has_no_injector_by_default_and_env_arms_it(make_fleet,
                                                           monkeypatch):
    manager = make_fleet(roles=("mixed",))
    assert FleetRouter(manager)._faults is None  # production default
    monkeypatch.setenv("DSTPU_FAULTS",
                       '{"enabled": true, "seed": 9, "http_5xx_p": 0.5}')
    armed = FleetRouter(manager)
    assert armed._faults is not None and armed._faults.config.seed == 9
    # allow_remote WITHOUT enabled: the chaos endpoint is live but nothing
    # fires until armed over it — a loadgen --chaos baseline stays fault-free
    monkeypatch.setenv("DSTPU_FAULTS", '{"allow_remote": true}')
    remote_only = FleetRouter(manager)
    assert remote_only._faults is None and remote_only._chaos_remote
    monkeypatch.setenv("DSTPU_FAULTS", '{"enabled": fal')  # malformed
    with pytest.raises(Exception):
        FleetRouter(manager)  # a typo'd chaos config must not run clean


# ---------------------------------------------------------------------------
# breaker cycle under injected faults (acceptance: open -> half-open ->
# closed observed in fleet_* metrics)
# ---------------------------------------------------------------------------
def test_injected_5xx_trips_breakers_then_recovery_closes_them(make_fleet):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    manager = make_fleet(roles=("mixed", "mixed"), config=_fleet_config())
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(enabled=True, seed=0, http_5xx_p=1.0))
    # every dispatch attempt 503s: each request feeds one failure to each
    # replica's breaker; threshold=2 opens both after two requests
    for _ in range(2):
        with pytest.raises(RoutingError):
            router.route({"prompt": _prompt(), "max_new_tokens": 2}).result()
    replicas = manager.replicas()
    assert all(r.breaker.state is BreakerState.OPEN for r in replicas)
    assert _snapshot("fleet_breaker_opens_total") == 2
    assert _snapshot("fleet_breaker_open_replicas") == 2
    # an OPEN breaker short-circuits candidacy: no pool at all
    with pytest.raises(RoutingError) as err:
        router.route({"prompt": _prompt(), "max_new_tokens": 2})
    assert "0 in pool" in str(err.value)
    assert _snapshot("fleet_routing_failures_total") >= 3
    # the fault clears; after the cooldown the HALF_OPEN trial dispatch
    # succeeds and the breakers close — the full cycle, metric-visible
    router.set_faults(None)
    time.sleep(0.12)
    assert all(r.breaker.state is BreakerState.HALF_OPEN for r in replicas)
    doc = router.route({"prompt": _prompt(), "max_new_tokens": 2}).result()
    assert doc["state"] == "DONE"
    assert any(r.breaker.state is BreakerState.CLOSED for r in replicas)
    assert _snapshot("fleet_breaker_closes_total") >= 1
    assert _snapshot("fleet_faults_injected_total") >= 4
    # /v1/fleet/stats surfaces breaker state + the injector report
    stats = router.fleet_stats()
    assert all(row["breaker"]["opens"] >= 1 for row in stats["replicas"])


def test_half_open_admits_bounded_trials_only(make_fleet):
    manager = make_fleet(roles=("mixed",), config=_fleet_config())
    replica = manager.replicas()[0]
    replica.breaker.record_failure()
    replica.breaker.record_failure()
    assert replica.breaker.state is BreakerState.OPEN
    time.sleep(0.12)
    assert replica.breaker.try_acquire()       # the one trial slot
    assert not replica.breaker.try_acquire()   # concurrent peers are refused
    replica.breaker.record_failure()           # trial failed: OPEN again,
    assert replica.breaker.state is BreakerState.OPEN
    d = replica.breaker.describe()
    assert d["open_episodes"] == 2             # with a scaled cooldown


# ---------------------------------------------------------------------------
# mid-stream death: single leg dies loudly, decode leg re-dispatches
# ---------------------------------------------------------------------------
def test_stream_truncation_single_leg_is_a_loud_502(make_fleet):
    manager = make_fleet(roles=("mixed",), config=_fleet_config())
    replica = manager.replicas()[0]
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(enabled=True, seed=1, stream_truncate_p=1.0,
                                  stream_truncate_max_tokens=2))
    routed = router.route({"prompt": _prompt(), "max_new_tokens": 8})
    with pytest.raises(ReplicaDied):
        routed.result()
    # the death fed the breaker and the replica-side request reached a
    # terminal state with its KV freed (the truncation cancels the leg)
    assert replica.breaker.describe()["consecutive_failures"] >= 1
    deadline = time.monotonic() + 10
    while replica.scheduler.n_active and time.monotonic() < deadline:
        time.sleep(0.01)
    assert replica.scheduler.n_active == 0
    assert replica.engine._state_manager.n_tracked_sequences == 0


def _make_disagg(make_fleet, decode_ids=("d0", "d1")):
    manager = make_fleet(roles=(), config=_fleet_config())
    manager.add_local(role="prefill", replica_id="p0")
    for rid in decode_ids:
        manager.add_local(role="decode", replica_id=rid)
    return manager


def test_decode_leg_death_redispatches_once_token_identical(make_fleet):
    """The ISSUE satellite: a decode replica dying mid-leg no longer 502s —
    the still-buffered handoff payload re-dispatches to a peer once, the
    token-identical resume's already-streamed prefix is skipped, and the
    client sees one seamless, byte-identical stream."""
    manager = _make_disagg(make_fleet)
    router = FleetRouter(manager)
    doc = {"prompt": _prompt(17), "max_new_tokens": 7}
    expected = router.route(dict(doc)).result()  # fault-free baseline
    assert expected["state"] == "DONE" and len(expected["tokens"]) == 7

    # a seed whose schedule kills d0's first streamed leg but spares d1
    # (dispatch order is deterministic: load ties break by id, d0 first)
    seed = next(s for s in range(1000)
                if (i := FaultInjector(FaultConfig(
                    enabled=True, seed=s, stream_truncate_p=0.5,
                    stream_truncate_max_tokens=2))).would_fire(
                        "stream_truncate", 0, "d0")
                and not i.would_fire("stream_truncate", 0, "d1"))
    router.set_faults(FaultConfig(enabled=True, seed=seed,
                                  stream_truncate_p=0.5,
                                  stream_truncate_max_tokens=2))
    routed = router.route(dict(doc))
    streamed = list(routed.tokens())
    final = routed.result()
    assert final["state"] == "DONE"
    assert final["tokens"] == expected["tokens"], "resume must be token-identical"
    assert streamed == expected["tokens"], "client stream must be seamless"
    kinds = [(m["kind"], m["replica"]) for m in final["legs"]]
    assert kinds[0] == ("prefill", "p0")
    assert kinds[-1] == ("decode", "d1"), f"decode must re-land on d1: {kinds}"
    # d0's dead leg reached a terminal state; nothing leaked anywhere
    for rid in ("d0", "d1"):
        replica = manager.get(rid)
        deadline = time.monotonic() + 10
        while replica.scheduler.n_active and time.monotonic() < deadline:
            time.sleep(0.01)
        assert replica.engine._state_manager.n_tracked_sequences == 0, rid


def test_corrupted_handoff_is_rejected_and_retried_pristine(make_fleet):
    """Corruption-in-transit: the decode replica rejects the payload loudly
    (never silent wrong tokens); the router re-sends its pristine buffered
    copy and the request completes token-identically."""
    manager = _make_disagg(make_fleet, decode_ids=("d0",))
    router = FleetRouter(manager)
    doc = {"prompt": _prompt(11), "max_new_tokens": 5}
    expected = router.route(dict(doc)).result()
    seed = next(s for s in range(1000)
                if (i := FaultInjector(FaultConfig(
                    enabled=True, seed=s, handoff_corrupt_p=0.5))).would_fire(
                        "handoff_corrupt", 0, "d0")
                and not i.would_fire("handoff_corrupt", 1, "d0"))
    router.set_faults(FaultConfig(enabled=True, seed=seed,
                                  handoff_corrupt_p=0.5))
    final = router.route(dict(doc)).result()
    assert final["state"] == "DONE"
    assert final["tokens"] == expected["tokens"]
    d0 = manager.get("d0")
    assert d0.engine._state_manager.n_tracked_sequences == 0


def test_replica_kill_fails_over_and_leaves_no_half_dead_replica(make_fleet):
    manager = make_fleet(roles=(), config=_fleet_config())
    manager.add_local(role="mixed", replica_id="m0")
    manager.add_local(role="mixed", replica_id="m1")
    router = FleetRouter(manager)
    seed = next(s for s in range(1000)
                if (i := FaultInjector(FaultConfig(
                    enabled=True, seed=s, replica_kill_p=0.5))).would_fire(
                        "replica_kill", 0, "m0")
                and not i.would_fire("replica_kill", 0, "m1"))
    router.set_faults(FaultConfig(enabled=True, seed=seed, replica_kill_p=0.5))
    doc = router.route({"prompt": _prompt(), "max_new_tokens": 3}).result()
    assert doc["state"] == "DONE"            # failover absorbed the kill
    m0, m1 = manager.get("m0"), manager.get("m1")
    assert m0.state is ReplicaState.DOWN     # killed outright, not half-dead
    assert doc["legs"][0]["replica"] == "m1"
    assert m0.scheduler._stopped             # kill disposition ran
    router.set_faults(None)
    doc2 = router.route({"prompt": _prompt(), "max_new_tokens": 2}).result()
    assert doc2["state"] == "DONE" and doc2["legs"][0]["replica"] == "m1"


# ---------------------------------------------------------------------------
# graceful degradation
# ---------------------------------------------------------------------------
def test_decode_pool_dark_degrades_to_monolithic_counted(make_fleet):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    manager = _make_disagg(make_fleet, decode_ids=("d0",))
    router = FleetRouter(manager)
    baseline = router.route({"prompt": _prompt(), "max_new_tokens": 4}).result()
    assert [m["kind"] for m in baseline["legs"]] == ["prefill", "decode"]
    assert "degraded" not in baseline
    # the whole decode pool goes dark (breaker OPEN — drained/quarantined
    # behave identically through _dispatchable)
    d0 = manager.get("d0")
    d0.breaker.record_failure()
    d0.breaker.record_failure()
    assert d0.breaker.state is BreakerState.OPEN
    final = router.route({"prompt": _prompt(), "max_new_tokens": 4}).result()
    assert final["state"] == "DONE", "degradation must serve, not 502"
    assert final["degraded"] is True
    assert [m["kind"] for m in final["legs"]] == ["serve"]  # monolithic
    assert final["legs"][0]["replica"] == "p0"
    assert _snapshot("fleet_degraded_requests_total") == 1
    assert router.fleet_stats()["router"]["degraded"] == 1


def test_decode_death_with_no_decode_peer_degrades_mid_request(make_fleet):
    """Mid-request degradation: the only decode replica is killed at its
    dispatch; the buffered payload resumes on the surviving prefill replica
    (counted), instead of 502ing a request whose prefill is already paid."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    manager = _make_disagg(make_fleet, decode_ids=("d0",))
    router = FleetRouter(manager)
    doc = {"prompt": _prompt(13), "max_new_tokens": 6}
    expected = router.route(dict(doc)).result()
    seed = next(s for s in range(1000)
                if (i := FaultInjector(FaultConfig(
                    enabled=True, seed=s, replica_kill_p=0.5))).would_fire(
                        "replica_kill", 0, "d0")
                and not i.would_fire("replica_kill", 0, "p0")
                and not i.would_fire("replica_kill", 1, "p0"))
    router.set_faults(FaultConfig(enabled=True, seed=seed, replica_kill_p=0.5))
    final = router.route(dict(doc)).result()
    assert final["state"] == "DONE"
    assert final["tokens"] == expected["tokens"]
    assert final["degraded"] is True
    assert final["legs"][-1]["kind"] == "decode"
    assert final["legs"][-1]["replica"] == "p0"  # resumed on the survivor
    assert _snapshot("fleet_degraded_requests_total") == 1


# ---------------------------------------------------------------------------
# chaos control endpoint
# ---------------------------------------------------------------------------
def test_chaos_endpoint_is_403_unless_explicitly_allowed(make_fleet):
    manager = make_fleet(roles=("mixed",))
    router = FleetRouter(manager).start()
    try:
        req = urllib.request.Request(
            router.url + "/v1/fleet/chaos",
            data=json.dumps({"enabled": True, "seed": 1}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 403
        assert router._faults is None
    finally:
        router.stop(drain=False)


def test_chaos_endpoint_arms_reseedss_and_disarms(make_fleet):
    manager = make_fleet(roles=("mixed",),
                         config=_fleet_config(
                             faults=FaultConfig(allow_remote=True)))
    router = FleetRouter(manager).start()
    try:
        def post(body):
            req = urllib.request.Request(
                router.url + "/v1/fleet/chaos", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())
        out = post({"enabled": True, "seed": 7, "dispatch_delay_p": 1.0,
                    "dispatch_delay_max_s": 0.001})
        assert out == {"enabled": True, "seed": 7}
        assert router._faults is not None and router._faults.config.seed == 7
        doc = router.route({"prompt": _prompt(), "max_new_tokens": 2}).result()
        assert doc["state"] == "DONE"
        stats = json.loads(urllib.request.urlopen(
            router.url + "/v1/fleet/stats", timeout=10).read())
        assert stats["faults"]["fired"].get("dispatch_delay", 0) >= 1
        assert post({"enabled": False}) == {"enabled": False, "seed": 0}
        assert router._faults is None
    finally:
        router.stop(drain=False)


# ---------------------------------------------------------------------------
# bounded socket budgets (ISSUE satellite)
# ---------------------------------------------------------------------------
def test_blackholed_upstream_bounded_by_read_budget():
    """An upstream that accepts and then goes silent pins the dispatch thread
    for the READ budget, not timeout_s=120; the failure is the breaker-grade
    ReplicaUnavailable."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(8)
    host, port = listener.getsockname()
    try:
        replica = HttpReplica(f"http://{host}:{port}", replica_id="blackhole",
                              connect_timeout_s=0.5, read_timeout_s=0.3)
        t0 = time.monotonic()
        with pytest.raises(ReplicaUnavailable) as err:
            replica.dispatch({"prompt": [1, 2], "max_new_tokens": 2})
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, f"dispatch pinned for {elapsed:.1f}s"
        assert "timeout" in str(err.value)
        assert err.value.status == 0  # transport-class: a breaker signal
        # probes are bounded too, and failed probes back off: the second
        # probe inside the backoff window serves the cached error doc
        # without touching the socket again
        t0 = time.monotonic()
        doc = replica.probe(max_age_s=0.0)
        assert not doc["healthy"] and "error" in doc
        first_at = replica._probe_at
        assert replica.probe(max_age_s=0.0) is doc
        assert replica._probe_at == first_at
        assert time.monotonic() - t0 < 5.0
    finally:
        listener.close()


def test_wedged_upstream_dies_by_progress_ceiling_despite_keepalives(make_engine):
    """A live-but-wedged replica (scheduler halted, HTTP handler still
    emitting SSE keepalives) dies by the whole-leg progress ceiling — the
    keepalives prove the process lives, so the read budget alone can't catch
    it, and must not."""
    from deepspeed_tpu.serving import ServingConfig, ServingScheduler, ServingServer
    engine = make_engine()
    scheduler = ServingScheduler(engine, ServingConfig(sse_keepalive_s=0.05))
    server = ServingServer(scheduler).start()
    try:
        scheduler.submit(_prompt(), max_new_tokens=2).result()  # XLA warm-up
        replica = HttpReplica(server.url, replica_id="stall",
                              connect_timeout_s=1.0, read_timeout_s=0.5,
                              timeout_s=1.2)
        leg = replica.dispatch({"prompt": _prompt(), "max_new_tokens": 200})
        first = next(iter(leg))
        assert isinstance(first, int)
        scheduler._shutdown = True  # wedge: loop exits, stream never closes
        t0 = time.monotonic()
        with pytest.raises(ReplicaDied, match="no token progress"):
            leg.result(timeout=10)
        elapsed = time.monotonic() - t0
        assert 0.5 < elapsed < 6.0, elapsed  # ceiling, not the read budget
    finally:
        scheduler._shutdown = True
        server.stop(drain=False)


def test_slow_but_alive_replica_survives_the_read_budget(make_engine):
    """Load is not breakage: a replica whose first token takes much longer
    than read_timeout_s (deep queue, long prefill) keepalives its way
    through the read budget and completes normally — no ReplicaDied, no
    breaker food."""
    from deepspeed_tpu.serving import ServingConfig, ServingScheduler, ServingServer
    engine = make_engine()
    scheduler = ServingScheduler(engine, ServingConfig(sse_keepalive_s=0.05),
                                 start=False)  # manual stepping = a stall knob
    server = ServingServer(scheduler).start()
    try:
        # read budget 10x the keepalive interval: the property under test
        # (keepalives, not tokens, satisfy the read budget) is unchanged —
        # TTFT is still >> read_timeout_s — but a whole-suite run on the
        # 1-CPU tier-1 host can starve the SSE handler past a 6x margin
        replica = HttpReplica(server.url, replica_id="slow",
                              connect_timeout_s=1.0, read_timeout_s=0.5,
                              timeout_s=120.0)
        leg = replica.dispatch({"prompt": _prompt(), "max_new_tokens": 3})
        time.sleep(1.0)  # TTFT >> read_timeout_s: only keepalives flow

        def drive():
            for _ in range(5000):
                if scheduler._counters["completed"] >= 1:
                    return
                if not scheduler.step():
                    time.sleep(0.005)

        driver = threading.Thread(target=drive, daemon=True)
        driver.start()
        final = leg.result(timeout=120)
        driver.join(timeout=60)
        assert final["state"] == "DONE" and len(final["tokens"]) == 3
    finally:
        scheduler.stop(drain=False)
        server.stop(drain=False)


# ---------------------------------------------------------------------------
# the seeded chaos soak (slow)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_seeded_chaos_soak_every_request_terminal_no_leaks(make_fleet):
    """The acceptance run: kills + resets + 5xx + delays + truncations +
    corrupted handoffs against a supervised disaggregated fleet under
    concurrent load. Every request reaches a terminal state, nothing leaks
    KV or sequences, no thread hangs, at least one automatic restart and one
    breaker open happen, and the identical seed reproduces the identical
    fault schedule."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    fault_config = FaultConfig(
        enabled=True, seed=1234,
        dispatch_delay_p=0.10, dispatch_delay_max_s=0.005,
        connect_reset_p=0.05, http_5xx_p=0.05, http_5xx_burst=3,
        stream_truncate_p=0.04, stream_truncate_max_tokens=3,
        handoff_corrupt_p=0.04, replica_kill_p=0.02)
    manager = make_fleet(roles=(), config=_fleet_config(), num_blocks=96)
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        poll_interval_s=0.05, ready_timeout_s=60.0,
        restart_backoff_base_s=0.05, restart_backoff_cap_s=0.2,
        restart_jitter_frac=0.1, max_crashes=50, crash_window_s=600.0, seed=7))
    for role in ("prefill", "prefill", "decode", "decode"):
        supervisor.add_local(role=role)
    supervisor.start()
    assert supervisor.wait_ready(timeout=300.0)
    router = FleetRouter(manager)
    router.set_faults(FaultConfig(**fault_config.model_dump()))

    n_requests = 200
    rng = np.random.default_rng(0)
    outcomes = []
    lock = threading.Lock()
    thread_floor = threading.active_count()

    def one(i):
        prompt = rng.integers(0, 64, int(rng.integers(4, 32))).tolist()
        doc = {"prompt": prompt, "max_new_tokens": int(rng.integers(2, 10)),
               "temperature": 0.7 if i % 3 == 0 else 0.0, "seed": i}
        try:
            routed = router.route(doc)
            final = routed.result()
            with lock:
                outcomes.append((final["state"], i))
        except (RoutingError, ReplicaDied, RuntimeError, ValueError) as e:
            # under chaos some requests legitimately fail — but they must
            # fail TERMINALLY and promptly, never hang
            with lock:
                outcomes.append((f"refused:{type(e).__name__}", i))

    threads = [threading.Thread(target=one, args=(i, )) for i in range(n_requests)]
    for batch in range(0, n_requests, 8):
        group = threads[batch:batch + 8]
        for t in group:
            t.start()
        for t in group:
            t.join(timeout=300)
            assert not t.is_alive(), "chaos request wedged — not terminal"

    assert len(outcomes) == n_requests  # every request reached a terminal state
    done = sum(1 for s, _ in outcomes if s == "DONE")
    assert done >= n_requests // 2, f"chaos drowned the fleet: {done} DONE"

    # at least one automatic restart and one breaker open, metric-visible
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not supervisor.wait_ready(timeout=1.0):
        pass
    assert _snapshot("fleet_restarts_total") >= 1, "no automatic restart"
    assert _snapshot("fleet_breaker_opens_total") >= 1, "no breaker trip"
    assert _snapshot("fleet_faults_injected_total") >= 10

    # quiesce, then the leak sweep over every LIVE engine
    router.set_faults(None)
    supervisor.stop()
    deadline = time.monotonic() + 60
    for replica in manager.replicas():
        if replica.state is not ReplicaState.UP:
            continue
        sched = replica.scheduler
        while ((sched.n_active or sched.queue_depth)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert sched.n_active == 0 and sched.queue_depth == 0, replica.id
        assert replica.engine._state_manager.n_tracked_sequences == 0, replica.id
        assert replica.engine.free_blocks == 96, \
            f"{replica.id} leaked {96 - replica.engine.free_blocks} KV blocks"

    # no hung threads beyond the replica schedulers that are still serving
    live_threads = threading.active_count()
    assert live_threads <= thread_floor + len(manager.replicas()) + 4, \
        f"thread leak: {live_threads} alive (floor {thread_floor})"

    # identical seed -> identical fault schedule: the pure-schedule property
    # the live run rode on, recomputed by two fresh injectors
    fresh = FaultInjector(FaultConfig(**fault_config.model_dump()))
    again = FaultInjector(FaultConfig(**fault_config.model_dump()))
    for point in ("connect_reset", "http_5xx", "replica_kill"):
        assert fresh.schedule(point, 300, "scope") == again.schedule(point, 300, "scope")


# ---------------------------------------------------------------------------
# loadgen chaos mode (ISSUE satellite)
# ---------------------------------------------------------------------------
def test_loadgen_chaos_mode_prints_recovery_report(make_fleet):
    """bin/dstpu_loadgen --chaos <seed>: baseline half, remote-armed fault
    injection half, recovery report with restarts / breaker trips / degraded
    counts and the p99 delta."""
    import os
    import subprocess
    import sys
    manager = make_fleet(roles=("mixed", "mixed"),
                         config=_fleet_config(
                             faults=FaultConfig(allow_remote=True)))
    router = FleetRouter(manager).start()
    bin_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "bin")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(bin_dir, "dstpu_loadgen"),
             "--target", router.url, "--requests", "8", "--concurrency", "2",
             "--prompt-len", "6", "--max-new-tokens", "3", "--vocab-size", "64",
             "--chaos", "7",
             "--chaos-profile",
             '{"dispatch_delay_p": 1.0, "dispatch_delay_max_s": 0.002,'
             ' "connect_reset_p": 0.0, "http_5xx_p": 0.0,'
             ' "stream_truncate_p": 0.0, "handoff_corrupt_p": 0.0,'
             ' "replica_kill_p": 0.0}'],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-800:] + r.stdout[-800:]
        assert "# chaos seed=7" in r.stdout
        assert "# recovery report" in r.stdout
        assert "faults injected" in r.stdout
        assert "breaker trips" in r.stdout
        assert "p99 e2e" in r.stdout
        assert "8/8 requests reached a terminal outcome" in r.stdout
        # the injector was disarmed at the end of the run
        assert router._faults is None
        # delays actually fired (dispatch_delay_p=1.0, 4 chaos requests)
        fired = [line for line in r.stdout.splitlines()
                 if "faults injected" in line][0]
        assert "dispatch_delay" in fired
    finally:
        router.stop(drain=False)
