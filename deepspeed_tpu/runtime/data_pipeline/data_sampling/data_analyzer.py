"""Offline metric indexing over a corpus (the data-efficiency analysis tier).

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py:20``
(DataAnalyzer — map workers compute per-sample metric values, reduce merges
them into ``sample_to_metric`` / ``metric_to_sample`` index files the
curriculum sampler consumes at train time).

TPU formulation: the map phase is host-parallel (thread pool over dataset
shards — metric fns are numpy; the reference's multi-process launcher
collapses to threads since there is no per-GPU affinity to respect), the
reduce phase merges shard outputs into:

- ``{metric}_sample_to_metric.npy`` — value per sample (difficulty array; the
  curriculum ``DeepSpeedDataSampler`` consumes exactly this), and
- ``{metric}_metric_to_sample.npz`` — value → sample-id arrays (the
  reference's per-value index files, one array per distinct value), plus
- ``{metric}_percentiles.npy`` for threshold scheduling.

Metric types follow the reference: ``single_value_per_sample`` (a value per
sample) and ``accumulate_value_over_samples`` (a running reduction, e.g. a
vocab histogram).
"""

import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


class DataAnalyzer:

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable],
                 metric_types: Sequence[str] = None,
                 save_path: str = "./data_analysis",
                 num_workers: int = 1, worker_id: int = 0,
                 num_threads: int = 4, batch_size: int = 1024,
                 metric_dtypes: Sequence = None):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types) if metric_types else \
            ["single_value_per_sample"] * len(self.metric_names)
        self.metric_dtypes = list(metric_dtypes) if metric_dtypes else \
            [np.int64] * len(self.metric_names)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.num_threads = max(1, num_threads)
        self.batch_size = batch_size

    # ----------------------------------------------------------------- map --
    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self) -> None:
        """Compute this worker's shard of every metric; one .npy per
        (metric, thread-shard) under save_path/worker_{id}/."""
        lo, hi = self._worker_range()
        wdir = os.path.join(self.save_path, f"worker_{self.worker_id}")
        os.makedirs(wdir, exist_ok=True)
        bounds = np.linspace(lo, hi, self.num_threads + 1).astype(np.int64)

        def one_thread(t):
            t_lo, t_hi = int(bounds[t]), int(bounds[t + 1])
            out = {m: [] for m in self.metric_names}
            for i in range(t_lo, t_hi):
                sample = self.dataset[i]
                for m, fn, typ in zip(self.metric_names, self.metric_functions,
                                      self.metric_types):
                    out[m].append(fn(sample))
            for m, typ, dt in zip(self.metric_names, self.metric_types, self.metric_dtypes):
                if typ == "single_value_per_sample":
                    arr = np.asarray(out[m], dtype=dt)
                else:  # accumulate_value_over_samples
                    arr = np.sum(np.stack(out[m]), axis=0).astype(dt) if out[m] else \
                        np.zeros(0, dt)
                np.save(os.path.join(wdir, f"{m}_thread{t}.npy"), arr)
            return t_hi - t_lo

        with ThreadPoolExecutor(max_workers=self.num_threads) as pool:
            done = sum(pool.map(one_thread, range(self.num_threads)))
        with open(os.path.join(wdir, "map_done.json"), "w") as f:
            json.dump({"lo": int(lo), "hi": int(hi), "threads": self.num_threads}, f)
        logger.info(f"data_analyzer worker {self.worker_id}: mapped {done} samples")

    # -------------------------------------------------------------- reduce --
    def run_reduce(self) -> Dict[str, np.ndarray]:
        """Merge every worker's shards into the train-time index files."""
        os.makedirs(self.save_path, exist_ok=True)
        results = {}
        for m, typ in zip(self.metric_names, self.metric_types):
            parts = []
            for w in range(self.num_workers):
                wdir = os.path.join(self.save_path, f"worker_{w}")
                with open(os.path.join(wdir, "map_done.json")) as f:
                    meta = json.load(f)
                for t in range(meta["threads"]):
                    parts.append(np.load(os.path.join(wdir, f"{m}_thread{t}.npy")))
            if typ == "single_value_per_sample":
                merged = np.concatenate(parts)
                np.save(os.path.join(self.save_path, f"{m}_sample_to_metric.npy"), merged)
                values, inverse = np.unique(merged, return_inverse=True)
                np.savez(os.path.join(self.save_path, f"{m}_metric_to_sample.npz"),
                         **{str(v): np.nonzero(inverse == j)[0]
                            for j, v in enumerate(values)})
                pct = np.percentile(merged, np.arange(0, 101))
                np.save(os.path.join(self.save_path, f"{m}_percentiles.npy"), pct)
            else:
                merged = np.sum(np.stack([p for p in parts if p.size], axis=0), axis=0)
                np.save(os.path.join(self.save_path, f"{m}_accumulated.npy"), merged)
            results[m] = merged
        logger.info(f"data_analyzer reduce: wrote indices for {self.metric_names} "
                    f"under {self.save_path}")
        return results

    def run_map_reduce(self) -> Dict[str, np.ndarray]:
        """Single-process convenience: every worker's map, then reduce."""
        me = self.worker_id
        for w in range(self.num_workers):
            self.worker_id = w
            self.run_map()
        self.worker_id = me
        return self.run_reduce()

    # ------------------------------------------------------------- consume --
    @staticmethod
    def sample_to_metric_path(save_path: str, metric_name: str) -> str:
        return os.path.join(save_path, f"{metric_name}_sample_to_metric.npy")

    @staticmethod
    def load_difficulties(save_path: str, metric_name: str) -> np.ndarray:
        """The curriculum sampler's difficulty array (one value per sample)."""
        return np.load(DataAnalyzer.sample_to_metric_path(save_path, metric_name))

    @staticmethod
    def get_metric_value_percentiles(save_path: str, metric_name: str) -> np.ndarray:
        return np.load(os.path.join(save_path, f"{metric_name}_percentiles.npy"))
