"""Monitor config (reference: deepspeed/monitor/config.py)."""

from typing import Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JSONLConfig(DeepSpeedConfigModel):
    """Append-only JSONL event stream (one ``{"tag", "value", "step", "ts"}``
    object per line) — the tail-able backend the telemetry layer reads."""
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = {}
    wandb: WandbConfig = {}
    csv_monitor: CSVConfig = {}
    jsonl: JSONLConfig = {}

    @property
    def enabled(self):
        return self.tensorboard.enabled or self.wandb.enabled \
            or self.csv_monitor.enabled or self.jsonl.enabled
