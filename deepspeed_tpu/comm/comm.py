"""Public collectives API over XLA.

TPU-native analog of ``deepspeed/comm/comm.py`` (the torch.distributed-compatible
surface: all_reduce / all_gather_into_tensor / reduce_scatter_tensor /
all_to_all_single / broadcast / barrier, plus ``init_distributed`` with env
discovery and the ``@timed_op`` comms-profiling wrapper, comm.py:101-771).

SPMD semantics
--------------
The reference's collectives act on *per-rank local tensors*. Under single-controller
SPMD the equivalent is a jax.Array sharded over the group's mesh axes along its
leading dimension — shard i plays the role of rank i's local tensor:

  - ``all_reduce(x, group)``:    x:[G, ...] sharded on dim0 → each shard replaced by
                                 the elementwise reduction over shards (shape kept).
  - ``all_gather_into_tensor``:  x:[G, s, ...] sharded on dim0 → [G*s, ...] fully
                                 replicated (torch-style concat along dim0).
  - ``reduce_scatter_tensor``:   x:[G, G*s, ...] sharded dim0 → [G, s, ...] sharded
                                 dim0; shard i = sum over ranks of slice i.
  - ``all_to_all_single``:       x:[G, G, ...] sharded dim0 → transpose of rank/chunk.
  - ``broadcast(x, src)``:       every shard replaced by shard ``src``.

``group`` is a mesh-axis name or tuple of names (see utils/groups.py); None means
the dense data-parallel group. These eager wrappers are for host-driven code and
tests; inside a jitted train step use ``jax.lax`` collectives directly — the engine
does — so XLA can fuse and overlap them.
"""

import functools
import os
import time

import numpy as np

from deepspeed_tpu.comm.backend import Backend
from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils import groups as groups_mod
from deepspeed_tpu.utils.comms_logging import CommsLogger
from deepspeed_tpu.utils.logging import logger

cdb = None  # current distributed backend (reference: comm.py:41)
comms_logger = CommsLogger()
timers = {}


class XLABackend(Backend):
    """The one backend: XLA collectives over the global mesh (ICI/DCN)."""

    def __init__(self):
        import jax
        super().__init__(name="xla", rank=jax.process_index(), size=jax.process_count())
        self.init_process_group()


def is_initialized():
    return cdb is not None


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1):
    """Bootstrap multi-host JAX + build the global mesh.

    Reference: comm.py:604-771 (init_distributed with MPI/AML/SageMaker discovery
    feeding torch.distributed rendezvous). Here the rendezvous is JAX's coordination
    service: on multi-host launches we call ``jax.distributed.initialize`` with
    coordinator discovery from env (DSTPU_COORDINATOR / MASTER_ADDR, or OpenMPI vars
    as in the reference's ``mpi_discovery``).
    """
    global cdb
    if cdb is not None:
        return cdb
    import jax

    coord = os.environ.get("DSTPU_COORDINATOR") or os.environ.get("COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("DSTPU_NUM_PROCESSES", os.environ.get("WORLD_SIZE", "0")) or 0)
    proc_id = os.environ.get("DSTPU_PROCESS_ID", os.environ.get("RANK"))
    if coord is None and auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in os.environ:
        # OpenMPI discovery, reference comm.py mpi_discovery()
        nproc = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        proc_id = os.environ["OMPI_COMM_WORLD_RANK"]
        coord = f"{os.environ.get('MASTER_ADDR', 'localhost')}:{distributed_port}"
    if coord is not None and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc,
                                   process_id=int(proc_id or 0))
        if verbose:
            logger.info(f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}")
    cdb = XLABackend()
    return cdb


def destroy_process_group(group=None):
    global cdb
    cdb = None


def get_rank(group=None):
    """Host process rank (reference rank == device rank; under SPMD one process
    drives many devices, so this is the process index)."""
    import jax
    return jax.process_index()


def get_world_size(group=None):
    """Number of devices in ``group`` (mesh axes), or all devices if None."""
    import jax
    if group is None:
        return len(jax.devices())
    return groups_mod._axis_size(group)


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


# ---- eager collective implementations --------------------------------------------


def _resolve_group(group):
    if group is None:
        group = groups_mod.get_data_parallel_axes()
    if isinstance(group, str):
        group = (group, )
    return tuple(group)


def _group_spec(axes):
    from jax.sharding import PartitionSpec as P
    return P(axes)


_REDUCE_FNS = None


def _reduce_fn(op):
    import jax
    import jax.numpy as jnp
    global _REDUCE_FNS
    if _REDUCE_FNS is None:
        _REDUCE_FNS = {
            ReduceOp.SUM: jax.lax.psum,
            ReduceOp.AVG: lambda x, ax: jax.lax.pmean(x, ax),
            ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
            ReduceOp.PRODUCT: lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)),
        }
    if op not in _REDUCE_FNS:
        raise NotImplementedError(f"ReduceOp {op} not supported")
    return _REDUCE_FNS[op]


def timed_op(func):
    """Profile collectives through the comms logger (reference: comm.py:101-134)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        name = func.__name__
        if comms_logger.enabled:
            import jax
            t0 = time.time()
            result = func(*args, **kwargs)
            jax.block_until_ready(result)
            elapsed = time.time() - t0
            tensor = args[0] if args else kwargs.get("tensor")
            size = int(np.prod(tensor.shape)) * tensor.dtype.itemsize if tensor is not None else 0
            comms_logger.append(name, kwargs.get("log_name", name), elapsed, size)
            return result
        return func(*args, **kwargs)

    return wrapper


def _shard_map(fn, in_specs, out_specs):
    import jax
    mesh = groups_mod.get_mesh()
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


def _device_put_grouped(tensor, axes):
    """Lay ``tensor`` out with dim0 sharded over the group axes."""
    import jax
    from jax.sharding import NamedSharding
    mesh = groups_mod.get_mesh()
    sharding = NamedSharding(mesh, _group_spec(axes))
    return jax.device_put(tensor, sharding)


@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    axes = _resolve_group(group)
    red = _reduce_fn(op)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    return _shard_map(lambda x: red(x, axes), spec, spec)(tensor)


@timed_op
def inference_all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    return all_reduce(tensor, op=op, group=group)


@timed_op
def all_gather_into_tensor(tensor, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    from jax.sharding import PartitionSpec as P

    def f(x):
        # x: [G_local=1, s, ...] → concat over group → [G*s, ...]
        g = jax.lax.all_gather(x, axes, axis=0, tiled=True)
        return g.reshape((-1, ) + g.shape[2:])

    return _shard_map(f, spec, P())(tensor)


# legacy name used across the reference
allgather_fn = all_gather_into_tensor


@timed_op
def reduce_scatter_tensor(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    red = "sum" if op in (ReduceOp.SUM, ReduceOp.AVG) else None
    if red is None:
        raise NotImplementedError("reduce_scatter supports SUM/AVG")
    G = groups_mod._axis_size(axes)

    def f(x):
        # x: [1, G*s, ...] per rank → scatter dim1 into G chunks, sum over ranks
        chunks = x.reshape((G, -1) + x.shape[2:])  # [G, s, ...]
        out = jax.lax.psum_scatter(chunks, axes, scatter_dimension=0, tiled=False)
        if op == ReduceOp.AVG:
            out = out / G
        return out[None]  # [1, s, ...]

    return _shard_map(f, spec, spec)(tensor)


reduce_scatter_fn = reduce_scatter_tensor


@timed_op
def all_to_all_single(tensor, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)

    def f(x):
        # x: [1, G, ...] per rank; exchange chunk j with rank j.
        return jax.lax.all_to_all(x, axes, split_axis=1, concat_axis=0, tiled=False).reshape(x.shape)

    return _shard_map(f, spec, spec)(tensor)


@timed_op
def broadcast(tensor, src=0, group=None, async_op=False, log_name=None):
    import jax
    import jax.numpy as jnp
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)

    def f(x):
        idx = jax.lax.axis_index(axes)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, axes)

    return _shard_map(f, spec, spec)(tensor)


@timed_op
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    # On an SPMD mesh a rooted reduce has no cost advantage over all_reduce.
    return all_reduce(tensor, op=op, group=group)


def barrier(group=None):
    import jax
    jax.effects_barrier()


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    barrier(group)


def log_summary(show_straggler=False):
    """Print per-op communication statistics (reference: comm.py:422)."""
    comms_logger.log_all(print_log=True, show_straggler=show_straggler)


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    comms_logger.configure(deepspeed_config=deepspeed_config,
                           enabled=enabled,
                           prof_all=prof_all,
                           prof_ops=prof_ops,
                           verbose=verbose,
                           debug=debug)
