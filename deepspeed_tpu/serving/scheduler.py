"""Async continuous-batching request scheduler (Dynamic SplitFuse).

Reference: DeepSpeed-FastGen's persistent serving loop (Holmes et al. 2024 —
MII ``RaggedBatchBase.schedule_requests``) and Orca-style iteration-level
scheduling (Yu et al., OSDI'22): requests are admitted continuously, every
engine iteration re-composes the ragged batch from in-flight decodes plus
prompt *chunks* under the token budget, and finished sequences leave the batch
the moment they finish.

The scheduler is the only thing that touches the engine once started —
``InferenceEngineV2`` is not thread-safe, so cancellation, deadline expiry and
shutdown are flags honored at tick boundaries on the scheduler thread, where
KV blocks can be freed safely.

Batch composition per tick (``step()``):

1. finalize cancelled / past-deadline requests (flush their KV blocks);
2. admit QUEUED requests (permanently-infeasible ones FAIL immediately);
3. decode tokens first (latency-critical, one token each), then prompt chunks
   fill the remaining ``max_ragged_batch_size`` budget — Dynamic SplitFuse;
4. under KV pressure: shrink the prompt chunk (halving), then evict the
   coldest idle sequence via ``engine.offload_sequence`` (restore-on-touch is
   transparent) and retry;
5. decode-only batches with ``decode_chunk > 1`` run through the on-device
   ``engine.decode_loop`` (one dispatch per K tokens);
6. idle ticks heartbeat ``engine.empty_run()`` so idle EP replicas stay in
   collective lock-step with busy ones.
"""

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.scheduling_utils import SchedulingError, SchedulingResult
from deepspeed_tpu.serving.config import ServingConfig
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.overload import (BrownoutController, FairSharePolicy,
                                            RateEstimator, priority_rank,
                                            validate_priority)
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.telemetry import new_span_id, new_trace_id, now_us
from deepspeed_tpu.telemetry.flight_recorder import SERVING_SCHEDULER_CHANNEL
from deepspeed_tpu.utils.logging import logger

# ticks with active requests but nothing engine-schedulable before the
# scheduler declares them wedged (covers allocator corner cases the
# permanent-infeasibility admission checks cannot see)
_STARVATION_FAIL_TICKS = 5000

# flight-recorder channel disambiguator for multiple schedulers per process
_SCHEDULER_IDS = itertools.count()


# error-string prefix kill() stamps on every request it fails: the fleet
# router keys on it to tell "this replica died under the request" (retryable
# on a peer — the decode leg re-dispatches) from a semantic engine failure
# (which would reproduce anywhere)
KILLED_ERROR_PREFIX = "replica killed"


_DRAFTER_PINS = ("prompt_lookup", "learned", "auto")


def _validate_drafter_pin(drafter) -> Optional[str]:
    if drafter is None:
        return None
    if drafter not in _DRAFTER_PINS:
        raise ValueError(f"unknown drafter {drafter!r}: "
                         f"expected one of {_DRAFTER_PINS}")
    return drafter


class QueueFullError(RuntimeError):
    """reject-mode backpressure: the submission queue is at capacity."""


class SchedulerStopped(RuntimeError):
    """submit() after stop(): the scheduler no longer admits requests."""


class AdmissionRejected(RuntimeError):
    """Overload control refused the request at submission — the deadline is
    provably unmeetable at the measured rate, or the brownout stage rejects
    its priority class. ``retry_after_s`` is the queue-drain-derived backoff
    the HTTP layer surfaces as a ``Retry-After`` header (429)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServingScheduler:
    """Owns the request lifecycle end-to-end over one :class:`InferenceEngineV2`.

    ``start=False`` skips the background thread; callers (tests, or an outer
    event loop) then drive ``step()`` manually. Exactly one scheduler may be
    attached to an engine at a time; ``engine.close()`` stops it.
    """

    def __init__(self, engine, config: Optional[ServingConfig] = None, start: bool = True):
        if getattr(engine, "_serving_scheduler", None) is not None:
            raise RuntimeError("engine already has an attached ServingScheduler; "
                               "stop it (or engine.close()) first")
        self._engine = engine
        self._config = config or ServingConfig()
        self._metrics = ServingMetrics.maybe_create()
        # per-instance channel: two schedulers under one telemetry session
        # must not clobber each other's provider or heartbeat watch
        self._flight_channel = f"{SERVING_SCHEDULER_CHANNEL}:{next(_SCHEDULER_IDS)}"
        self._flight = None

        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._active: Dict[int, Request] = {}  # uid -> Request, admission order
        # the request _admit popped but has not yet activated (a resume KV
        # import runs in this window, off the lock): it is neither queued nor
        # active, but drain and load accounting must still see it
        self._admitting: Optional[Request] = None
        self._uids = itertools.count()
        self._counters = {k: 0 for k in
                          ("submitted", "rejected", "completed", "cancelled",
                           "timed_out", "failed", "evictions", "batches", "heartbeats",
                           "prefix_hits", "prefix_tokens_saved", "prefix_evictions",
                           "shed_admission", "shed_queue", "brownout_rejected",
                           "brownout_clamped", "spec_drafted", "spec_accepted",
                           "spec_steps", "spec_rollback",
                           "spec_tree_nodes", "spec_tree_compactions",
                           "spec_drafter_switches",
                           "spec_drafted_learned", "spec_accepted_learned",
                           "spec_drafted_lookup", "spec_accepted_lookup",
                           "peer_fetch_hits", "peer_fetch_rejects",
                           "peer_fetch_blocks", "steals",
                           "tier_demotions", "brownout_demotions",
                           "parks", "rehydrates", "fair_share_shed")}
        self._stopping = False   # no new submits
        self._shutdown = False   # thread exit
        self._stopped = False
        self._killed = False     # kill(): abrupt-death disposition ran
        self._kill_reason: Optional[str] = None
        self._ready = threading.Event()  # the loop has started ticking
        self._starved_ticks = 0
        self._start_s = time.monotonic()
        self._last_heartbeat_s = 0.0
        # pool capacity for permanent-infeasibility checks (a prompt needing
        # more KV blocks than the whole pool can never run)
        self._capacity_blocks = engine._state_manager.kv_cache.num_blocks

        # fleet data motion: cross-thread control calls (prefix export for a
        # peer fetch, work-stealing) run on THIS loop via _call_on_loop — the
        # engine, the trie and the block allocator are all single-threaded
        # state, so a probe/handler thread must never touch them directly
        self._control: deque = deque()
        # router-installed hook: fn(digests, have_blocks) -> payload | None.
        # Called on the scheduler thread at admission when the local trie
        # match is shallower than the request's chain; a returned frame is
        # CRC/digest-validated before any block lands.
        self._peer_fetch = None
        # companion hook: fn("hit" | "reject") — lets the fleet layer mirror
        # peer-fetch outcomes into its own metric registry without reaching
        # into scheduler counters
        self._peer_fetch_notify = None

        # overload control (serving/overload.py): the measured-rate estimator
        # feeds admission feasibility + Retry-After; the brownout controller
        # maps smoothed pressure to staged degradation. Both exist even when
        # disabled (stage stays 0, estimator unread) so the hot path is one
        # boolean, not a None check per site.
        ocfg = self._config.overload
        self._rate = RateEstimator(alpha=ocfg.rate_alpha,
                                   min_samples=ocfg.min_rate_samples)
        self._brownout = BrownoutController(
            thresholds=ocfg.brownout_stage_thresholds,
            hysteresis=ocfg.brownout_hysteresis,
            alpha=ocfg.pressure_alpha)
        self._brownout_transitions_seen = 0

        # cost-attribution plane (telemetry/ledger.py + perf/observed.py):
        # both exist only while a telemetry session is active, so every
        # charging site below is one `is not None` check and disabled
        # telemetry pays nothing — the same zero-cost contract as _metrics.
        # The engine's dispatch_observer stashes each jitted call's wall time
        # here (same thread, same tick) for the execute path to attribute.
        ccfg = self._config.cost
        self._ledger = None
        self._perf_obs = None
        self._last_dispatch_s = 0.0
        self._last_dispatch_amnesty_s = 0.0
        if ccfg.enabled and telemetry.is_active():
            from deepspeed_tpu.perf.observed import PerfObservedLedger
            from deepspeed_tpu.telemetry.ledger import CostLedger, PriceBook
            pricebook = PriceBook.from_model_config(
                getattr(getattr(engine, "model", None), "config", None))
            registry = telemetry.get_registry()
            self._ledger = CostLedger(registry, pricebook,
                                      max_tenants=ccfg.max_tenants,
                                      tenant_metric_top_k=ccfg.tenant_metric_top_k,
                                      default_tenant=ccfg.default_tenant)
            self._perf_obs = PerfObservedLedger(
                registry, pricebook, chip=ccfg.perf_chip,
                drift_factor=ccfg.perf_drift_factor,
                drift_consecutive=ccfg.perf_drift_consecutive,
                baseline_dispatches=ccfg.perf_baseline_dispatches)
            engine.dispatch_observer = self._on_dispatch
        # fair-share admission (opt-in): the policy itself is pressure-
        # independent; THIS scheduler gates every consult on brownout stage
        # >= 1, so an uncontended fleet never sheds on share arithmetic
        self._fair_share = None
        if ocfg.enabled and ocfg.fair_share_enabled:
            self._fair_share = FairSharePolicy(
                shares=ocfg.fair_share_shares,
                alpha=ocfg.fair_share_alpha,
                over_factor=ocfg.fair_share_over_factor,
                hysteresis=ocfg.fair_share_hysteresis)

        # automatic prefix caching: radix-tree KV reuse with copy-on-write
        # block sharing (inference/v2/ragged/prefix_cache.py). All trie
        # mutation happens on the scheduler thread — the same thread that owns
        # every other engine touch.
        self._prefix_cache = None
        if self._config.prefix_cache.enabled:
            from deepspeed_tpu.inference.v2.ragged.prefix_cache import PrefixCache
            self._prefix_cache = PrefixCache(
                engine._state_manager.kv_cache,
                max_blocks=self._config.prefix_cache.max_blocks,
                min_prefix_blocks=self._config.prefix_cache.min_prefix_blocks)

        # speculative decoding (inference/v2/spec/): a model-free drafter
        # proposes k continuation tokens per decode step at batch-build time;
        # the engine verifies 1+k positions in one ragged forward and the
        # execute path accepts the longest matching prefix. Trie-backed when
        # the prefix cache runs (the trie holds exactly the token histories a
        # prompt-lookup drafter wants to mine), self-lookup otherwise.
        self._drafter = None
        self._spec_accept_ewma: Optional[float] = None
        self._drafter_mode = "prompt_lookup"
        self._learned = None
        self._spec_head_id: Optional[str] = None
        self._spec_drafter_ewmas: Dict[str, float] = {}
        if self._config.speculative.enabled:
            from deepspeed_tpu.inference.v2.spec import PromptLookupDrafter
            scfg = self._config.speculative
            self._drafter = PromptLookupDrafter(min_ngram=scfg.min_ngram,
                                                max_ngram=scfg.max_ngram,
                                                prefix_cache=self._prefix_cache)
            self._drafter_mode = scfg.drafter
            if scfg.drafter != "prompt_lookup":
                # learned / auto: Medusa-style heads read the target's hidden
                # state and propose token TREES verified by engine.verify_tree;
                # "auto" races them against prompt-lookup per request on
                # measured acceptance EWMAs. Untrained fresh heads are safe —
                # acceptance adapts their k to 0 until dstpu_spec_train runs.
                from deepspeed_tpu.inference.v2.spec import (LearnedDrafter,
                                                             MedusaDraftHead)
                if scfg.draft_head_path:
                    head = MedusaDraftHead.load(scfg.draft_head_path)
                else:
                    mcfg = engine.model.config
                    head = MedusaDraftHead.fresh(mcfg.hidden_size,
                                                 mcfg.vocab_size,
                                                 num_heads=scfg.num_draft_heads)
                self._learned = LearnedDrafter(head, width=scfg.tree_width,
                                               node_budget=scfg.tree_node_budget)
                self._spec_head_id = head.head_id

        # tiered KV memory (serving/kv_tiers.py over ragged/tiering.py):
        # retrofits the engine's host→disk ladder with the operator's budget
        # and drives demote-under-pressure — idle cached state moves down a
        # tier before anything is evicted or shed
        from deepspeed_tpu.serving import kv_tiers as _kv_tiers_mod
        self._kv_tiers = _kv_tiers_mod.maybe_create(
            engine, self._config.kv_tiers, metrics=self._metrics)

        engine._serving_scheduler = self
        # armed last: flight_state() must never observe a half-built
        # scheduler, and an __init__ that raises must not leak a provider or
        # a watched channel (which would guarantee a spurious stall dump);
        # a manually-step()ped scheduler (start=False) has no loop to watch
        self._attach_flight(telemetry.get_flight_recorder(), watch=start)
        self._thread = None
        if start:
            self._thread = threading.Thread(target=self._run, name="dstpu-serving-scheduler",
                                            daemon=True)
            self._thread.start()

    @property
    def _spans(self):
        """The live SpanRecorder (None while telemetry is off) — resolved per
        use, like engine_v2's span/metric fallback, so a telemetry
        reconfigure mid-serve cannot strand the scheduler on a displaced
        recorder; each hot-path use stays one global read + None check."""
        return telemetry.get_span_recorder()

    def _attach_flight(self, flight, watch: bool = True) -> None:
        """Move this scheduler's state provider + watchdog channel to
        ``flight``: a telemetry reconfigure replaces the process-wide
        recorder, and dumps/stall detection must follow it (the loop
        re-attaches whenever the recorder changes)."""
        old = self._flight
        if old is flight:
            return
        if old is not None:
            old.unwatch_heartbeat(self._flight_channel)
            old.unregister_provider(self._flight_channel)
        self._flight = flight
        if flight is not None:
            flight.register_provider(self._flight_channel, self.flight_state)
            if watch:
                flight.watch_heartbeat(self._flight_channel)

    # ------------------------------------------------------------ cost plane --
    def _on_dispatch(self, kind: str, n_seqs: int, n_tokens: int,
                     seconds: float) -> None:
        """Engine ``dispatch_observer`` hook (scheduler thread, fired right
        after each jitted forward): feeds the predicted-vs-observed perf
        ledger and stashes the wall time — minus any compile amnesty — for
        the execute path's cost attribution on the same tick."""
        amnesty = 0.0
        if self._perf_obs is not None:
            amnesty = self._perf_obs.observe(kind, n_seqs, n_tokens, seconds)
        self._last_dispatch_s = seconds - amnesty
        self._last_dispatch_amnesty_s = amnesty

    def _charge_members(self, members, seconds: Optional[float] = None,
                        amnesty: Optional[float] = None) -> None:
        """Bill one executed dispatch to its plan members
        (``[(req, phase, tokens)]``): ledger attribution amortized by token
        share, plus the fair-share policy's per-tenant rate EWMAs. Defaults
        to the observer-stashed wall time of the dispatch that just ran."""
        if self._ledger is not None and members:
            self._ledger.charge_dispatch(
                [(req.cost, phase, tokens) for req, phase, tokens in members],
                self._last_dispatch_s if seconds is None else seconds,
                self._last_dispatch_amnesty_s if amnesty is None else amnesty)
        if self._fair_share is not None:
            by_tenant: Dict[str, int] = {}
            for req, _, tokens in members:
                if req.tenant is not None:
                    by_tenant[req.tenant] = by_tenant.get(req.tenant, 0) + tokens
            now = time.monotonic()
            for tenant, tokens in by_tenant.items():
                self._fair_share.observe(tenant, tokens, now=now)

    def _touch_kv_plan(self, plan) -> None:
        """Re-anchor each scheduled request's KV block-second accrual at its
        current (blocks, tier) — piecewise-constant billing between execute
        ticks; the final segment closes at ledger finalize."""
        if self._ledger is None:
            return
        sm = self._engine._state_manager
        now_s = time.monotonic()
        for req, _ in plan:
            if req.cost is None:
                continue
            seq = sm.get_sequence(req.uid)
            blocks = seq.cur_allocated_blocks if seq is not None else 0
            tier = (sm.sequence_tier(req.uid) or "device") if blocks else "device"
            self._ledger.touch_kv(req.cost, blocks, tier, now_s)

    # ------------------------------------------------------------- submission --
    def submit(self,
               prompt,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               seed: int = 0,
               trace_id: Optional[str] = None,
               parent_span_id: Optional[int] = None,
               handoff: bool = False,
               priority: Optional[str] = None,
               park: bool = False,
               drafter: Optional[str] = None,
               tenant: Optional[str] = None) -> Request:
        """Enqueue a generation request (any thread). Returns the live
        :class:`Request`; stream tokens from ``request.stream`` or block on
        ``request.result()``. Backpressure per ``config.backpressure``:
        ``reject`` raises :class:`QueueFullError`, ``block`` stalls until the
        queue has room. With overload control enabled, a brownout stage-3
        batch-class request or a provably-unmeetable deadline raises
        :class:`AdmissionRejected` (HTTP 429 + ``Retry-After``) instead of
        being admitted to fail later.

        ``trace_id``/``parent_span_id`` adopt an upstream trace (the fleet
        router's) instead of minting a fresh one, so router → replica shows as
        one parented Perfetto track. ``handoff`` marks a prefill-role request:
        when it finishes DONE its engine state is exported as a portable
        KV-handoff payload (``request.handoff_payload``) for
        :meth:`submit_resume` on a decode-role peer. ``park`` marks a
        continuable multi-turn session: at finish (length OR eos) the engine
        state exports as a v2 *park frame* (``request.park_payload``) the
        fleet park store holds until the session returns — rehydrated via
        :meth:`submit_resume` with the next turn's full prompt.

        ``drafter`` pins THIS request's speculative drafter
        (``prompt_lookup`` | ``learned`` | ``auto``), overriding the
        scheduler's ``SpeculativeConfig.drafter`` arbitration — the loadgen's
        per-request A/B lever. A pin the scheduler can't honor (``learned``
        without a loaded draft head, or any pin on a linear prompt_lookup
        scheduler) is ignored, never an error: output is drafter-independent
        by the bitwise-identity invariant.

        ``tenant`` is the cost-attribution identity (JSON field or
        ``X-DSTPU-Tenant`` header at the HTTP layer): the ledger bills every
        dispatch/KV/wire charge to it and the opt-in fair-share stage sheds
        a tenant over its measured share first under pressure. None lands on
        ``config.cost.default_tenant``."""
        req = Request(prompt,
                      max_new_tokens=max_new_tokens if max_new_tokens is not None
                      else self._config.default_max_new_tokens,
                      temperature=temperature,
                      eos_token_id=eos_token_id,
                      deadline_s=deadline_s if deadline_s is not None
                      else self._config.default_deadline_s,
                      seed=seed,
                      priority=validate_priority(priority),
                      tenant=tenant)
        req.park_requested = bool(park)
        req._spec_drafter_pin = _validate_drafter_pin(drafter)
        self._admission_gate(req)
        return self._enqueue(req, trace_id, parent_span_id, handoff)

    def submit_resume(self,
                      payload: bytes,
                      max_new_tokens: Optional[int] = None,
                      temperature: float = 0.0,
                      eos_token_id: Optional[int] = None,
                      deadline_s: Optional[float] = None,
                      seed: int = 0,
                      trace_id: Optional[str] = None,
                      parent_span_id: Optional[int] = None,
                      handoff: bool = False,
                      priority: Optional[str] = None,
                      prompt=None,
                      park: bool = False,
                      drafter: Optional[str] = None,
                      tenant: Optional[str] = None) -> Request:
        """Admit a handed-off sequence for decode continuation: ``payload`` is
        an ``engine.export_sequence`` product from a prefill-role peer. The
        scheduler imports it into its engine at admission (on the scheduler
        thread — the engine is not thread-safe) and the request enters DECODE
        directly; its ``prompt`` is the full token history so context,
        deadline and stats accounting match a locally-prefilled request.
        Generation state (next input token, sampler RNG state) rides in the
        payload's ``extra`` block, so greedy AND sampled continuations are
        token-identical to the single-engine run. ``request.tokens`` holds
        only the tokens generated HERE; the caller merges with the prefill
        leg's.

        ``prompt`` switches to the *rehydrate* formulation (a parked
        multi-turn session returning with its next turn): it is the new
        turn's FULL token history, of which the payload's parked tokens must
        be a strict prefix. The parked KV imports as-is and the request
        enters PREFILL for the un-parked suffix only — the cached turns
        schedule zero prefill chunks. The new turn samples on its own
        ``seed`` (the parked ``rng_state`` is NOT adopted), so the result is
        bitwise-identical to an uninterrupted request over the same full
        prompt at the same seed."""
        from deepspeed_tpu.inference.v2.ragged.handoff import unpack
        if not isinstance(payload, (bytes, bytearray)):
            # materialize views; a bytearray from the streaming body decoder
            # is kept as-is (copying it would double the resume peak memory)
            payload = bytes(payload)
        header, kv = unpack(payload)  # validate framing before queueing
        extra = header.get("extra") or {}
        if prompt is None and "next_token" not in extra:
            raise ValueError(
                "handoff payload carries no next_token (the donor request must "
                "finish with finish_reason='length' to be continuable, or the "
                "caller must rehydrate with the next turn's prompt)")
        if prompt is not None:
            new_prompt = np.asarray(prompt, np.int32).reshape(-1)
            parked = [int(t) for t in header["tokens"]]
            if (new_prompt.size <= len(parked)
                    or [int(t) for t in new_prompt[:len(parked)]] != parked):
                raise ValueError(
                    "rehydrate prompt must strictly extend the parked token "
                    "history (the parked turns are a proper prefix of the "
                    "returning turn's prompt)")
        req = Request(new_prompt if prompt is not None else header["tokens"],
                      max_new_tokens=max_new_tokens if max_new_tokens is not None
                      else self._config.default_max_new_tokens,
                      temperature=temperature,
                      eos_token_id=eos_token_id,
                      deadline_s=deadline_s if deadline_s is not None
                      else self._config.default_deadline_s,
                      seed=seed,
                      priority=validate_priority(priority),
                      tenant=tenant)
        req._resume_payload = payload
        req._resume_header = header
        req._rehydrate = prompt is not None
        req.park_requested = bool(park)
        req._spec_drafter_pin = _validate_drafter_pin(drafter)
        self._admission_gate(req)  # after the header lands: resume work is
        # its generation budget (plus a rehydrate's un-parked suffix) only,
        # the donor already paid the parked turns' prefill
        req._resume_kv = kv  # zero-copy view into payload; parsed exactly once
        if req._rehydrate:
            req.kv_tier_source = (extra.get("tier") or {}).get("source")
            return self._enqueue(req, trace_id, parent_span_id, handoff)
        req._next = int(extra["next_token"])
        rng_state = extra.get("rng_state")
        if rng_state is not None:
            # exact sampler continuation: the donor's PCG64 state, not a
            # reseed — sampled handoffs stay token-identical
            req._rng = np.random.default_rng()
            req._rng.bit_generator.state = rng_state
        req.decode_steps = int(extra.get("decode_steps") or 0)
        spec = extra.get("spec")
        if spec:
            # drafter continuation: adopt the donor's acceptance EWMA and
            # counters so adaptive k resumes where it left off
            ewma = spec.get("accept_ewma")
            req._spec_ewma = float(ewma) if ewma is not None else None
            req.spec_drafted = int(spec.get("drafted") or 0)
            req.spec_accepted = int(spec.get("accepted") or 0)
            drafters = spec.get("drafters")
            if drafters:
                donor_head = spec.get("head_id")
                for name, val in drafters.items():
                    if name == "learned" and donor_head is not None \
                            and donor_head != self._spec_head_id:
                        # a different head's acceptance record says nothing
                        # about ours: the learned drafter re-explores cold
                        continue
                    req._spec_ewmas[str(name)] = float(val)
        return self._enqueue(req, trace_id, parent_span_id, handoff)

    def _enqueue(self, req: Request, trace_id: Optional[str],
                 parent_span_id: Optional[int], handoff: bool) -> Request:
        req.handoff_requested = bool(handoff)
        if self._ledger is not None:
            # every admitted request carries a RequestCost from birth (the
            # charging sites assume it); rejected requests never get one
            self._ledger.begin(req)
        if self._spans is not None:
            # trace identity is assigned at admission so the HTTP layer can
            # hand the id back in response headers before streaming begins
            req.trace_id = trace_id if trace_id else new_trace_id()
            req.root_span_id = new_span_id()
            req.parent_span_id = parent_span_id
        with self._not_full:
            if self._stopping:
                raise SchedulerStopped("scheduler is stopping; not admitting requests")
            if len(self._queue) >= self._config.queue_capacity:
                if self._config.backpressure == "reject":
                    self._counters["rejected"] += 1
                    if self._metrics:
                        self._metrics.rejections.inc()
                    raise QueueFullError(
                        f"queue at capacity ({self._config.queue_capacity})")
                while len(self._queue) >= self._config.queue_capacity and not self._stopping:
                    self._not_full.wait(0.05)
                if self._stopping:
                    raise SchedulerStopped("scheduler stopped while blocked on a full queue")
            self._queue.append(req)
            self._counters["submitted"] += 1
            if self._metrics:
                self._metrics.admissions.inc()
                self._metrics.queue_depth.set(len(self._queue))
        return req

    def cancel(self, request: Request) -> None:
        """Flag a request for cancellation; the scheduler thread frees its KV
        blocks on the next tick (``Request.cancel()`` is equivalent)."""
        request.cancel()

    # ---------------------------------------------------------- overload --
    @staticmethod
    def _request_work(req: Request) -> int:
        """Engine-token work this request still needs: unfed prompt tokens
        plus its remaining generation budget (a resume request's prompt was
        prefilled by the donor; a rehydrate owes only the un-parked suffix)."""
        if req._resume_header is not None and not req._rehydrate:
            return max(0, req.max_new_tokens - len(req.tokens))
        fed = req._fed
        if req._rehydrate and fed == 0:
            # not yet imported: the parked turns count as already-fed
            fed = int(req._resume_header["seen_tokens"])
        return (max(0, int(req.prompt.size) - fed)
                + max(0, req.max_new_tokens - len(req.tokens)))

    def _active_work_tokens(self) -> int:
        """Outstanding work already admitted into the engine (active plus the
        one mid-admission request)."""
        work = sum(self._request_work(r) for r in list(self._active.values()))
        admitting = self._admitting
        if admitting is not None:
            work += self._request_work(admitting)
        return work

    def _outstanding_work_tokens(self) -> int:
        """Everything committed or queued, in engine tokens — the numerator
        of every queue-wait / Retry-After estimate."""
        with self._not_full:
            queued = list(self._queue)
        return self._active_work_tokens() + sum(self._request_work(r)
                                                for r in queued)

    def retry_after_s(self) -> float:
        """Client backoff derived from the measured drain rate: how long the
        currently-committed-plus-queued work takes at the observed token
        rate, bounded by the configured floor/cap. Cold estimator: the floor
        scaled by queue depth (some signal beats none)."""
        ocfg = self._config.overload
        est = self._rate.seconds_for(self._outstanding_work_tokens())
        if est is None:
            est = ocfg.retry_after_floor_s * (1 + self.queue_depth)
        return min(ocfg.retry_after_cap_s, max(ocfg.retry_after_floor_s, est))

    def _admission_gate(self, req: Request) -> None:
        """submit()-time overload gate (any thread): brownout stage actions
        for the batch class, then the deadline-feasibility estimate. Raises
        :class:`AdmissionRejected` — failing here is cheap; admitting a
        provably-doomed request wastes prefill work and queue capacity."""
        if req.tenant is None:
            # every request bills to a concrete tenant from here on (the
            # ledger, the fair-share EWMAs and the stats rows all key on it)
            req.tenant = self._config.cost.default_tenant
        ocfg = self._config.overload
        if not ocfg.enabled:
            return
        stage = self._brownout.stage
        fs = self._fair_share
        if fs is not None:
            fs.note(req.tenant)
            if stage >= 1 and fs.over_share(req.tenant):
                # the fair-share stage fires only under pressure: a tenant
                # past over_factor x its configured share is 429'd before
                # anyone else degrades (hysteresis clears the flag once its
                # measured rate falls back under the share)
                self._counters["fair_share_shed"] += 1
                fs.sheds += 1
                if self._metrics:
                    self._metrics.fair_share_sheds.inc()
                raise AdmissionRejected(
                    f"fair-share: tenant {req.tenant!r} is over its share "
                    f"under overload (brownout stage {stage})",
                    retry_after_s=self.retry_after_s())
        if stage >= 1 and req.priority == "batch":
            if stage >= self._brownout.max_stage:
                self._counters["brownout_rejected"] += 1
                if self._metrics:
                    self._metrics.brownout_rejections.inc()
                raise AdmissionRejected(
                    f"brownout stage {stage}: batch-class requests are "
                    f"rejected under overload", retry_after_s=self.retry_after_s())
            if req.max_new_tokens > ocfg.brownout_clamp_max_new_tokens:
                req.max_new_tokens = ocfg.brownout_clamp_max_new_tokens
                req.degraded_mode.append("max_new_tokens_clamped")
                self._counters["brownout_clamped"] += 1
                if self._metrics:
                    self._metrics.brownout_clamped.inc()
        if stage >= 2 and (self._config.decode_chunk > 1
                           or self._config.speculative.enabled):
            # speculative extras — the decode chunk AND the draft budget —
            # are globally off at stage >= 2 (the first capacity lever that
            # touches no request's token budget); flagged per affected
            # request so no degradation is silent
            req.degraded_mode.append("speculative_disabled")
        if ocfg.admission_control and req.deadline_s is not None:
            own = self._request_work(req)
            est = self._rate.seconds_for(self._outstanding_work_tokens() + own)
            if est is not None and est > req.deadline_s * ocfg.admission_margin:
                self._counters["shed_admission"] += 1
                if self._metrics:
                    self._metrics.shed_admission.inc()
                raise AdmissionRejected(
                    f"deadline unmeetable at admission: estimated completion "
                    f"{est:.2f}s > deadline {req.deadline_s:.2f}s at the "
                    f"measured rate", retry_after_s=self.retry_after_s())

    def _queue_order_key(self, req: Request):
        return (priority_rank(req.priority),
                req.deadline if req.deadline is not None else float("inf"),
                req.arrival_s)

    def _pop_next_locked(self) -> Request:
        """Next request to admit (caller holds the queue lock): FIFO without
        overload control; (priority, deadline, arrival) order with it."""
        ocfg = self._config.overload
        if not (ocfg.enabled and ocfg.priority_ordering):
            return self._queue.popleft()
        best = min(self._queue, key=self._queue_order_key)
        self._queue.remove(best)
        return best

    def _pop_shed_reason(self, req: Request, now: float) -> Optional[str]:
        """Cheap per-request feasibility re-check at admission pop: the
        estimate may have collapsed since submit() (a stalled engine, a
        burst admitted ahead). A reason string fails the request *before*
        it consumes any engine work; None admits."""
        ocfg = self._config.overload
        if (not ocfg.enabled or not ocfg.admission_control
                or req.deadline is None):
            return None
        est = self._rate.seconds_for(self._active_work_tokens()
                                     + self._request_work(req))
        remaining = req.deadline - now
        if est is not None and est > max(0.0, remaining) * ocfg.admission_margin:
            return (f"deadline unmeetable at admission (est {est:.2f}s, "
                    f"{remaining:.2f}s remaining)")
        return None

    def _overload_tick(self, now: float) -> None:
        """Per-tick pressure sampling -> brownout stage -> queue shedding."""
        with self._not_full:
            depth = len(self._queue)
        kv_occupancy = (1.0 - self._engine.free_blocks / self._capacity_blocks
                        if self._capacity_blocks else 0.0)
        pressure = max(depth / self._config.queue_capacity, kv_occupancy)
        if self._config.overload.slo_pressure:
            # config-gated: a burning error budget floors the pressure sample
            # even while queue depth and KV occupancy look healthy
            slo = telemetry.get_slo_engine()
            if slo is not None:
                pressure = max(pressure, slo.breach_signal())
        stage = self._brownout.update(pressure)
        if self._brownout.transitions != self._brownout_transitions_seen:
            delta = self._brownout.transitions - self._brownout_transitions_seen
            self._brownout_transitions_seen = self._brownout.transitions
            logger.warning(f"serving: brownout stage -> {stage} "
                           f"(pressure {self._brownout.pressure:.2f})")
            if self._metrics:
                self._metrics.brownout_transitions.inc(delta)
                self._metrics.brownout_stage.set(stage)
        if stage >= 1:
            # demote-before-shed: with the tier ladder on, pressure first
            # pushes idle cached KV down a tier (nothing is lost — it
            # promotes back on the next hit). Shedding only runs on ticks
            # where demotion freed nothing.
            demoted = self._demote_for_pressure()
            if demoted == 0 and self._config.overload.shed_enabled:
                self._shed_queued(now)
        if self._kv_tiers is not None:
            self._kv_tiers.update_gauges(self._prefix_cache)

    def _demote_for_pressure(self) -> int:
        """Brownout's demote stage: one controller pass down the tier ladder
        (trie nodes device→host, then coldest offloaded sessions host→disk).
        Returns demotions performed; 0 when tiering is off or nothing is
        demotable (shedding then proceeds as before)."""
        if self._kv_tiers is None:
            return 0
        demoted = self._kv_tiers.demote_for_pressure(
            self._prefix_cache, list(self._active.values()))
        if demoted:
            self._counters["brownout_demotions"] += demoted
        return demoted

    def _shed_queued(self, now: float) -> None:
        """Under sustained pressure, shed queued requests whose deadline is
        provably unmeetable at the measured rate — before they waste a
        prefill. The feasibility walk runs in scheduling order (work ahead of
        a request is work that WILL run first); the doomed are shed lowest
        priority / latest deadline first.

        The fair-share pass runs first and independently of the rate
        estimator (the policy owns its own per-tenant EWMAs): queued work
        from tenants over their measured share is shed deficit-weighted, so
        a flooding tenant drains the queue before anyone else loses work."""
        with self._not_full:
            queued = list(self._queue)
        if not queued:
            return
        self._shed_fair_share(queued)
        rate = self._rate.rate
        if rate is None or rate <= 0:
            return  # cannot prove anything on a cold estimator
        queued = [r for r in queued if not r.finished]
        margin = self._config.overload.admission_margin
        acc = self._active_work_tokens()
        doomed = []
        for req in sorted(queued, key=self._queue_order_key):
            own = self._request_work(req)
            if req.deadline is not None and \
                    (acc + own) / rate > max(0.0, req.deadline - now) * margin:
                doomed.append(req)
                continue  # its work will never run; don't charge the others
            acc += own
        doomed.sort(key=lambda r: (-priority_rank(r.priority),
                                   -(r.deadline - now)))
        # one drain-rate estimate for the whole pass: retry_after_s() walks
        # active + queued under the queue lock, and the estimate cannot
        # meaningfully change between two sheds of the same tick
        retry_after = self.retry_after_s() if doomed else None
        for req in doomed:
            with self._not_full:
                try:
                    self._queue.remove(req)
                except ValueError:
                    continue  # raced into admission
                self._not_full.notify()
            req.shed_reason = ("queue shed under overload: deadline provably "
                              "unmeetable")
            req.retry_after_s = retry_after
            self._counters["shed_queue"] += 1
            if self._metrics:
                self._metrics.shed_queue.inc()
            self._finalize(req, RequestState.FAILED,
                           error=f"shed: {req.shed_reason}")

    def _shed_fair_share(self, queued: List[Request]) -> None:
        """Shed queued work from over-share tenants (this only runs from
        :meth:`_overload_tick`'s stage >= 1 branch — never unpressured).
        Deficit order: the most-over tenant's requests go first, and every
        shed carries the same Retry-After contract as any other 429."""
        fs = self._fair_share
        if fs is None:
            return
        over = [r for r in queued
                if r.tenant is not None and fs.over_share(r.tenant)]
        if not over or len(over) == len(queued):
            # work-conserving guard: shed only while an under-share tenant is
            # actually waiting behind the over-share work. With no such
            # victim, dropping queued work frees capacity for nobody — and a
            # tenant legitimately alone on the engine (its competitors shed
            # or departed, their stale rate EWMAs still inflating the
            # measured-share denominator) must not lose work to its own flag.
            return
        over.sort(key=lambda r: -fs.deficit(r.tenant))
        retry_after = self.retry_after_s()
        for req in over:
            with self._not_full:
                try:
                    self._queue.remove(req)
                except ValueError:
                    continue  # raced into admission
                self._not_full.notify()
            req.shed_reason = (f"fair-share shed under overload: tenant "
                               f"{req.tenant!r} is over its share")
            req.retry_after_s = retry_after
            self._counters["fair_share_shed"] += 1
            fs.sheds += 1
            if self._metrics:
                self._metrics.fair_share_sheds.inc()
            self._finalize(req, RequestState.FAILED,
                           error=f"shed: {req.shed_reason}")

    # ------------------------------------------------------------------ tick --
    def step(self) -> bool:
        """One scheduling iteration; returns True iff a batch executed.
        Runs on the scheduler thread — or inline when ``start=False``."""
        self._drain_control()
        now = time.monotonic()
        for req in list(self._active.values()):
            # the deadline check doubles as the decode feed-stop: a request
            # past its deadline is finalized HERE, before batch building, so
            # it never receives another decode step
            if req.cancel_requested:
                self._finalize(req, RequestState.CANCELLED)
            elif req.deadline is not None and now > req.deadline:
                self._finalize(req, RequestState.TIMED_OUT)
        if self._config.overload.enabled:
            self._overload_tick(now)
        self._admit(now)
        plan = self._build_batch()
        if not plan:
            if not self._active:
                self._starved_ticks = 0  # idle, not starved
            else:
                self._starved_ticks += 1
                if self._starved_ticks >= _STARVATION_FAIL_TICKS:
                    for req in list(self._active.values()):
                        self._finalize(req, RequestState.FAILED,
                                       error=f"starved: unschedulable for "
                                             f"{self._starved_ticks} ticks "
                                             f"({self._engine.free_blocks} free KV blocks)")
                    self._starved_ticks = 0  # a fresh grace period for later work
            return False
        self._starved_ticks = 0
        self._execute(plan)
        self._counters["batches"] += 1
        return True

    def _admit(self, now: float) -> None:
        max_active = self._engine._config.state_manager.max_tracked_sequences
        while True:
            # the queue condition guards ONLY the pop: engine work below (a
            # resume import scatters hundreds of MB of KV and may evict) must
            # never run under the lock submit()'s handler threads block on
            with self._not_full:
                if not self._queue or len(self._active) >= max_active:
                    break
                req = self._pop_next_locked()
                self._admitting = req  # visible to _has_work/load while popped
                self._not_full.notify()
            try:
                if req.cancel_requested:
                    self._finalize(req, RequestState.CANCELLED)
                    continue
                if req.deadline is not None and now > req.deadline:
                    if self._config.overload.enabled:
                        # deadline-failed while queued = rejected at
                        # admission: zero engine work was spent, so the
                        # client gets the same Retry-After contract as a shed
                        req.retry_after_s = self.retry_after_s()
                    self._finalize(req, RequestState.TIMED_OUT)
                    continue
                shed = self._pop_shed_reason(req, now)
                if shed is not None:
                    req.shed_reason = shed
                    req.retry_after_s = self.retry_after_s()
                    self._counters["shed_admission"] += 1
                    if self._metrics:
                        self._metrics.shed_admission.inc()
                    self._finalize(req, RequestState.FAILED, error=f"shed: {shed}")
                    continue
                infeasible = self._permanently_infeasible(req)
                if infeasible:
                    self._finalize(req, RequestState.FAILED, error=infeasible)
                    continue
                req.uid = next(self._uids)
                if req._resume_payload is None and self._prefix_cache is not None:
                    try:
                        self._apply_prefix_hit(req)
                    except Exception:  # pragma: no cover - defensive: a failed
                        # hit application degrades to a cold prefill, never a
                        # failed request
                        logger.exception(f"serving: prefix-cache hit application "
                                         f"failed for uid {req.uid}; prefilling cold")
                if req._resume_payload is not None:
                    outcome = self._import_resume(req)
                    if outcome is None:
                        # the pool can't hold the handed-off KV right now and
                        # nothing was evictable: put it back, retry next tick
                        req.uid = None
                        with self._not_full:
                            self._queue.appendleft(req)
                        break
                    if outcome != "ok":
                        self._finalize(req, RequestState.FAILED, error=outcome)
                        continue
                # a rehydrate enters PREFILL: its parked KV imported, the
                # un-parked suffix still needs feeding (a handoff enters
                # DECODE — its donor fed everything)
                req._set_state(RequestState.DECODE
                               if (req._resume_header is not None
                                   and not req._rehydrate)
                               else RequestState.PREFILL)
                with self._not_full:
                    self._active[req.uid] = req
            finally:
                self._admitting = None
            spans = self._spans  # bind once: the property re-resolves
            if spans is not None:
                spans.record("queued", cat="serving", ts_us=req.arrival_us,
                             dur_us=now_us() - req.arrival_us,
                             trace_id=req.trace_id,
                             parent_id=req.root_span_id,
                             args={"uid": req.uid})
        if self._metrics:
            with self._not_full:
                queue_depth = len(self._queue)
            self._metrics.queue_depth.set(queue_depth)
            self._metrics.in_flight.set(len(self._active))

    def _import_resume(self, req: Request) -> Optional[str]:
        """Import a handed-off sequence under the request's uid (scheduler
        thread — the engine is not thread-safe), evicting cold idle sequences
        under KV pressure. ``"ok"`` = imported, the engine owns the state;
        ``None`` = the pool is full and nothing was evictable (retry next
        tick); any other string = the import failed with the pool able to
        hold the payload — NOT capacity, the request can never land (FAIL it
        rather than retry the queue head forever). Known-permanent problems
        (geometry, payload > pool or > per-sequence cap) were already
        rejected by :meth:`_permanently_infeasible`."""
        kv_meta = (req._resume_header or {}).get("kv")
        needed = int(kv_meta["shape"][2]) if kv_meta else 0
        # the manager-level import reuses the header/KV parsed once at
        # submit_resume (compatibility was checked by _permanently_infeasible)
        # rather than re-unpacking the full payload on every retry
        snapshot = {"uid": req.uid,
                    "seen_tokens": req._resume_header["seen_tokens"],
                    "kv": req._resume_kv}
        while True:
            try:
                self._engine._state_manager.import_sequence(snapshot, uid=req.uid)
            except Exception as e:
                if self._engine.free_blocks >= needed:
                    return f"handoff import failed: {e}"
                if self._evict_one({req.uid}):
                    continue
                return None
            if self._ledger is not None and req.cost is not None:
                self._ledger.charge_wire(req.cost, "resume",
                                         len(req._resume_payload))
            req._resume_payload = None  # imported; the engine owns the KV now
            req._resume_kv = None
            if req._rehydrate:
                # the parked turns are prefilled; the new turn's suffix is
                # not — feed resumes exactly at the import's seen_tokens (the
                # boundary token re-feeds, same KV slot, like a full prefix
                # hit) so the cached turns schedule zero prefill chunks
                seen = int(snapshot["seen_tokens"])
                req._fed = seen
                req.cached_tokens = seen
                self._counters["rehydrates"] += 1
            else:
                req._fed = req.prompt.size  # whole history already prefilled
            return "ok"

    # -------------------------------------------------- fleet data motion --
    def _call_on_loop(self, fn, timeout: float = 5.0):
        """Run ``fn`` on the scheduler (engine-owning) thread and return its
        result — the cross-thread entry for fleet control operations (peer
        prefix export, work-stealing). A manually-stepped scheduler
        (``start=False``) runs inline; otherwise the call is queued and
        drained at the top of the next ``step()``. Raises ``TimeoutError``
        when the loop does not service it in ``timeout`` (a wedged or
        mutually-fetching peer: the caller degrades, never deadlocks) and
        :class:`SchedulerStopped` when the scheduler dies first."""
        if self._stopped or self._killed:
            raise SchedulerStopped("scheduler is stopped")
        if self._thread is None:
            return fn()
        box = {"done": threading.Event(), "result": None, "error": None}
        self._control.append((fn, box))
        if not box["done"].wait(timeout):
            raise TimeoutError(f"scheduler control call not serviced in {timeout}s")
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def _drain_control(self) -> None:
        """Service queued control calls (scheduler thread, top of every tick)."""
        while self._control:
            try:
                fn, box = self._control.popleft()
            except IndexError:  # pragma: no cover - single consumer
                break
            try:
                box["result"] = fn()
            except BaseException as e:
                box["error"] = e
            box["done"].set()

    def _fail_control(self) -> None:
        """Unblock every pending control caller at stop/kill — a waiter must
        observe the death, not its timeout."""
        while self._control:
            try:
                _, box = self._control.popleft()
            except IndexError:  # pragma: no cover - single consumer
                break
            box["error"] = SchedulerStopped("scheduler stopped before the "
                                            "control call was serviced")
            box["done"].set()

    def prefix_digest_catalog(self) -> Optional[List[str]]:
        """Truncated-hex digests of this replica's hottest trie paths — what
        the probe doc publishes for the fleet's cache-aware routing. Safe from
        probe threads (lock-guarded snapshot; staleness is bounded by the
        probe TTL). None = cache off or publication disabled."""
        if self._prefix_cache is None:
            return None
        limit = self._config.prefix_cache.digest_catalog_limit
        if limit <= 0:
            return None
        return self._prefix_cache.digest_catalog(limit)

    def export_prefix(self, digests, min_blocks: int = 1,
                      timeout: float = 5.0) -> Optional[bytes]:
        """Frame this replica's cached KV along ``digests`` (full chained
        block digests) as a portable payload — the peer prefix-fetch donor
        side. Any thread; the trie walk AND the device gather run on the
        scheduler loop so no block can be freed or recycled mid-gather (the
        allocator is not thread-safe, and a CRC computed over a recycled
        block would certify garbage). None = no path at least ``min_blocks``
        deep (or the cache is off)."""
        if self._prefix_cache is None:
            return None
        digests = list(digests)
        floor = max(1, min_blocks)

        def _do():
            from deepspeed_tpu.inference.v2.ragged.handoff import pack_blocks
            blocks, tokens = self._prefix_cache.export_nodes(digests)
            if len(blocks) < floor:
                return None
            return pack_blocks(self._engine._state_manager, blocks, tokens,
                               extra={"kind": "prefix"})
        return self._call_on_loop(_do, timeout=timeout)

    def _import_peer_prefix(self, req: Request, have: int) -> bool:
        """Traced wrapper around :meth:`_import_peer_prefix_inner`: the fetch
        is a leg of the request's trace — it records under the request root
        with the original trace id, so a cross-replica KV import shows up in
        the merged fleet trace instead of as unexplained prefill latency."""
        spans = self._spans
        if spans is None:
            return self._import_peer_prefix_inner(req, have)
        _t0 = now_us()
        ok = self._import_peer_prefix_inner(req, have)
        spans.record("peer_prefix_fetch", cat="serving", ts_us=_t0,
                     dur_us=now_us() - _t0, trace_id=req.trace_id,
                     parent_id=req.root_span_id,
                     args={"uid": req.uid, "have_blocks": have, "imported": ok})
        return ok

    def _import_peer_prefix_inner(self, req: Request, have: int) -> bool:
        """Fetch KV blocks along the request's prefix chain from a fleet peer
        (the router-installed hook) and publish them into the local trie;
        True = the trie now indexes a deeper prefix than ``have`` blocks and
        the caller should re-acquire. Every failure mode — transport error,
        CRC mismatch, geometry drift, a payload whose tokens do not extend
        THIS prompt's chain — rejects loudly and degrades to a cold prefill:
        recompute is always correct."""
        from deepspeed_tpu.inference.v2.ragged.handoff import (
            compatibility_error, unpack)
        from deepspeed_tpu.inference.v2.ragged.prefix_cache import digest_chain
        pc = self._prefix_cache
        sm = self._engine._state_manager
        notify = self._peer_fetch_notify or (lambda outcome: None)
        try:
            payload = self._peer_fetch(list(req._prefix_digests), have)
        except Exception as e:
            self._counters["peer_fetch_rejects"] += 1
            notify("reject")
            logger.warning(f"serving: peer prefix fetch failed: {e}")
            return False
        if payload is None:
            return False
        try:
            header, kv = unpack(payload)  # CRC verified here: a flipped byte
            # in the KV region is a ValueError, never silently wrong attention
            err = compatibility_error(sm, header)
            if err:
                raise ValueError(err)
            tokens = np.asarray(header["tokens"], np.int32)
            if kv is None or tokens.size != kv.shape[2] * sm.kv_block_size:
                raise ValueError("peer prefix payload is not block-aligned")
            got = digest_chain(tokens, sm.kv_block_size)
            if len(got) <= have or got != req._prefix_digests[:len(got)]:
                raise ValueError("peer prefix does not extend this prompt's "
                                 "cached chain")
        except ValueError as e:
            self._counters["peer_fetch_rejects"] += 1
            notify("reject")
            logger.warning(f"serving: rejecting peer prefix payload: {e}")
            return False
        needed = int(kv.shape[2])
        while True:
            try:
                ids = sm.kv_cache.scatter_blocks(kv)
                break
            except Exception:
                if self._engine.free_blocks >= needed:
                    self._counters["peer_fetch_rejects"] += 1
                    notify("reject")
                    return False  # not a capacity problem: give up, recompute
                if not self._evict_one({req.uid}):
                    return False  # pool genuinely can't hold it right now
        # publish takes trie references on the NEW nodes only; dropping the
        # import reference then frees exactly the blocks that duplicated an
        # already-indexed prefix
        pc.publish(tokens, ids, int(tokens.size), digests=got)
        sm.kv_cache.free(ids)
        if self._ledger is not None and req.cost is not None:
            self._ledger.charge_wire(req.cost, "peer_fetch", len(payload))
        self._counters["peer_fetch_hits"] += 1
        self._counters["peer_fetch_blocks"] += needed
        notify("hit")
        if self._metrics:
            self._metrics.prefix_trie_blocks.set(pc.n_blocks)
        return True

    def _find_by_handle(self, handle: str) -> Optional[Request]:
        with self._not_full:
            for req in self._queue:
                if req.handle == handle:
                    return req
        for req in list(self._active.values()):
            if req.handle == handle:
                return req
        return None

    def request_steal(self, handle: str, timeout: float = 5.0) -> dict:
        """Fleet work-stealing entry (any thread): move the request addressed
        by ``handle`` off this replica so the router can re-grant it to a
        cold one. Runs on the scheduler loop; outcomes:

        - ``{"status": "queued"}`` — the request had consumed no decode state
          (still QUEUED, or prefilling with nothing streamed): finalized here
          with a ``stolen:`` error; the router re-dispatches the original
          request from scratch (token-identical trivially — same prompt,
          same seed);
        - ``{"status": "exported", "payload": .., "sent": n}`` — early
          decode: the live sequence is exported token-identically (the same
          frame as a prefill→decode handoff) and finalized here; the router
          resumes it on the peer and skips the ``n`` tokens already streamed;
        - ``{"status": "finished"}`` — the victim won the race (request
          already terminal, unknown, or not exportable): exactly-once
          completion, the router keeps consuming the original leg.
        """
        def _do():
            req = self._find_by_handle(handle)
            if req is None or req.finished:
                return {"status": "finished"}
            with self._not_full:
                try:
                    self._queue.remove(req)
                    queued = True
                    self._not_full.notify()
                except ValueError:
                    queued = False
            if queued or req.state is RequestState.PREFILL or not req.tokens:
                # no decode state worth moving: a restart on the cold peer
                # beats shipping a partial prefill's KV (and a PREFILL
                # sequence has no next-input token to export yet)
                self._counters["steals"] += 1
                self._finalize(req, RequestState.CANCELLED,
                               error="stolen: re-granted to a peer replica")
                return {"status": "queued"}
            if (req.state is not RequestState.DECODE or req._next is None
                    or self._engine._state_manager.get_sequence(req.uid) is None):
                return {"status": "finished"}  # not exportable: let it finish here
            sent = len(req.tokens)
            # the continuable-export shape: _export_handoff ships next_token
            # only for a "length" finish, and mid-steal the invariant is the
            # same — the last kept token is the next decode input
            req.finish_reason = "length"
            try:
                payload = self._export_handoff(req)
            except Exception as e:
                req.finish_reason = None
                logger.warning(f"serving: steal export failed for uid "
                               f"{req.uid}: {e}")
                return {"status": "finished"}
            req.finish_reason = None
            if self._ledger is not None and req.cost is not None:
                self._ledger.charge_wire(req.cost, "steal", len(payload))
            self._counters["steals"] += 1
            self._finalize(req, RequestState.CANCELLED,
                           error="stolen: exported to a peer replica")
            return {"status": "exported", "payload": payload, "sent": sent}
        return self._call_on_loop(_do, timeout=timeout)

    # ---------------------------------------------------------- prefix cache --
    def _apply_prefix_hit(self, req: Request) -> None:
        """Map the longest cached prefix of ``req.prompt`` into a
        pre-populated sequence so only the suffix prefills (scheduler thread).

        A *fully*-cached prompt still re-feeds its final token — the engine
        needs one forward to produce logits — and that token's KV write lands
        in the last matched block, which is shared read-only; that block is
        forked copy-on-write first. When no block is free for the fork (and
        nothing is evictable) the hit degrades by one block instead, keeping
        the write in a fresh suffix block."""
        pc = self._prefix_cache
        sm = self._engine._state_manager
        # hash the prompt exactly once per request: the same chain serves the
        # lookup here and both publish points (prefill completion + finalize)
        req._prefix_digests = pc.chain(req.prompt)
        hit = pc.acquire(req.prompt, digests=req._prefix_digests)
        if (self._peer_fetch is not None
                and len(hit.blocks) < len(req._prefix_digests)
                and self._import_peer_prefix(req, have=len(hit.blocks))):
            # a peer held a deeper prefix and its blocks now live in the
            # local trie: re-acquire over the extended index. One admission
            # stays one lookup in the hit-rate denominator — the retry must
            # not dilute the rate the fleet routing gate reads.
            pc.release(hit.blocks)
            hit = pc.acquire(req.prompt, digests=req._prefix_digests)
            pc.lookups -= 1
        if self._metrics:
            self._metrics.prefix_lookups.inc()
            self._metrics.prefix_lookup_depth.observe(len(hit.blocks))
        if not hit.blocks:
            return
        blocks = list(hit.blocks)
        seen = hit.tokens
        try:
            if seen >= req.prompt.size:
                forked = self._fork_for_cow(blocks[-1], req.uid)
                if forked is None:
                    pc.release([blocks[-1]])
                    blocks.pop()  # degrade: recompute the last cached block
                    if len(blocks) < self._config.prefix_cache.min_prefix_blocks:
                        pc.release(blocks)  # below the configured hit floor
                        return
                    seen = len(blocks) * sm.kv_block_size
                else:
                    pc.release([blocks[-1]])
                    blocks[-1] = int(forked)
                    seen = req.prompt.size - 1  # one last-token step, then DECODE
            sm.create_cached_sequence(req.uid, blocks, seen)
        except Exception:
            # drop every reference this hit still holds (a successful fork
            # swapped the trie ref for a private refcount-1 copy, which the
            # same release frees) — a failed application must leak nothing
            pc.release(blocks)
            raise
        req._fed = seen
        req.cached_tokens = seen
        if self._ledger is not None and req.cost is not None:
            # the savings side of the bill: prompt tokens this request did
            # NOT pay to prefill
            self._ledger.charge_prefix(req.cost, seen)
        pc.record_hit(len(blocks), seen)  # applied for real: now it counts
        self._counters["prefix_hits"] += 1
        self._counters["prefix_tokens_saved"] += seen
        if self._metrics:
            self._metrics.prefix_hits.inc()
            self._metrics.prefix_tokens_saved.inc(seen)
            self._metrics.prefix_trie_blocks.set(pc.n_blocks)

    def _fork_for_cow(self, src_block: int, uid: int) -> Optional[int]:
        """Copy-on-write fork of one shared block, evicting (trie leaves
        first, then cold idle sequences) under KV pressure. None = the pool
        cannot yield a block right now."""
        kv = self._engine._state_manager.kv_cache
        while True:
            if kv.free_blocks >= 1:
                return int(kv.fork_blocks([src_block])[0])
            if not self._evict_one({uid}):
                return None

    def _publish(self, req: Request, seq, tokens, committed: int) -> None:
        """Index ``tokens``' full KV blocks in the prefix trie. Called at two
        points: **prefill completion** (the prompt's blocks — so concurrent
        requests over a shared prefix hit as soon as the first one's prefill
        lands, not only after it finishes generating) and **finalize** on DONE
        (prompt + generated history — multi-turn reuse). Publishing is
        idempotent per content: already-indexed prefixes just refresh LRU.
        The admission-time digest chain is extended, not recomputed."""
        try:
            req._prefix_digests = self._prefix_cache.chain(
                tokens, base=req._prefix_digests)
            self._prefix_cache.publish(tokens, seq.kv_blocks, committed,
                                       digests=req._prefix_digests)
        except Exception:  # pragma: no cover - defensive: publishing is an
            # optimization; a failure must not lose the request's result
            logger.exception(f"serving: prefix-cache publish failed for uid {req.uid}")
        if self._metrics:
            self._metrics.prefix_trie_blocks.set(self._prefix_cache.n_blocks)

    def _publish_finished(self, req: Request, seq) -> None:
        """The finalize-time publish (full history, instead of letting flush
        free the blocks). Valid positions are those whose KV was computed from
        a *kept* token: chunked decode commits discarded over-run tokens past
        the history, so the committed count is capped at the kept length."""
        history = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)]) if req.tokens else req.prompt
        self._publish(req, seq, history, min(seq.seen_tokens, history.size))

    # ---------------------------------------------------- speculative decode --
    def _spec_draft_budget(self) -> int:
        """Draft tokens this batch may spend (0 = drafting off this tick).
        Brownout stage >= 2 zeroes the budget — speculation is the first
        capacity lever pulled under overload, before anything clamps a
        request's own token budget."""
        if self._drafter is None:
            return 0
        if self._config.overload.enabled and self._brownout.stage >= 2:
            return 0
        budget = self._config.speculative.draft_token_budget
        return budget if budget is not None else (1 << 30)

    def _spec_k(self, req: Request) -> int:
        """Per-request adaptive draft depth: the acceptance EWMA scales
        ``max_draft_tokens`` down to 0 on adversarial (pattern-free) text —
        bounded regression — with a periodic single-token probe so acceptance
        can recover when the text turns repetitive again."""
        scfg = self._config.speculative
        ewma = req._spec_ewma
        k = (scfg.max_draft_tokens if ewma is None
             else int(scfg.max_draft_tokens * ewma + 0.5))
        if k == 0 and req.decode_steps % scfg.probe_interval == 0:
            k = 1
        return k

    @staticmethod
    def _history_for(req: Request) -> np.ndarray:
        """The request's token history (prompt + generated) as a read-only
        view over an incrementally-grown buffer: each decode tick copies only
        the newly-pushed tokens, not the whole history — per-token drafting
        cost stays O(new), not O(length)."""
        n = int(req.prompt.size) + len(req.tokens)
        buf = req._spec_history
        if buf is None or n > buf.size:
            grown = np.empty(max(64, 2 * n), np.int32)
            if buf is None:
                grown[:req.prompt.size] = req.prompt
                req._spec_history_len = int(req.prompt.size)
            else:
                grown[:req._spec_history_len] = buf[:req._spec_history_len]
            req._spec_history = buf = grown
        if req._spec_history_len < n:
            tail = req.tokens[req._spec_history_len - int(req.prompt.size):]
            buf[req._spec_history_len:n] = tail
            req._spec_history_len = n
        return buf[:n]

    def _draft_for(self, req: Request, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation tokens for ``req`` (scheduler
        thread). History = prompt + everything generated; the admission-time
        digest chain is extended (never recomputed) so the trie walk hashes
        only newly-completed blocks."""
        history = self._history_for(req)
        digests = None
        if self._prefix_cache is not None:
            req._prefix_digests = self._prefix_cache.chain(
                history, base=req._prefix_digests)
            digests = req._prefix_digests
        return self._drafter.draft(history, k, digests=digests)

    def _pick_drafter(self, req: Request) -> str:
        """Which drafter builds this request's feed this step. ``auto``
        arbitrates on per-request per-drafter acceptance EWMAs: cold drafters
        explore first (learned before lookup — it needs a step to capture its
        hidden state anyway), then the higher EWMA wins, with the loser
        probed every ``probe_interval`` decode steps so arbitration can
        reverse when the text regime changes mid-stream. A per-request pin
        (``submit(drafter=...)``) overrides both, when honorable: a
        ``learned`` pin needs a loaded draft head."""
        pin = req._spec_drafter_pin
        if pin is not None and pin != "auto" and \
                (pin != "learned" or self._learned is not None):
            return pin
        mode = self._drafter_mode
        if mode != "auto":
            return mode
        ew = req._spec_ewmas
        learned, lookup = ew.get("learned"), ew.get("prompt_lookup")
        if learned is None:
            return "learned"
        if lookup is None:
            return "prompt_lookup"
        winner, loser = (("learned", "prompt_lookup") if learned >= lookup
                         else ("prompt_lookup", "learned"))
        if req.decode_steps and \
                req.decode_steps % self._config.speculative.probe_interval == 0:
            return loser  # periodic probe: the loser gets a round to recover
        return winner

    def _arb_update(self, req: Request, name: str, rate: float) -> None:
        """Fold one step's depth-productivity ``rate`` into the arbitration
        EWMAs: the request's (what ``auto`` decides on) and the scheduler's
        (the per-drafter gauge). A picked drafter that proposes NOTHING
        scores 0 here — otherwise "auto" wedges on a drafter that never
        proposes and therefore never gets measured — while ``req._spec_ewma``
        keeps the linear-path rule that an empty draft is not rejection."""
        alpha = self._config.speculative.accept_alpha
        prev = req._spec_ewmas.get(name)
        req._spec_ewmas[name] = (rate if prev is None
                                 else alpha * rate + (1 - alpha) * prev)
        sprev = self._spec_drafter_ewmas.get(name)
        self._spec_drafter_ewmas[name] = (rate if sprev is None
                                          else alpha * rate + (1 - alpha) * sprev)
        if self._metrics:
            gauge = (self._metrics.spec_drafter_learned_ewma if name == "learned"
                     else self._metrics.spec_drafter_lookup_ewma)
            gauge.set(self._spec_drafter_ewmas[name])

    def _draft_tree_for(self, req: Request, k: int, room: int):
        """A :class:`TokenTree` feed for the learned/auto modes (always
        non-None: every decode entry in tree mode feeds a tree, so one
        ``verify_tree`` dispatch carries the whole tick). ``k`` caps draft
        DEPTH, ``room`` caps draft NODES (root excluded) under the ragged
        token budget. A prompt-lookup draft rides as a chain tree — bitwise
        the linear verify program's output — and a learned draft without a
        valid hidden state bootstraps with a root-only tree whose verify
        returns the hidden state the next step drafts from."""
        from deepspeed_tpu.inference.v2.spec import TokenTree
        scfg = self._config.speculative
        name = self._pick_drafter(req)
        if name != req._spec_last_drafter:
            if req._spec_last_drafter is not None:
                self._counters["spec_drafter_switches"] += 1
                if self._metrics:
                    self._metrics.spec_drafter_switches.inc()
            req._spec_last_drafter = name
        root = np.asarray([req._next], np.int32)
        room = min(room, scfg.tree_node_budget - 1)
        if k <= 0 or room <= 0:
            return TokenTree.chain(root)
        if name == "prompt_lookup":
            draft = self._draft_for(req, min(k, room))
            if draft.size == 0:
                self._arb_update(req, name, 0.0)  # no n-gram match: scored 0
                return TokenTree.chain(root)
            return TokenTree.chain(np.concatenate([root, draft]))
        hist = int(req.prompt.size) + len(req.tokens)
        if req._spec_hidden is None or req._spec_hidden_pos != hist:
            return TokenTree.chain(root)  # bootstrap: capture hidden first
        tree = self._learned.draft_tree(req._spec_hidden, int(req._next), k,
                                        node_budget=room + 1)
        if tree is None:
            self._arb_update(req, name, 0.0)  # nothing fit the node budget
            return TokenTree.chain(root)
        return tree

    def _spec_accept(self, req: Request, feed: np.ndarray, rows: np.ndarray):
        """The acceptance rule over one verify feed. ``rows[j]`` scores the
        token after ``feed[:j+1]``; the emitted sequence is EXACTLY what
        non-speculative decoding would produce: each emitted token is sampled
        (or argmaxed) from the target distribution with the request's own
        stream — one draw per emitted token, same draw order as spec-off — and
        a draft survives only when it equals that token (rejection sampling
        with a point-mass draft distribution). Returns ``(emitted,
        accepted_drafts)``; emission stops at eos / the generation cap,
        mirroring :meth:`_push_token`'s rules."""
        emitted: List[int] = []
        accepted = 0
        k = int(feed.size) - 1
        for j in range(int(feed.size)):
            tok = self._sample(req, rows[j])
            emitted.append(tok)
            if req.eos_token_id is not None and tok == req.eos_token_id:
                break
            if len(req.tokens) + len(emitted) >= req.max_new_tokens:
                break
            if j >= k:
                break  # the bonus token: no more drafts to validate
            if int(feed[j + 1]) != tok:
                break  # rejection: the target model disagrees with the draft
            accepted += 1
        return emitted, accepted

    def _permanently_infeasible(self, req: Request) -> Optional[str]:
        """A reason this request can NEVER be scheduled, or None. Failing at
        admission beats starving it forever against budgets that will not
        change (generate()'s old 'no sequence schedulable' RuntimeError)."""
        sm = self._engine._config.state_manager
        if req._resume_header is not None:
            from deepspeed_tpu.inference.v2.ragged.handoff import compatibility_error
            err = compatibility_error(self._engine._state_manager, req._resume_header)
            if err:
                return err
            if int(req._resume_header["seen_tokens"]) + 1 > sm.max_context:
                return (f"handed-off sequence has "
                        f"{req._resume_header['seen_tokens']} committed tokens; "
                        f"max_context={sm.max_context} leaves no room to decode")
            if req._rehydrate and req.prompt.size + 1 > sm.max_context:
                return (f"rehydrate prompt of {req.prompt.size} tokens exceeds "
                        f"max_context={sm.max_context} (room for at least one "
                        f"generated token is required)")
            return None
        if req.prompt.size + 1 > sm.max_context:
            return (f"prompt of {req.prompt.size} tokens exceeds max_context="
                    f"{sm.max_context} (room for at least one generated token "
                    f"is required)")
        block_size = self._engine._state_manager.kv_block_size
        min_blocks = -(-(req.prompt.size + 1) // block_size)
        if min_blocks > self._capacity_blocks:
            return (f"prompt needs {min_blocks} KV blocks; the pool holds "
                    f"{self._capacity_blocks}")
        return None

    # -------------------------------------------------------- batch building --
    def _build_batch(self) -> List[Tuple[Request, np.ndarray]]:
        engine = self._engine
        sm_cfg = engine._config.state_manager
        budget = sm_cfg.max_ragged_batch_size
        plan: List[Tuple[Request, np.ndarray]] = []
        uids: List[int] = []
        lens: List[int] = []

        def admission(uid: int, n: int) -> SchedulingResult:
            return engine.can_schedule(uids + [uid], lens + [n])

        def admit(req: Request, toks) -> None:
            toks = np.asarray(toks, np.int32).reshape(-1)
            uids.append(req.uid)
            lens.append(toks.size)
            plan.append((req, toks))

        def admit_under_pressure(req: Request, n: int) -> bool:
            """1-token admission with evict-coldest retries on KV pressure."""
            while True:
                result = admission(req.uid, n)
                if result == SchedulingResult.Success:
                    return True
                if result != SchedulingResult.KVCacheLimitExceeded:
                    return False  # token/sequence budget: eviction cannot help
                if not self._evict_one(set(uids) | {req.uid}):
                    return False

        def by_pressure_priority(reqs):
            # requests deferred under KV pressure go first the next tick —
            # in-batch sequences are never eviction candidates, so without
            # this a permanently-admitted peer could starve a deferred one
            return sorted(reqs, key=lambda r: (-r._deferred, r.uid))

        # --- decode tokens first: one each (plus up to k draft tokens when
        # speculation is on), latency-critical
        draft_budget = self._spec_draft_budget()
        for req in by_pressure_priority(
                [r for r in list(self._active.values()) if r.state is RequestState.DECODE]):
            if len(lens) + 1 > sm_cfg.max_ragged_sequence_count or sum(lens) + 1 > budget:
                break
            seq = engine._state_manager.get_sequence(req.uid)
            if seq is not None and seq.seen_tokens + 1 > sm_cfg.max_context:
                # context window exhausted: a clean length-cut, not an error
                req.finish_reason = "context"
                self._finalize(req, RequestState.DONE)
                continue
            feed = None
            tree = None
            req._spec_tree = None
            if draft_budget > 0:
                # draft tokens compete with prefill chunks under the same
                # ragged token budget; never draft past the generation cap or
                # the context window (the device commits every fed position)
                room = min(draft_budget, budget - sum(lens) - 1,
                           req.max_new_tokens - len(req.tokens) - 1)
                if seq is not None:
                    room = min(room, sm_cfg.max_context - seq.seen_tokens - 1)
                k = min(self._spec_k(req), room)
                if self._drafter_mode != "prompt_lookup":
                    # learned/auto: every decode entry feeds a TokenTree so
                    # ONE verify_tree dispatch carries the tick (a root-only
                    # tree when nothing drafts — its verify still returns the
                    # hidden state the learned drafter reads next step)
                    tree = self._draft_tree_for(req, k, room)
                    feed = tree.tokens
                elif k > 0:
                    draft = self._draft_for(req, k)
                    if draft.size:
                        feed = np.concatenate(
                            [np.asarray([req._next], np.int32), draft])
            if feed is not None and \
                    admission(req.uid, int(feed.size)) == SchedulingResult.Success:
                # drafts are speculative: they never trigger eviction — a feed
                # the pool can't take falls back to the k=0 single token below
                req._deferred = 0
                req._spec_tree = tree
                admit(req, feed)
                draft_budget -= int(feed.size) - 1
            elif admit_under_pressure(req, 1):
                req._deferred = 0
                if tree is not None:
                    # tree mode under pressure: a root-only tree keeps the
                    # tick on one verify_tree dispatch (same 1-token cost)
                    from deepspeed_tpu.inference.v2.spec import TokenTree
                    req._spec_tree = TokenTree.chain(
                        np.asarray([req._next], np.int32))
                admit(req, [req._next])
            else:
                req._deferred += 1  # KV held by in-flight work; retry next tick

        # --- prompt chunks fill what's left (Dynamic SplitFuse)
        for req in by_pressure_priority(
                [r for r in list(self._active.values()) if r.state is RequestState.PREFILL]):
            room = budget - sum(lens)
            if self._config.max_prefill_chunk is not None:
                room = min(room, self._config.max_prefill_chunk)
            if room < 1 or len(lens) + 1 > sm_cfg.max_ragged_sequence_count:
                break
            remaining = req.prompt[req._fed:]
            while True:
                chunk = remaining[:room]
                while chunk.size and admission(req.uid, chunk.size) != SchedulingResult.Success:
                    chunk = chunk[:chunk.size // 2]  # shrink under KV pressure first
                if chunk.size or not self._evict_one(set(uids) | {req.uid}):
                    break  # admitted something, or nothing left to evict
            if chunk.size:
                req._deferred = 0
                admit(req, chunk)
            else:
                req._deferred += 1
        return plan

    def _evict_one(self, exclude_uids) -> bool:
        """Free device KV blocks under pressure: evict an unreferenced prefix-
        trie leaf (LRU) first — reclaiming cached-but-idle state costs nothing
        live — then fall back to offloading the coldest idle engine-resident
        sequence (not in the batch being built), which restores transparently
        when next touched. Returns False when nothing is evictable.

        With the tier ladder on, *demotion* runs ahead of the eviction
        ladder: a demoted trie node keeps its KV (host tier, promotes back on
        the next hit) where an evicted leaf recomputes from scratch."""
        if self._kv_tiers is not None and self._prefix_cache is not None:
            freed = self._prefix_cache.demote(1)
            if freed:
                self._counters["tier_demotions"] += freed
                if self._metrics:
                    self._metrics.kv_tier_demotions.inc(freed)
                return True
        if self._prefix_cache is not None:
            freed = self._prefix_cache.evict(1)
            if freed:
                self._counters["prefix_evictions"] += freed
                if self._metrics:
                    self._metrics.prefix_evictions.inc(freed)
                    self._metrics.prefix_trie_blocks.set(self._prefix_cache.n_blocks)
                return True
        engine = self._engine
        candidates = []
        for req in self._active.values():
            if req.uid in exclude_uids or engine.is_offloaded(req.uid):
                continue
            seq = engine._state_manager.get_sequence(req.uid)
            if seq is not None and seq.cur_allocated_blocks > 0:
                candidates.append(req)
        if not candidates:
            return False
        coldest = min(candidates, key=lambda r: r._last_touch_s)
        engine.offload_sequence(coldest.uid)
        self._counters["evictions"] += 1
        if self._metrics:
            self._metrics.evictions.inc()
        return True

    # --------------------------------------------------------------- execute --
    def _execute(self, plan: List[Tuple[Request, np.ndarray]]) -> None:
        engine = self._engine
        uids = [req.uid for req, _ in plan]
        tokens = [t for _, t in plan]
        now = time.monotonic()
        for req, _ in plan:
            req._last_touch_s = now
        # close + re-anchor each member's KV block-second segment at its
        # pre-dispatch occupancy (the final segment closes at finalize)
        self._touch_kv_plan(plan)
        spans = self._spans
        if spans is not None:
            # capture each request's phase before the processing loop mutates
            # state (PREFILL flips to DECODE on the final chunk)
            _t0 = now_us()
            _phases = [("prefill" if req.state is RequestState.PREFILL else "decode",
                        int(toks.size)) for req, toks in plan]

        def _record_phase_spans(counts=None):
            if spans is None:
                return
            end = now_us()
            for i, ((phase, ntok), (req, _)) in enumerate(zip(_phases, plan)):
                spans.record(phase, cat="serving", ts_us=_t0, dur_us=end - _t0,
                             trace_id=req.trace_id, parent_id=req.root_span_id,
                             args={"uid": req.uid,
                                   "tokens": ntok if counts is None else counts[i]})

        # tree-verify (learned/auto drafters): any decode entry carrying a
        # TokenTree — root-only trees included — routes the tick through ONE
        # engine.verify_tree dispatch
        if any(req._spec_tree is not None for req, _ in plan):
            self._execute_verify_tree(plan, _record_phase_spans)
            return
        # speculative verify: any decode feed wider than one token (next
        # input + draft tokens) routes the tick through the verify path
        if any(req.state is RequestState.DECODE and toks.size > 1
               for req, toks in plan):
            self._execute_verify(plan, _record_phase_spans)
            return

        K = self._config.decode_chunk
        if K > 1 and self._config.overload.enabled and self._brownout.stage >= 2:
            K = 1  # brownout stage >= 2: speculative extras disabled
        max_context = self._engine._config.state_manager.max_context

        def chunk_safe(req):
            # greedy only (a sampled batch must keep each request on its own
            # private seeded stream, which a shared device PRNG cannot honor)
            # and never past max_context: the device loop always runs K steps,
            # and tokens beyond the context window must not reach the client
            seq = engine._state_manager.get_sequence(req.uid)
            return (req.temperature <= 0.0
                    and (seq is None or seq.seen_tokens + K <= max_context))

        decode_only = (K > 1 and all(req.state is RequestState.DECODE
                                     and chunk_safe(req) for req, _ in plan))
        if decode_only:
            try:
                rows = np.asarray(engine.decode_loop(uids, tokens, K))
            except SchedulingError:
                rows = None  # KV too tight for K steps — single-step fallback
            if rows is not None:
                # record before pushing: the final token finalizes the request
                # and closes the root span, which children must nest inside —
                # with the kept-token counts driving BOTH the span args and
                # the push loop, so trace and stream cannot disagree
                counts = [self._kept_tokens(req, row)
                          for (req, _), row in zip(plan, rows)]
                self._rate.observe(sum(counts))
                # billed work is what the device ran: K decode steps per
                # member, kept or not (the discarded over-run still computed)
                self._charge_members([(req, "decode", K) for req, _ in plan])
                _record_phase_spans(counts=counts)
                for (req, _), row, kept in zip(plan, rows, counts):
                    req.decode_steps += 1
                    # eos/cap discard the over-generated tail
                    self._push_burst(req, row[:kept])
                return

        try:
            logits = np.asarray(engine.put(uids, tokens))
        except Exception as e:  # pragma: no cover - defensive: the scheduler
            # thread must survive an engine fault; the batch's requests fail
            logger.exception("serving: engine.put failed; failing the batch")
            for req, _ in plan:
                self._finalize(req, RequestState.FAILED, error=f"engine error: {e}")
            return
        self._rate.observe(sum(int(t.size) for t in tokens))
        # attribute BEFORE the processing loop flips any PREFILL to DECODE
        self._charge_members(
            [(req, "prefill" if req.state is RequestState.PREFILL else "decode",
              int(toks.size)) for req, toks in plan])
        _record_phase_spans()
        for i, (req, toks) in enumerate(plan):
            if req.state is RequestState.PREFILL:
                self._advance_prefill(req, toks, logits[i])
            else:
                req.decode_steps += 1
                nxt = self._sample(req, logits[i])
                self._push_token(req, nxt)
                if not req.finished:
                    req._next = nxt

    def _advance_prefill(self, req: Request, toks: np.ndarray, last_row) -> None:
        """Account one executed prefill chunk; on the final chunk: flip to
        DECODE, publish the prompt's blocks (peers sharing the prefix are
        likely already queued behind it — the burst shape), and emit the
        first token from the chunk's final-position logits. Shared by the
        put and verify execute paths so prefill behavior cannot depend on
        whether a draft rode the same batch."""
        req._fed += toks.size
        if req._fed < req.prompt.size:
            return  # mid-prefill logits are meaningless
        req._set_state(RequestState.DECODE)
        if self._prefix_cache is not None:
            seq = self._engine._state_manager.get_sequence(req.uid)
            if seq is not None:
                self._publish(req, seq, req.prompt, seq.seen_tokens)
        nxt = self._sample(req, last_row)
        self._push_token(req, nxt)
        if not req.finished:
            req._next = nxt

    def _push_burst(self, req: Request, toks) -> None:
        """Stream a multi-token burst (a decode chunk's kept tokens, a verify
        step's emitted run): pushes honor :meth:`_push_token`'s finish rules,
        ``req._next`` advances to the last pushed token, and the dispatch gap
        is amortized per token so ITL reflects the cadence a client sees
        rather than the microsecond host loop."""
        prev = req._last_token_s
        pushed = 0
        for tok in toks:
            self._push_token(req, int(tok), record_itl=False)
            pushed += 1
            if req.finished:
                break  # _push_token's rules stay the authority
        if not req.finished and pushed:
            req._next = int(toks[pushed - 1])
        if self._metrics and prev is not None and pushed:
            gap = (req._last_token_s - prev) / pushed
            for _ in range(pushed):
                self._metrics.itl.observe(gap)

    def _execute_verify(self, plan: List[Tuple[Request, np.ndarray]],
                        record_spans) -> None:
        """Execute a tick containing speculative verify feeds. The decode
        entries (each a next-input token plus k drafts) run through ONE
        ``engine.verify`` dispatch; prefill chunks sharing the tick run
        through their normal ``engine.put`` — a prefill bucket must not pay
        the verify program's all-position unembed (and a [T, vocab] logits
        transfer at prefill widths) for a peer's draft. Each decode entry
        accepts its longest matching draft prefix, rolls the rejected tail
        back (write-then-truncate on ``seen_tokens``) and streams the
        emitted tokens."""
        engine = self._engine
        decode_plan = [(req, toks) for req, toks in plan
                       if req.state is not RequestState.PREFILL]
        prefill_plan = [(req, toks) for req, toks in plan
                        if req.state is RequestState.PREFILL]
        try:
            per_seq = engine.verify([req.uid for req, _ in decode_plan],
                                    [toks for _, toks in decode_plan])
            # stash the verify dispatch's observed wall time before the
            # prefill put overwrites the observer slots
            verify_s = self._last_dispatch_s
            verify_amnesty_s = self._last_dispatch_amnesty_s
            prefill_logits = (np.asarray(engine.put(
                [req.uid for req, _ in prefill_plan],
                [toks for _, toks in prefill_plan])) if prefill_plan else None)
        except Exception as e:  # pragma: no cover - defensive: same contract
            # as the put path — the scheduler thread must survive
            logger.exception("serving: engine verify tick failed; failing the batch")
            for req, _ in plan:
                self._finalize(req, RequestState.FAILED, error=f"engine error: {e}")
            return
        # the estimator measures engine-token throughput: verify feeds cost
        # their full width (accepted or not), like any other fed token
        self._rate.observe(sum(int(t.size) for _, t in plan))
        self._charge_members([(req, "verify", int(t.size))
                              for req, t in decode_plan],
                             seconds=verify_s, amnesty=verify_amnesty_s)
        if prefill_plan:
            self._charge_members([(req, "prefill", int(t.size))
                                  for req, t in prefill_plan])
        alpha = self._config.speculative.accept_alpha
        # sample/accept BEFORE any push: span token counts must be final when
        # the root span closes, and each request's private stream makes the
        # per-request draw order independent of processing order
        accepts = {id(req): self._spec_accept(req, toks, rows)
                   for (req, toks), rows in zip(decode_plan, per_seq)}
        record_spans(counts=[len(accepts[id(req)][0]) if id(req) in accepts
                             else int(toks.size) for req, toks in plan])
        for (req, toks), rows in zip(decode_plan, per_seq):
            emitted, accepted = accepts[id(req)]
            k = int(toks.size) - 1
            rejected = int(toks.size) - len(emitted)
            # rollback BEFORE pushing: a push may finalize, and the handoff
            # export / trie publish there must see the truncated seen_tokens
            # (= full history - 1, the same invariant every other path keeps)
            engine.rollback(req.uid, rejected)
            req.decode_steps += 1
            if k:
                # a k=0 feed riding a verify batch proposed nothing — no
                # acceptance evidence, no EWMA movement
                req.spec_drafted += k
                req.spec_accepted += accepted
                if self._ledger is not None and req.cost is not None:
                    self._ledger.charge_spec(req.cost, k, accepted)
                self._counters["spec_steps"] += 1
                self._counters["spec_drafted"] += k
                self._counters["spec_rollback"] += rejected
                self._counters["spec_accepted"] += accepted
                rate = accepted / k
                req._spec_ewma = (rate if req._spec_ewma is None
                                  else alpha * rate + (1 - alpha) * req._spec_ewma)
                self._spec_accept_ewma = (rate if self._spec_accept_ewma is None
                                          else alpha * rate
                                          + (1 - alpha) * self._spec_accept_ewma)
                if self._metrics:
                    self._metrics.spec_verify_steps.inc()
                    self._metrics.spec_drafted.inc(k)
                    self._metrics.spec_accepted.inc(accepted)
                    self._metrics.spec_rollback.inc(rejected)
                    self._metrics.spec_accept_rate.set(self._spec_accept_ewma or 0.0)
                    self._metrics.spec_tokens_per_step.observe(len(emitted))
            self._push_burst(req, emitted)
        for i, (req, toks) in enumerate(prefill_plan):
            self._advance_prefill(req, toks, prefill_logits[i])

    def _spec_accept_tree(self, req: Request, tree, rows, ids):
        """The acceptance rule over one verified token tree. Walk from the
        root: each emitted token is sampled (or argmaxed) from the target
        distribution with the request's own stream — one draw per emitted
        token, same draw order as spec-off — then the walk descends into the
        child CARRYING that token while one exists (rejection sampling with a
        point-mass draft at each branch). The deepest matching path is
        accepted; the first disagreement's sampled token is the bonus
        emission. Returns ``(emitted, path, last_node)``: ``path`` lists the
        accepted draft node indices (root-exclusive, the compaction input)
        and ``last_node`` is the deepest CONSUMED node, whose hidden state
        seeds the next learned draft. Emission stops at eos / the generation
        cap, mirroring :meth:`_push_token`'s rules."""
        emitted: List[int] = []
        path: List[int] = []
        node = 0
        while True:
            tok = (int(ids[node]) if rows is None
                   else self._sample(req, rows[node]))
            emitted.append(tok)
            if req.eos_token_id is not None and tok == req.eos_token_id:
                break
            if len(req.tokens) + len(emitted) >= req.max_new_tokens:
                break
            child = tree.child_with_token(node, tok)
            if child is None:
                break  # rejection: the target disagrees with every branch
            path.append(child)
            node = child
        return emitted, path, node

    def _execute_verify_tree(self, plan: List[Tuple[Request, np.ndarray]],
                             record_spans) -> None:
        """Execute a tick whose decode entries carry TokenTree feeds (the
        learned/auto drafter modes). Every tree — branching, chain, or
        root-only — verifies in ONE ``engine.verify_tree`` dispatch; prefill
        chunks sharing the tick keep their normal ``engine.put`` (same split
        as :meth:`_execute_verify`, same reason). Each entry accepts its
        deepest matching path under the spec-off sampling rule, compacts the
        accepted path's KV left behind the committed history (tree-aware
        write-then-truncate) and streams the emitted run; the deepest
        consumed node's hidden state is captured for the next learned
        draft."""
        engine = self._engine
        decode_plan = [(req, toks) for req, toks in plan
                       if req.state is not RequestState.PREFILL]
        prefill_plan = [(req, toks) for req, toks in plan
                        if req.state is RequestState.PREFILL]
        trees = []
        for req, toks in decode_plan:
            tree = req._spec_tree
            req._spec_tree = None
            if tree is None:  # defensive: a plain feed rides as a chain
                from deepspeed_tpu.inference.v2.spec import TokenTree
                tree = TokenTree.chain(toks)
            trees.append(tree)
        # the device-argmax program only when EVERY decode entry is greedy: a
        # sampled request needs the full rows for its private stream (greedy
        # peers argmax the same f32 rows host-side — the identical result)
        greedy = all(req.temperature <= 0.0 for req, _ in decode_plan)
        try:
            per_seq = engine.verify_tree([req.uid for req, _ in decode_plan],
                                         trees, greedy=greedy)
            # stash the tree-verify dispatch's observed wall time before the
            # prefill put overwrites the observer slots
            verify_s = self._last_dispatch_s
            verify_amnesty_s = self._last_dispatch_amnesty_s
            prefill_logits = (np.asarray(engine.put(
                [req.uid for req, _ in prefill_plan],
                [toks for _, toks in prefill_plan])) if prefill_plan else None)
        except Exception as e:  # pragma: no cover - defensive: same contract
            # as the put path — the scheduler thread must survive
            logger.exception("serving: tree-verify tick failed; failing the batch")
            for req, _ in plan:
                self._finalize(req, RequestState.FAILED, error=f"engine error: {e}")
            return
        # verify feeds cost their full width (accepted or not), like any fed
        # token — tree nodes included
        self._rate.observe(sum(int(t.size) for _, t in plan))
        self._charge_members([(req, "tree_verify", int(t.size))
                              for req, t in decode_plan],
                             seconds=verify_s, amnesty=verify_amnesty_s)
        if prefill_plan:
            self._charge_members([(req, "prefill", int(t.size))
                                  for req, t in prefill_plan])
        alpha = self._config.speculative.accept_alpha
        # sample/accept BEFORE any push: span token counts must be final when
        # the root span closes, and each request's private stream makes the
        # per-request draw order independent of processing order
        accepts = {id(req): self._spec_accept_tree(req, tree,
                                                   res["rows"], res["ids"])
                   for (req, _), tree, res in zip(decode_plan, trees, per_seq)}
        record_spans(counts=[len(accepts[id(req)][0]) if id(req) in accepts
                             else int(toks.size) for req, toks in plan])
        for (req, toks), tree, res in zip(decode_plan, trees, per_seq):
            emitted, path, last_node = accepts[id(req)]
            k = tree.size - 1  # draft nodes proposed (the root is the input)
            accepted = len(path)
            # compact BEFORE pushing (a push may finalize, and the handoff
            # export / trie publish there must see the truncated seen_tokens):
            # accepted-path KV moves contiguously behind the committed
            # history, the rejected remainder truncates off — the same
            # full-history-minus-1 invariant every other path leaves behind
            rejected = engine.compact_accepted(req.uid, tree.size, path)
            req.decode_steps += 1
            # the hidden state behind the next decode input is the deepest
            # CONSUMED node's residual; _spec_hidden_pos stamps the history
            # length it is valid at (stale after any gap: handoff, brownout)
            hidden = res.get("hidden")
            if hidden is not None:
                req._spec_hidden = np.asarray(hidden[last_node], np.float32)
                req._spec_hidden_pos = (int(req.prompt.size) + len(req.tokens)
                                        + len(emitted))
            self._counters["spec_tree_nodes"] += tree.size
            compacted = any(p != j + 1 for j, p in enumerate(path))
            if compacted:
                self._counters["spec_tree_compactions"] += 1
            if self._metrics:
                self._metrics.spec_tree_nodes.inc(tree.size)
                if compacted:
                    self._metrics.spec_tree_compactions.inc()
            if k:
                # a root-only bootstrap proposed nothing — no acceptance
                # evidence, no EWMA movement (linear-path rule, tree-shaped)
                drafter = req._spec_last_drafter or self._drafter_mode
                short = "learned" if drafter == "learned" else "lookup"
                # the arbitration/adaptation signal is DEPTH productivity:
                # accepted serial depth over proposed depth — comparable
                # across a branching tree and a linear chain at the same k
                rate = accepted / max(int(tree.max_depth), 1)
                req.spec_drafted += k
                req.spec_accepted += accepted
                if self._ledger is not None and req.cost is not None:
                    self._ledger.charge_spec(req.cost, k, accepted)
                self._counters["spec_steps"] += 1
                self._counters["spec_drafted"] += k
                self._counters["spec_rollback"] += rejected
                self._counters["spec_accepted"] += accepted
                self._counters[f"spec_drafted_{short}"] += k
                self._counters[f"spec_accepted_{short}"] += accepted
                req._spec_ewma = (rate if req._spec_ewma is None
                                  else alpha * rate + (1 - alpha) * req._spec_ewma)
                self._arb_update(req, drafter, rate)
                self._spec_accept_ewma = (rate if self._spec_accept_ewma is None
                                          else alpha * rate
                                          + (1 - alpha) * self._spec_accept_ewma)
                if self._metrics:
                    self._metrics.spec_verify_steps.inc()
                    self._metrics.spec_drafted.inc(k)
                    self._metrics.spec_accepted.inc(accepted)
                    self._metrics.spec_rollback.inc(rejected)
                    self._metrics.spec_accept_rate.set(self._spec_accept_ewma or 0.0)
                    self._metrics.spec_tokens_per_step.observe(len(emitted))
                    self._metrics.spec_tree_accept_depth.observe(accepted)
            self._push_burst(req, emitted)
        for i, (req, toks) in enumerate(prefill_plan):
            self._advance_prefill(req, toks, prefill_logits[i])

    @staticmethod
    def _kept_tokens(req: Request, row) -> int:
        """How many of a decode-loop ``row``'s tokens the client will receive
        — the device loop always runs K steps; eos / the max_new_tokens cap
        cut the tail. Mirrors :meth:`_push_token`'s termination rules (the
        per-token authority); keep the two in lock-step."""
        n = 0
        for tok in row:
            n += 1
            if ((req.eos_token_id is not None and int(tok) == req.eos_token_id)
                    or len(req.tokens) + n >= req.max_new_tokens):
                break
        return n

    @staticmethod
    def _sample(req: Request, row: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        if req._rng is None:
            req._rng = np.random.default_rng(req.seed)
        z = row.astype(np.float64) / req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(req._rng.choice(row.shape[0], p=p))

    def _push_token(self, req: Request, tok: int, record_itl: bool = True) -> None:
        now = time.monotonic()
        req.tokens.append(tok)
        if req.first_token_s is None:
            req.first_token_s = now
            if self._metrics:
                self._metrics.ttft.observe(now - req.arrival_s)
        elif self._metrics and record_itl:
            self._metrics.itl.observe(now - req._last_token_s)
        req._last_token_s = now
        req.stream.put(tok)
        if req.eos_token_id is not None and tok == req.eos_token_id:
            req.finish_reason = "eos"
            self._finalize(req, RequestState.DONE)
        elif len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
            self._finalize(req, RequestState.DONE)

    # -------------------------------------------------------------- finalize --
    _FINAL_COUNTER = {RequestState.DONE: "completed", RequestState.CANCELLED: "cancelled",
                      RequestState.TIMED_OUT: "timed_out", RequestState.FAILED: "failed"}

    def _export_handoff(self, req: Request) -> bytes:
        """Portable continuation payload for a DONE handoff-requested request:
        full token history, KV blocks, next decode input and the sampler's
        exact RNG state — everything :meth:`submit_resume` on a decode-role
        peer needs to continue token-identically. Runs on the scheduler
        thread, before the sequence's KV is flushed."""
        extra = {"generated": len(req.tokens)}
        if req.finish_reason == "length" and req.tokens:
            # an eos/context finish is not continuable; length means the donor
            # stopped at ITS cap with the last kept token as the next input
            extra["next_token"] = int(req.tokens[-1])
        if req._rng is not None:
            extra["rng_state"] = req._rng.bit_generator.state
        # the dispatch count rides every handoff (tokens-per-step accounting
        # must survive the migration whether or not the donor ever drafted)
        extra["decode_steps"] = req.decode_steps
        if req._spec_ewma is not None or req.spec_drafted:
            # drafter state rides the handoff: the decode-role peer continues
            # the acceptance adaptation exactly where the donor stopped (no
            # cold re-probe tax on a mid-stream migration)
            extra["spec"] = {"accept_ewma": req._spec_ewma,
                             "drafted": req.spec_drafted,
                             "accepted": req.spec_accepted}
            if req._spec_ewmas:
                # per-drafter EWMAs: an "auto" peer resumes the arbitration
                # mid-race instead of re-exploring both drafters cold
                extra["spec"]["drafters"] = {
                    name: val for name, val in req._spec_ewmas.items()
                    if val is not None}
            if self._spec_head_id is not None:
                # which trained heads produced the learned EWMA: a peer with
                # different heads must not inherit their acceptance record
                extra["spec"]["head_id"] = self._spec_head_id
        tokens = [int(t) for t in req.prompt.tolist()] + [int(t) for t in req.tokens]
        # chunked greedy decode feeds the device ahead of the kept history (a
        # mid-chunk cap leaves the last kept token — and discarded over-run —
        # already committed). Export seen = history-1 so the recipient re-feeds
        # the last token: deterministic, same KV values into the same slot,
        # and the continuation stays exactly aligned.
        return self._engine.export_sequence(req.uid, tokens=tokens, extra=extra,
                                            seen_tokens=len(tokens) - 1)

    def _export_park(self, req: Request) -> bytes:
        """Version-2 park frame for a finished park-requested request: the
        handoff export plus a versioned ``tier`` record (which tier the KV
        was resident on at finish — what the rehydrate response reports).
        Unlike a handoff, an eos finish IS parkable: the next turn continues
        from the full history via a rehydrate prompt, not from ``next_token``.
        The parked ``rng_state`` is informational — a rehydrate samples on
        its own seed so the returning turn matches a cold run bitwise."""
        from deepspeed_tpu.inference.v2.ragged.handoff import (PARK_VERSION,
                                                               TIER_FIELD_VERSION)
        sm = self._engine._state_manager
        source = sm.sequence_tier(req.uid)  # capture BEFORE export restores
        extra = {"generated": len(req.tokens),
                 "decode_steps": req.decode_steps,
                 "tier": {"v": TIER_FIELD_VERSION, "source": source}}
        if req.finish_reason == "length" and req.tokens:
            extra["next_token"] = int(req.tokens[-1])
        if req._rng is not None:
            extra["rng_state"] = req._rng.bit_generator.state
        tokens = [int(t) for t in req.prompt.tolist()] + [int(t) for t in req.tokens]
        return self._engine.export_sequence(req.uid, tokens=tokens, extra=extra,
                                            seen_tokens=len(tokens) - 1,
                                            version=PARK_VERSION)

    def _finalize(self, req: Request, state: RequestState, error: Optional[str] = None) -> None:
        """Terminal transition on the scheduler thread: free engine state
        (tracked OR offloaded KV), close the stream, account."""
        if req.finished:
            return
        req.error = error
        if req.uid is not None:
            self._active.pop(req.uid, None)
            seq = self._engine._state_manager.get_sequence(req.uid)
            if seq is not None:
                if (state is RequestState.DONE and req.handoff_requested
                        and req.finish_reason == "length" and req.tokens):
                    # export BEFORE flushing: the payload reads the sequence's
                    # live KV blocks (fleet prefill→decode handoff). An eos/
                    # context finish is not continuable — exporting it would
                    # device_get the whole KV only for the router to discard it
                    try:
                        req.handoff_payload = self._export_handoff(req)
                        if self._ledger is not None and req.cost is not None:
                            self._ledger.charge_wire(req.cost, "handoff",
                                                     len(req.handoff_payload))
                    except Exception:  # pragma: no cover - defensive: a failed
                        # export degrades to a non-continuable response
                        logger.exception(f"serving: handoff export failed for "
                                         f"uid {req.uid}")
                if (state is RequestState.DONE and req.park_requested
                        and req.finish_reason in ("length", "eos")
                        and req.tokens):
                    # park BEFORE flushing, same reason as the handoff export;
                    # eos finishes park too (a multi-turn session's next turn
                    # rehydrates with a longer prompt, no next_token needed)
                    try:
                        req.park_payload = self._export_park(req)
                        if self._ledger is not None and req.cost is not None:
                            self._ledger.charge_wire(req.cost, "park",
                                                     len(req.park_payload))
                        self._counters["parks"] += 1
                    except Exception:  # pragma: no cover - defensive: a failed
                        # park degrades to a cold next turn
                        logger.exception(f"serving: park export failed for "
                                         f"uid {req.uid}")
                if (self._prefix_cache is not None and state is RequestState.DONE
                        and not self._engine.is_offloaded(req.uid)):
                    # publish BEFORE flushing: the trie takes references on the
                    # full blocks, so flush's decref leaves them cached instead
                    # of freed (an offloaded sequence's table is stale — its
                    # device blocks were already returned — so it cannot
                    # publish)
                    self._publish_finished(req, seq)
                self._engine.flush(req.uid)  # returns KV blocks (incl. offloaded)
        req._set_state(state)
        self._counters[self._FINAL_COUNTER[state]] += 1
        if self._ledger is not None and req.cost is not None:
            # close the open KV segment and fold the bill into the tenant
            # rollup — conservation holds once every request finalizes
            self._ledger.finalize(req, time.monotonic())
        spans = self._spans  # bind once: the property re-resolves
        if spans is not None and req.trace_id is not None:
            # the trace's root: arrival → terminal state, with the ids every
            # lifecycle child span parented under; a routed request's root
            # itself parents under the fleet router's span
            spans.record("request", cat="serving", ts_us=req.arrival_us,
                         dur_us=now_us() - req.arrival_us,
                         trace_id=req.trace_id, span_id=req.root_span_id,
                         parent_id=req.parent_span_id,
                         args={"uid": req.uid, "state": state.name,
                               "finish_reason": req.finish_reason,
                               "prompt_tokens": int(req.prompt.size),
                               "cached_tokens": req.cached_tokens,
                               "generated": len(req.tokens),
                               "resumed": req._resume_header is not None})
        if self._metrics:
            {RequestState.DONE: self._metrics.completions,
             RequestState.CANCELLED: self._metrics.cancellations,
             RequestState.TIMED_OUT: self._metrics.timeouts,
             RequestState.FAILED: self._metrics.failures}[state].inc()
            self._metrics.e2e.observe(req.e2e_s)
            self._metrics.in_flight.set(len(self._active))

    # ------------------------------------------------------------------ loop --
    def _run(self) -> None:
        self._ready.set()  # readiness gate: the loop is ticking
        while not self._shutdown:
            if self._kill_reason is not None:
                self._die()  # in-flight disposition on the engine-owning thread
                return
            flight = telemetry.get_flight_recorder()
            if flight is not self._flight:
                self._attach_flight(flight)
            if flight is not None:
                flight.heartbeat(self._flight_channel)
            try:
                progressed = self.step()
            except Exception:  # pragma: no cover - must never kill the thread
                logger.exception("serving scheduler: step() raised")
                progressed = False
            if not progressed:
                self._maybe_heartbeat()
                time.sleep(self._config.scheduler_tick_s)

    def _maybe_heartbeat(self) -> None:
        enabled = self._config.heartbeat_enabled
        if enabled is None:
            enabled = self._engine._config.expert_parallel.enabled
        if not enabled:
            return
        now = time.monotonic()
        if now - self._last_heartbeat_s >= self._config.heartbeat_interval_s:
            self._last_heartbeat_s = now
            self._counters["heartbeats"] += 1
            self._engine.empty_run()

    # ------------------------------------------------------------------ stop --
    @property
    def ready(self) -> bool:
        """Readiness (the ``/healthz`` gate): the background loop has started
        ticking — requests submitted now will actually be scheduled. A
        manually-driven scheduler (``start=False``) is ready by construction;
        a stopped/killed one is not."""
        if self._stopped:
            return False
        return self._ready.is_set() or self._thread is None

    def kill(self, reason: str = "killed") -> None:
        """Abrupt-death disposition (the fault-injection / supervisor path —
        ``stop()`` is the graceful sibling): no drain, every queued and
        in-flight request is finalized FAILED with a ``replica killed:``
        error so streams and legs observe the death as a terminal event, KV
        blocks return to the pool, and the loop exits. Idempotent."""
        if self._stopped or self._killed:
            return
        with self._not_full:
            self._stopping = True
            self._kill_reason = reason
            self._not_full.notify_all()  # wake blocked submitters
        if self._thread is not None:
            self._thread.join()  # _run sees the flag and runs _die()
            self._thread = None
        else:
            self._die()

    def _die(self) -> None:
        """The kill disposition, on the engine-owning thread: fail everything
        terminal, free KV, detach, mark dead."""
        error = f"{KILLED_ERROR_PREFIX}: {self._kill_reason or 'killed'}"
        for req in list(self._active.values()):
            self._finalize(req, RequestState.FAILED, error=error)
        while self._queue:
            self._finalize(self._queue.popleft(), RequestState.FAILED, error=error)
        self._shutdown = True
        self._killed = True
        self._fail_control()  # waiters observe the death, not a timeout
        if self._prefix_cache is not None:
            self._prefix_cache.clear()  # unpin the trie's blocks
            if self._metrics:
                self._metrics.prefix_trie_blocks.set(0)
        if getattr(self._engine, "_serving_scheduler", None) is self:
            self._engine._serving_scheduler = None
        self._detach_observer()
        self._attach_flight(None)
        self._stopped = True

    def _detach_observer(self) -> None:
        """Clear the engine's dispatch observer iff it is still ours — a
        stopped scheduler must not keep feeding (or block a successor from
        installing) the cost plane's timing hook."""
        if getattr(self._engine, "dispatch_observer", None) == self._on_dispatch:
            self._engine.dispatch_observer = None

    def _has_work(self) -> bool:
        return (bool(self._queue) or bool(self._active)
                or self._admitting is not None)

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop the scheduler: no further admissions; with ``drain`` in-flight
        and queued requests get up to ``timeout`` (default
        ``config.drain_timeout_s``) to finish, then the remainder is
        CANCELLED. Idempotent."""
        if self._stopped:
            return
        if timeout is None:
            timeout = self._config.drain_timeout_s
        with self._not_full:
            self._stopping = True
            self._not_full.notify_all()  # wake blocked submitters
        deadline = time.monotonic() + (timeout if drain else 0.0)
        if self._thread is not None:
            while drain and self._has_work() and time.monotonic() < deadline:
                time.sleep(min(self._config.scheduler_tick_s, 0.01))
            self._shutdown = True
            self._thread.join()
            self._thread = None
        else:
            while drain and self._has_work() and time.monotonic() < deadline:
                if not self.step():
                    time.sleep(self._config.scheduler_tick_s)
        # cancel whatever drain didn't finish (scheduler thread is dead, so
        # touching the engine from here is safe)
        self._fail_control()
        for req in list(self._active.values()):
            self._finalize(req, RequestState.CANCELLED)
        while self._queue:
            self._finalize(self._queue.popleft(), RequestState.CANCELLED)
        if self._prefix_cache is not None:
            # unpin the trie's blocks: a stopped scheduler leaves the engine's
            # KV pool exactly as it found it (shared blocks survive until any
            # still-tracked sequence flushes)
            self._prefix_cache.clear()
            if self._metrics:
                self._metrics.prefix_trie_blocks.set(0)
        if getattr(self._engine, "_serving_scheduler", None) is self:
            self._engine._serving_scheduler = None
        self._detach_observer()
        self._attach_flight(None)
        self._stopped = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=False)

    # ----------------------------------------------------------------- stats --
    @property
    def queue_depth(self) -> int:
        # an in-admission request (popped, importing) still counts as pending
        # work: drain budgets and least-loaded dispatch must not miss it
        return len(self._queue) + (1 if self._admitting is not None else 0)

    @property
    def n_active(self) -> int:
        return len(self._active)

    def _snapshot_requests(self) -> Tuple[List[Request], List[Request]]:
        """(queued, active) request lists copied for reader threads (stats /
        flight dumps). Prefers a brief lock so the copy is consistent with
        admission; falls back to a lockless copy (GIL-atomic in CPython) when
        the scheduler thread is wedged holding the lock — a flight dump of a
        stalled loop must never block on that same loop's lock."""
        locked = self._lock.acquire(timeout=0.2)
        try:
            return list(self._queue), list(self._active.values())
        finally:
            if locked:
                self._lock.release()

    @staticmethod
    def _request_row(req: Request, now: float) -> dict:
        return {
            "uid": req.uid,
            "state": req.state.name,
            "priority": req.priority,
            "tenant": req.tenant,
            "prompt_tokens": int(req.prompt.size),
            "cached_tokens": req.cached_tokens,
            "generated": len(req.tokens),
            "age_s": now - req.arrival_s,
            "ttft_s": req.ttft_s,
            "trace_id": req.trace_id,
            # cost-to-date (None with telemetry off): post-mortems and the
            # stats surface see the bill as it accrues, not only at the end
            "cost": req.cost.compact_row() if req.cost is not None else None,
        }

    def _latency_percentiles(self) -> Optional[dict]:
        """p50/p95/p99 TTFT/ITL/e2e from the telemetry histograms' buckets
        (Histogram.quantile) — None when telemetry is disabled."""
        if not self._metrics:
            return None
        out = {}
        for name, hist in (("ttft_s", self._metrics.ttft),
                           ("itl_s", self._metrics.itl),
                           ("e2e_s", self._metrics.e2e)):
            out[name] = {f"p{int(q * 100)}": hist.quantile(q)
                         for q in (0.5, 0.95, 0.99)}
        return out

    def _spec_stats(self) -> Optional[dict]:
        if self._drafter is None:
            return None
        drafted = self._counters["spec_drafted"]
        out = {
            "enabled": True,
            "drafter": self._drafter_mode,
            "drafted": drafted,
            "accepted": self._counters["spec_accepted"],
            "accept_rate": (self._counters["spec_accepted"] / drafted
                            if drafted else 0.0),
            "accept_ewma": self._spec_accept_ewma,
            "verify_steps": self._counters["spec_steps"],
            "rollback_tokens": self._counters["spec_rollback"],
            "max_draft_tokens": self._config.speculative.max_draft_tokens,
        }
        if self._drafter_mode != "prompt_lookup":
            scfg = self._config.speculative
            out["head_id"] = self._spec_head_id
            out["tree"] = {
                "nodes": self._counters["spec_tree_nodes"],
                "compactions": self._counters["spec_tree_compactions"],
                "width": scfg.tree_width,
                "node_budget": scfg.tree_node_budget,
            }
            out["drafter_switches"] = self._counters["spec_drafter_switches"]
            out["drafters"] = {
                name: {"drafted": self._counters[f"spec_drafted_{short}"],
                       "accepted": self._counters[f"spec_accepted_{short}"],
                       "ewma": self._spec_drafter_ewmas.get(name)}
                for name, short in (("learned", "learned"),
                                    ("prompt_lookup", "lookup"))}
        return out

    def usage(self) -> dict:
        """The ``/v1/usage`` document: ledger totals, per-tenant rollups,
        pricing, and the fair-share posture. ``{"enabled": False}`` with
        telemetry (or the cost plane) off — the endpoint stays useful as a
        feature probe either way."""
        doc = (self._ledger.usage_doc() if self._ledger is not None
               else {"enabled": False})
        if self._fair_share is not None:
            doc["fair_share"] = self._fair_share.doc()
        return doc

    def stats(self) -> dict:
        queued, active = self._snapshot_requests()
        return self._stats_doc(queued, active)

    def _stats_doc(self, queued: List[Request], active: List[Request]) -> dict:
        now = time.monotonic()
        prefix_stats = None
        if self._prefix_cache is not None:
            prefix_stats = self._prefix_cache.stats()
            # the router hashes a request's chain with the replica's block
            # size — it must ride the same doc as the digest catalog
            prefix_stats["block_size"] = self._engine._state_manager.kv_block_size
            digests = self.prefix_digest_catalog()
            if digests is not None:
                # the fleet-visible trie shape: an HTTP replica's probe reads
                # /v1/stats, so the digest catalog rides the same doc the
                # local probe reads directly
                prefix_stats["digests"] = digests
        return {
            "queue_depth": len(queued),
            "active": {
                "total": len(active),
                "prefill": sum(1 for r in active if r.state is RequestState.PREFILL),
                "decode": sum(1 for r in active if r.state is RequestState.DECODE),
            },
            "requests": [self._request_row(r, now) for r in active],
            "latency": self._latency_percentiles(),
            "counters": dict(self._counters),
            "engine": {
                "free_blocks": self._engine.free_blocks,
                "capacity_blocks": self._capacity_blocks,
                "tracked_sequences": self._engine._state_manager.n_tracked_sequences,
            },
            "prefix_cache": prefix_stats,
            "speculative": self._spec_stats(),
            "kv_tiers": (self._kv_tiers.stats(self._prefix_cache)
                         if self._kv_tiers is not None else None),
            "usage": self.usage(),
            "perf": (self._perf_obs.doc()
                     if self._perf_obs is not None else None),
            "timeseries": (ts.snapshot(max_points=64)
                           if (ts := telemetry.get_timeseries()) is not None
                           else None),
            "slo": (slo.status()
                    if (slo := telemetry.get_slo_engine()) is not None
                    else None),
            "overload": {
                "enabled": self._config.overload.enabled,
                "brownout_stage": self._brownout.stage,
                "pressure": round(self._brownout.pressure, 4),
                "rate_tokens_per_s": self._rate.rate,
                "retry_after_s": round(self.retry_after_s(), 3),
            },
            "draining": self._stopping,
            "uptime_s": time.monotonic() - self._start_s,
        }

    def flight_state(self) -> dict:
        """The flight recorder's view: ``stats()`` plus queued-request rows,
        per-request scheduler internals and KV occupancy — everything a
        post-mortem of a wedged loop needs."""
        now = time.monotonic()
        queued, active = self._snapshot_requests()
        doc = self._stats_doc(queued, active)
        doc["queued_requests"] = [self._request_row(r, now) for r in queued]
        engine = self._engine
        rows = []
        for req in active:
            row = self._request_row(req, now)
            seq = engine._state_manager.get_sequence(req.uid)
            row.update(
                fed_tokens=req._fed,
                cached_tokens=req.cached_tokens,
                deferred_ticks=req._deferred,
                deadline_in_s=(req.deadline - now) if req.deadline is not None else None,
                kv_blocks=seq.cur_allocated_blocks if seq is not None else 0,
                offloaded=engine.is_offloaded(req.uid),
            )
            rows.append(row)
        doc["requests"] = rows
        doc["starved_ticks"] = self._starved_ticks
        return doc
