"""ReplicaSupervisor unit tests (ISSUE satellite): readiness gate, backoff
schedule, exit/hang detection, restart, crash-loop quarantine — against both
in-process (local) slots and real subprocesses (the stdlib stub server, so no
jax import per spawn)."""

import os
import signal
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.fleet import (FleetConfig, FleetRouter, ReplicaManager,
                                 ReplicaState, SlotState, SupervisorConfig,
                                 backoff_delay)
from deepspeed_tpu.fleet.supervisor import ReplicaSupervisor

STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "stub_replica.py")

FAST = dict(poll_interval_s=0.05, ready_timeout_s=5.0,
            restart_backoff_base_s=0.05, restart_backoff_cap_s=0.2,
            restart_jitter_frac=0.0)


def _stub_cmd(mode="serve", ttl_s=0.5):
    return [sys.executable, STUB, "--port-file", "{port_file}",
            "--mode", mode, "--ttl-s", str(ttl_s)]


def _fleet_config(**kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("connect_timeout_s", 1.0)
    kw.setdefault("read_timeout_s", 1.0)
    kw.setdefault("probe_backoff_cap_s", 0.1)
    kw.setdefault("retry_backoff_base_s", 0.0)
    return FleetConfig(**kw)


def _wait(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# the shared backoff policy
# ---------------------------------------------------------------------------
def test_backoff_delay_grows_caps_and_jitters():
    base, cap = 0.5, 10.0
    bare = [backoff_delay(k, base, cap) for k in range(8)]
    assert bare[:4] == [0.5, 1.0, 2.0, 4.0]
    assert bare[-1] == cap  # capped, not unbounded
    assert bare == sorted(bare)
    # jitter is BOUNDED: d*(1±j), deterministic in the caller's draw
    lo = backoff_delay(2, base, cap, jitter_frac=0.25, u=0.0)
    hi = backoff_delay(2, base, cap, jitter_frac=0.25, u=1.0 - 1e-12)
    assert lo == pytest.approx(2.0 * 0.75)
    assert hi == pytest.approx(2.0 * 1.25, rel=1e-6)
    assert backoff_delay(2, base, cap, 0.25, u=0.5) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# local-backed slots (in-process replicas, real engines)
# ---------------------------------------------------------------------------
@pytest.fixture
def supervised_local(make_fleet):
    """One supervised local slot over the shared engine factory."""
    manager = make_fleet(roles=(), config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=3, crash_window_s=60.0, **FAST))
    slot = supervisor.add_local(role="mixed")
    supervisor.start()
    yield manager, supervisor, slot
    supervisor.stop()


def test_local_readiness_gate_then_dispatchable(supervised_local):
    manager, supervisor, slot = supervised_local
    assert supervisor.wait_ready(timeout=30.0)
    assert slot.state is SlotState.READY
    # registration happened only after readiness: the replica is dispatchable
    assert manager.pool_size("mixed") == 1
    router = FleetRouter(manager)
    doc = router.route({"prompt": [1, 2, 3], "max_new_tokens": 2}).result()
    assert doc["state"] == "DONE"
    # surfaced in /v1/fleet/stats via the manager
    stats = router.fleet_stats()
    assert stats["supervisor"]["slots"][0]["state"] == "READY"
    assert stats["supervisor"]["restarts"] == 0


def test_local_kill_is_detected_and_restarted(supervised_local):
    manager, supervisor, slot = supervised_local
    assert supervisor.wait_ready(timeout=30.0)
    old_replica = slot.replica
    old_replica.kill("chaos")
    _wait(lambda: slot.restarts >= 1 and slot.state is SlotState.READY,
          timeout=60.0, what="automatic restart")
    assert slot.replica is not old_replica          # a fresh engine
    assert slot.replica.id == slot.id               # same fleet identity
    assert manager.pool_size("mixed") == 1
    router = FleetRouter(manager)
    doc = router.route({"prompt": [4, 5], "max_new_tokens": 2}).result()
    assert doc["state"] == "DONE"  # the restarted replica serves


def test_local_crash_loop_quarantines_and_reset_recovers(make_fleet):
    manager = make_fleet(roles=(), config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=2, crash_window_s=60.0, **FAST))
    slot = supervisor.add_local(role="mixed")
    supervisor.start()
    try:
        for _ in range(2):  # kill every incarnation: a persistent crasher
            _wait(lambda: slot.state is SlotState.READY
                  or slot.state is SlotState.QUARANTINED,
                  timeout=60.0, what="slot ready")
            if slot.state is SlotState.QUARANTINED:
                break
            slot.replica.kill("chaos")
            time.sleep(0.1)
        _wait(lambda: slot.state is SlotState.QUARANTINED, timeout=60.0,
              what="quarantine")
        # surfaced, not silently respawned: a QUARANTINED row in stats,
        # absent from every capacity view
        assert manager.pool_size("mixed") == 0
        stats = manager.stats()
        assert stats["quarantined"] == 1
        row = next(r for r in stats["replicas"] if r["id"] == slot.id)
        assert row["state"] == "QUARANTINED"
        restarts_before = slot.restarts
        time.sleep(0.3)
        assert slot.restarts == restarts_before, "quarantined slot respawned"
        # operator reset clears the budget and relaunches
        supervisor.reset(slot.id)
        _wait(lambda: slot.state is SlotState.READY, timeout=60.0,
              what="post-reset relaunch")
        assert manager.pool_size("mixed") == 1
    finally:
        supervisor.stop()


def test_quarantined_replica_is_absent_capacity_for_autoscaler(make_fleet):
    """The ISSUE small-fix: a quarantined replica must read as a hole to
    fill (scale up to replace), not an unhealthy-but-live member to
    oscillate around."""
    from deepspeed_tpu.fleet import AutoscaleConfig, FleetAutoscaler
    manager = make_fleet(roles=("mixed", "mixed"), config=_fleet_config())
    victim = manager.replicas()[0]
    victim.state = ReplicaState.QUARANTINED  # what the supervisor does
    scaler = FleetAutoscaler(manager, AutoscaleConfig(
        min_replicas=2, max_replicas=4, sustain_ticks=3))
    obs = scaler.observe()
    assert obs["replicas"] == 1  # absent, not unhealthy-but-live
    assert obs["queue_per_replica"] != float("inf")
    # below the floor: replaced immediately, no sustain window
    assert scaler.step() == "up"
    assert manager.pool_size("mixed") == 2
    # and the pool is now stable: no oscillating scale-down of the new member
    assert scaler.step() is None


def test_sweep_gauges_reset_when_the_fleet_goes_absent(make_fleet):
    """ISSUE satellite: the probe-sweep gauges (``fleet_kv_pressure``,
    ``fleet_queue_depth``) must RESET when every replica is quarantined or
    removed — a gauge frozen at the last live value reads as healthy
    occupancy on a fleet that no longer exists."""
    from deepspeed_tpu import telemetry
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    manager = make_fleet(roles=("mixed",), config=_fleet_config())
    reg = telemetry.get_registry()
    pressure = reg.gauge("fleet_kv_pressure")
    depth = reg.gauge("fleet_queue_depth")
    victim = manager.replicas()[0]

    blocker = victim.scheduler.submit((np.arange(7) % 64).tolist(),
                                      max_new_tokens=100)

    def _pressured():
        manager.sweep_probes()
        return pressure.value > 0.0

    _wait(_pressured, timeout=60.0, what="nonzero kv pressure under load")
    frozen = pressure.value

    victim.state = ReplicaState.QUARANTINED
    manager.sweep_probes()
    assert pressure.value == 0.0, \
        f"kv_pressure froze at {frozen} with zero live replicas"
    assert depth.value == 0

    victim.state = ReplicaState.UP  # let the blocker finish cleanly
    blocker.result(timeout=300)
    manager.sweep_probes()  # back to live: the gauge tracks reality again


def test_autoscaler_does_not_double_fill_a_restarting_slot(make_fleet):
    """A supervised slot mid-restart (BACKOFF) is capacity in flight, not a
    hole: the below-min replacement must wait for the supervisor, else every
    crash overshoots the pool by one."""
    from deepspeed_tpu.fleet import AutoscaleConfig, FleetAutoscaler
    manager = make_fleet(roles=("mixed",), config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=10, crash_window_s=60.0, **FAST))
    slot = supervisor.add_local(role="mixed")
    scaler = FleetAutoscaler(manager, AutoscaleConfig(
        min_replicas=2, max_replicas=4))
    # simulate the supervisor's crash window: replica removed, slot BACKOFF
    slot.state = SlotState.BACKOFF
    assert manager.pool_size("mixed") == 1
    assert manager.pending_replicas("mixed") == 1
    assert scaler.step() is None, "restart in flight — not a hole to fill"
    # a QUARANTINED slot IS a durable hole
    slot.state = SlotState.QUARANTINED
    assert scaler.step() == "up"
    assert manager.pool_size("mixed") == 2


# ---------------------------------------------------------------------------
# process-backed slots (real subprocesses, stdlib stub server)
# ---------------------------------------------------------------------------
def test_process_spawn_ready_kill_restart():
    manager = ReplicaManager(config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=3, crash_window_s=60.0, **FAST))
    slot = supervisor.add_process(_stub_cmd("serve"), role="mixed")
    supervisor.start()
    try:
        assert supervisor.wait_ready(timeout=30.0)
        assert manager.pool_size("mixed") == 1
        pid = slot.replica.proc.pid
        probe = slot.replica.probe(max_age_s=0.0)
        assert probe["healthy"]
        os.kill(pid, signal.SIGKILL)  # a real crash
        _wait(lambda: slot.restarts >= 1 and slot.state is SlotState.READY,
              timeout=30.0, what="process restart")
        assert slot.replica.proc.pid != pid
        assert manager.pool_size("mixed") == 1
        row = manager.stats()["supervisor"]["slots"][0]
        assert row["restarts"] == 1 and row["kind"] == "process"
    finally:
        supervisor.stop()
    assert slot.replica is None or slot.replica.proc.poll() is not None, \
        "supervisor.stop() must reap its processes"


def test_process_never_ready_exhausts_budget_and_quarantines():
    manager = ReplicaManager(config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=2, crash_window_s=60.0, poll_interval_s=0.05,
        ready_timeout_s=0.5, restart_backoff_base_s=0.05,
        restart_backoff_cap_s=0.1, restart_jitter_frac=0.0))
    slot = supervisor.add_process(_stub_cmd("never-ready"), role="mixed")
    supervisor.start()
    try:
        _wait(lambda: slot.state is SlotState.QUARANTINED, timeout=30.0,
              what="quarantine of a never-ready replica")
        assert "not ready" in slot.last_error
        # never registered as dispatchable capacity — only the placeholder row
        assert manager.pool_size("mixed") == 0
        row = next(r for r in manager.stats()["replicas"] if r["id"] == slot.id)
        assert row["state"] == "QUARANTINED"
    finally:
        supervisor.stop()


def test_process_exit_before_announce_is_a_launch_crash():
    manager = ReplicaManager(config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=1, crash_window_s=60.0, **FAST))
    slot = supervisor.add_process(_stub_cmd("exit"), role="mixed")
    supervisor.start()
    try:
        _wait(lambda: slot.state is SlotState.QUARANTINED, timeout=30.0,
              what="instant-exit quarantine")
        assert "exited" in slot.last_error
    finally:
        supervisor.stop()


def test_process_hang_is_detected_and_restarted():
    """A wedged-but-alive replica (answers nothing, process up) is killed
    after probe_hang_failures consecutive failed probes and restarted."""
    manager = ReplicaManager(config=_fleet_config())
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=5, crash_window_s=2.0, probe_hang_failures=2, **FAST))
    slot = supervisor.add_process(_stub_cmd("hang-after-ready", ttl_s=0.3),
                                  role="mixed")
    supervisor.start()
    try:
        assert supervisor.wait_ready(timeout=30.0)
        pid = slot.replica.proc.pid
        _wait(lambda: slot.restarts >= 1, timeout=30.0, what="hang restart")
        assert "hung" in (slot.last_error or "")
        assert slot.replica is None or slot.replica.proc.pid != pid
    finally:
        supervisor.stop()


@pytest.mark.slow
def test_dstpu_replica_process_end_to_end(tmp_path):
    """The real bin/dstpu_replica entrypoint under supervision: readiness-
    gated registration, a routed request, graceful teardown. Slow: each spawn
    imports jax in a subprocess."""
    pytest.importorskip("jax")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    cmd = [sys.executable, os.path.join(repo, "bin", "dstpu_replica"),
           "--port-file", "{port_file}", "--vocab-size", "64",
           "--num-blocks", "32", "--max-context", "64"]
    # a real replica's first request compiles XLA: give the read budget the
    # compile time (the 1s test default is for the stub server)
    manager = ReplicaManager(config=_fleet_config(read_timeout_s=180.0))
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        max_crashes=2, crash_window_s=120.0, poll_interval_s=0.1,
        ready_timeout_s=180.0, restart_backoff_base_s=0.1,
        restart_backoff_cap_s=0.5, restart_jitter_frac=0.0))
    slot = supervisor.add_process(cmd, role="mixed",
                                  env={"JAX_PLATFORMS": "cpu"})
    supervisor.start()
    try:
        assert supervisor.wait_ready(timeout=240.0), slot.describe()
        router = FleetRouter(manager)
        prompt = (np.arange(5) % 64).tolist()
        doc = router.route({"prompt": prompt, "max_new_tokens": 3}).result()
        assert doc["state"] == "DONE" and doc["n_tokens"] == 3
    finally:
        supervisor.stop()
