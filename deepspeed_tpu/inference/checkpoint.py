"""HF checkpoint → training-pytree loader.

Reference: ``deepspeed/inference/v2/checkpoint/huggingface_engine.py``
(HuggingFaceCheckpointEngine — downloads + iterates params) and v1's
``load_model_with_checkpoint`` (``inference/engine.py:331``). The TPU framework's
model params are functional pytrees in the training layout
(:mod:`deepspeed_tpu.models.llama`), so checkpoint loading is a pure
name-mapping step: HF tensor names → pytree paths, with kernels transposed
(HF Linear stores ``[out, in]``; flax Dense kernels are ``[in, out]``).
"""

import json
import os
from typing import Dict, Tuple

import numpy as np


def _iterate_hf_tensors(path: str):
    """Yield (name, numpy array) from all safetensors / torch .bin shards."""
    st_files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if st_files:
        from safetensors.numpy import load_file
        for f in st_files:
            for name, arr in load_file(os.path.join(path, f)).items():
                yield name, arr
        return
    bin_files = sorted(f for f in os.listdir(path) if f.endswith(".bin"))
    if not bin_files:
        raise FileNotFoundError(f"no .safetensors or .bin weights under {path}")
    import torch
    for f in bin_files:
        sd = torch.load(os.path.join(path, f), map_location="cpu", weights_only=True)
        for name, t in sd.items():
            yield name, t.float().numpy()


def _model_config_from_hf(cfg: dict):
    import jax.numpy as jnp
    arch = (cfg.get("architectures") or [""])[0].lower()
    model_type = cfg.get("model_type", "").lower()
    dtype = {"float32": jnp.float32, "float16": jnp.float16,
             "bfloat16": jnp.bfloat16}.get(cfg.get("torch_dtype", "bfloat16"), jnp.bfloat16)
    common = dict(dtype=dtype,
                  vocab_size=cfg["vocab_size"],
                  hidden_size=cfg["hidden_size"],
                  intermediate_size=cfg["intermediate_size"],
                  num_hidden_layers=cfg["num_hidden_layers"],
                  num_attention_heads=cfg["num_attention_heads"],
                  num_key_value_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
                  max_position_embeddings=cfg.get("max_position_embeddings", 4096),
                  rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
                  rope_theta=cfg.get("rope_theta", 1e4))
    if "mixtral" in model_type or "mixtral" in arch:
        from deepspeed_tpu.models.mixtral import MixtralConfig
        return MixtralConfig(num_local_experts=cfg.get("num_local_experts", 8),
                             num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
                             **common)
    if model_type == "mistral" or "mistral" in arch:
        from deepspeed_tpu.models.llama import LlamaConfig
        return LlamaConfig(model_type="mistral",
                           sliding_window=cfg.get("sliding_window") or 0, **common)
    if model_type == "qwen2" or "qwen2" in arch:
        from deepspeed_tpu.models.llama import LlamaConfig
        return LlamaConfig(model_type="qwen2", attention_bias=True, **common)
    if model_type == "llama" or "llama" in arch:
        from deepspeed_tpu.models.llama import LlamaConfig
        return LlamaConfig(**common)
    raise ValueError(f"unsupported HF model_type: {model_type!r}")


def _set_path(tree: Dict, path: Tuple[str, ...], value) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _map_hf_name(name: str, n_experts: int):
    """HF tensor name → (pytree path, needs_transpose). Returns None to skip."""
    name = name.removeprefix("model.")
    if name == "embed_tokens.weight":
        return ("model", "embed_tokens", "embedding"), False
    if name == "norm.weight":
        return ("model", "norm", "weight"), False
    if name == "lm_head.weight":
        # lm_head lives INSIDE the "model" subtree in the training layout
        # (models/llama.py nests it with everything else _root/unembed read).
        return ("model", "lm_head", "kernel"), True
    if not name.startswith("layers."):
        return None
    parts = name.split(".")
    li = parts[1]
    layer = ("model", f"layers_{li}")
    rest = parts[2:]
    if rest[0] in ("input_layernorm", "post_attention_layernorm"):
        return layer + (rest[0], "weight"), False
    if rest[0] == "self_attn":
        if rest[2] == "bias":  # qwen2 q/k/v biases
            return layer + ("self_attn", rest[1], "bias"), False
        return layer + ("self_attn", rest[1], "kernel"), True
    if rest[0] == "mlp":
        return layer + ("mlp", rest[1], "kernel"), True
    if rest[0] == "block_sparse_moe":
        if rest[1] == "gate":
            return layer + ("block_sparse_moe", "gate"), True
        # experts.<e>.w{1,2,3}.weight -> stacked banks, handled by caller
        return ("__expert__", f"layers_{li}", rest[2], rest[3]), True
    return None


def load_hf_checkpoint(path: str):
    """Load an HF llama/mistral/mixtral checkpoint directory into
    ``(params pytree, model config)`` in the training layout."""
    import jax.numpy as jnp

    with open(os.path.join(path, "config.json")) as f:
        cfg = _model_config_from_hf(json.load(f))
    n_experts = getattr(cfg, "num_local_experts", 0)
    # store tensors in the checkpoint's own dtype (a f16 7B model must occupy
    # 14GB, not 28GB); jnp handles ml_dtypes bfloat16 numpy arrays natively
    target_dtype = jnp.dtype(cfg.dtype)

    params: Dict = {}
    experts: Dict = {}  # (layer, w1/w2/w3) -> {expert_idx: array}
    for name, arr in _iterate_hf_tensors(path):
        mapped = _map_hf_name(name, n_experts)
        if mapped is None:
            continue
        pth, transpose = mapped
        if arr.dtype != target_dtype:
            arr = arr.astype(target_dtype)
        if transpose and arr.ndim == 2:
            arr = arr.T
        if pth[0] == "__expert__":
            _, layer, eidx, wname = pth
            experts.setdefault((layer, wname), {})[int(eidx)] = arr
        else:
            _set_path(params, pth, jnp.asarray(arr))

    # Tied embeddings (tie_word_embeddings=true ships no lm_head.weight): the
    # unembed projection is the embedding matrix transposed ([V, M] -> [M, V]).
    root = params.setdefault("model", {})
    if "lm_head" not in root and "embed_tokens" in root:
        root["lm_head"] = {"kernel": root["embed_tokens"]["embedding"].T}

    # Stack per-expert w1 (gate->wi half), w3 (up->wi half), w2 (down->wo) into
    # the training ExpertFFN bank layout: wi [E, M, 2F] (gate|up), wo [E, F, M].
    for layer in sorted({l for (l, _) in experts}):
        w1 = np.stack([experts[(layer, "w1")][e] for e in range(n_experts)])
        w3 = np.stack([experts[(layer, "w3")][e] for e in range(n_experts)])
        w2 = np.stack([experts[(layer, "w2")][e] for e in range(n_experts)])
        moe = params["model"].setdefault(layer, {}).setdefault("block_sparse_moe", {})
        moe.setdefault("ExpertFFN_0", {})["wi"] = jnp.asarray(np.concatenate([w1, w3], axis=-1))
        moe["ExpertFFN_0"]["wo"] = jnp.asarray(w2)

    return params, cfg
