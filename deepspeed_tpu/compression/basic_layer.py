"""Compression primitives.

Reference: ``deepspeed/compression/basic_layer.py`` (LinearLayer_Compress with
weight quantization, row/head/sparse pruning; QuantAct) — torch module
subclasses holding masks. TPU formulation: pure functions over weight arrays;
compression is a parameter-tree transform, not module surgery.
"""

import numpy as np

import jax
import jax.numpy as jnp


def fake_quantize(w, bits: int = 8, symmetric: bool = True, per_channel: bool = True,
                  channel_axis: int = -1):
    """Quantize-dequantize (the reference's training-time fake quant,
    ``deepspeed/compression/utils.py`` Quantizer): keeps dtype, snaps values to
    the 2^bits grid so downstream training sees quantization error."""
    w = jnp.asarray(w)
    axes = tuple(i for i in range(w.ndim) if i != (channel_axis % w.ndim)) \
        if per_channel and w.ndim > 1 else None
    if bits == 1:
        # XTC binarization (reference compression/utils.py BinaryQuantizer):
        # sign(w) scaled by the mean magnitude
        scale = jnp.mean(jnp.abs(w), axis=axes, keepdims=True)
        # sign(), not where(>=0): exact zeros (pruned weights) must STAY zero
        return (jnp.sign(w) * scale).astype(w.dtype)
    if bits == 2:
        # XTC ternarization (reference TernaryQuantizer): threshold at
        # 0.7·mean|w|, scale by the mean magnitude of the surviving entries
        mag = jnp.abs(w)
        thresh = 0.7 * jnp.mean(mag, axis=axes, keepdims=True)
        mask = mag > thresh
        denom = jnp.maximum(jnp.sum(mask, axis=axes, keepdims=True), 1)
        scale = jnp.sum(jnp.where(mask, mag, 0.0), axis=axes, keepdims=True) / denom
        return (jnp.sign(w) * scale * mask).astype(w.dtype)
    qmax = 2.0**(bits - 1) - 1 if symmetric else 2.0**bits - 1
    if symmetric:
        scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-12)
        return jnp.round(w / scale).clip(-qmax - 1, qmax) * scale
    lo = jnp.min(w, axis=axes, keepdims=True)
    hi = jnp.max(w, axis=axes, keepdims=True)
    scale = jnp.maximum((hi - lo) / qmax, 1e-12)
    return jnp.round((w - lo) / scale).clip(0, qmax) * scale + lo


def row_prune_mask(w, ratio: float, axis: int = 0):
    """L1-structured row pruning mask (reference LinearLayer_Compress
    row-pruning): zero the ``ratio`` fraction of rows with smallest L1 norm."""
    w = jnp.asarray(w)
    other = tuple(i for i in range(w.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(w), axis=other)
    k = int(np.floor(ratio * norms.shape[0]))
    if k == 0:
        return jnp.ones_like(norms, bool)
    thresh = jnp.sort(norms)[k - 1]
    return norms > thresh


def head_prune_mask(w, ratio: float, num_heads: int):
    """Attention-head pruning mask over an [in, H*D] projection (reference
    head-pruning): returns [H] bool keep-mask by per-head L1 norm."""
    w = jnp.asarray(w)
    hd = w.shape[-1] // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(-1, num_heads, hd)), axis=(0, 2))
    k = int(np.floor(ratio * num_heads))
    if k == 0:
        return jnp.ones((num_heads, ), bool)
    thresh = jnp.sort(per_head)[k - 1]
    return per_head > thresh


def apply_head_mask(w, keep_mask, num_heads: int):
    hd = w.shape[-1] // num_heads
    m = jnp.repeat(jnp.asarray(keep_mask), hd)
    return w * m[None, :].astype(w.dtype)
