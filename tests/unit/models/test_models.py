"""Model family tests: tiny Llama/GPT-2/Mixtral train through the engine with real
parallel shardings on the virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import gpt2, llama, mixtral
from deepspeed_tpu.utils import groups


def _lm_batches(n, batch, seq, vocab, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int64)
        out.append((ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)))
    return out


def _cfg(stage=2, micro=2):
    return {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
    }


def test_llama_tiny_trains():
    groups.initialize_mesh(force=True)
    cfg = llama.LlamaConfig.tiny()
    model, params = llama.init_params(cfg, batch_size=8, seq_len=16)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=_cfg(stage=3))
    losses = [float(engine.train_batch(batch=b)) for b in _lm_batches(8, 16, 16, cfg.vocab_size)]
    assert losses[-1] < losses[0]


def test_llama_tensor_parallel_specs():
    groups.initialize_mesh(model_parallel_size=2, force=True)
    cfg = llama.LlamaConfig.tiny()
    model, params = llama.init_params(cfg, batch_size=4, seq_len=16)
    specs = llama.llama_param_specs(params)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=_cfg(stage=1), param_specs=specs)
    q = engine.params["model"]["layers_0"]["self_attn"]["q_proj"]["kernel"]
    assert not q.sharding.is_fully_replicated
    loss = engine.train_batch(batch=_lm_batches(1, 8, 16, cfg.vocab_size)[0])
    assert np.isfinite(float(loss))


def test_llama_ulysses_sequence_parallel():
    groups.initialize_mesh(sequence_parallel_size=2, force=True)
    cfg = llama.LlamaConfig.tiny(sequence_parallel=True)
    model, params = llama.init_params(cfg, batch_size=4, seq_len=16)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=_cfg(stage=2))
    losses = [float(engine.train_batch(batch=b)) for b in _lm_batches(4, 8, 16, cfg.vocab_size)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_llama_ulysses_matches_dense():
    """Sequence-parallel run computes the same loss as the plain run."""
    cfg_sp = llama.LlamaConfig.tiny(sequence_parallel=True)
    cfg_dense = llama.LlamaConfig.tiny()
    model_sp = llama.LlamaForCausalLM(cfg_sp)
    model_dense = llama.LlamaForCausalLM(cfg_dense)
    _, params = llama.init_params(cfg_dense, batch_size=2, seq_len=16)
    b = _lm_batches(1, 2, 16, cfg_dense.vocab_size)[0]

    groups.initialize_mesh(sequence_parallel_size=4, force=True)
    loss_sp = jax.jit(lambda p: model_sp.apply({"params": p}, b))(params)
    loss_dense = jax.jit(lambda p: model_dense.apply({"params": p}, b))(params)
    np.testing.assert_allclose(float(loss_sp), float(loss_dense), rtol=2e-2)


def test_gpt2_tiny_trains():
    groups.initialize_mesh(force=True)
    cfg = gpt2.GPT2Config.tiny()
    model, params = gpt2.init_params(cfg, batch_size=8, seq_len=16)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=_cfg(stage=2))
    losses = [float(engine.train_batch(batch=b)) for b in _lm_batches(8, 16, 16, cfg.vocab_size)]
    assert losses[-1] < losses[0]


def test_mixtral_tiny_trains_expert_parallel():
    groups.initialize_mesh(expert_parallel_size=4, force=True)
    cfg = mixtral.MixtralConfig.tiny()
    model, params = mixtral.init_params(cfg, batch_size=4, seq_len=16)
    specs = mixtral.mixtral_param_specs(params)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=_cfg(stage=2), param_specs=specs)
    # expert banks sharded over the expert axis
    wi = engine.params["layers_0"]["block_sparse_moe"]["ExpertFFN_0"]["wi"]
    assert not wi.sharding.is_fully_replicated
    losses = [float(engine.train_batch(batch=b)) for b in _lm_batches(6, 8, 16, cfg.vocab_size)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
