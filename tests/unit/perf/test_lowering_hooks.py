"""The engines' official lowering hooks (the perf gates' only entry points —
no reaching into compile-watch-wrapped jit caches).

Engine builds are consolidated (one training engine, one inference engine)
— tier-1 runs on a small CPU box and every deepspeed_tpu.initialize pays an
XLA compile."""

import numpy as np
import pytest

from deepspeed_tpu.perf.programs import (build_train_engine, build_v2_engine,
                                         train_batch_example)


# ------------------------------------------------------------ training side --
def test_train_engine_lowering_hooks_end_to_end():
    """One engine build covers: raw-jit exposure under an ACTIVE compile
    watch (the wrapped cache entry cannot lower; the hook's raw one can),
    lowering producing real StableHLO, and engine state staying untouched."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry.config import TelemetryConfig

    telemetry.shutdown()
    telemetry.state.registry = None
    try:
        telemetry.configure(TelemetryConfig(enabled=True))
        engine, cfg = build_train_engine()
        rng_before = engine._rng
        steps_before = engine.global_steps

        lowered = engine.lower_train_batch(batch=train_batch_example(cfg))
        assert lowered.as_text().startswith("module")

        # state must not advance: lowering is analysis, not a step
        assert engine.global_steps == steps_before
        assert (np.asarray(engine._rng) == np.asarray(rng_before)).all(), \
            "lowering must not consume training rng"

        wrapped = engine._compiled["train_batch"]
        raw = engine.lowerable_callables()["train_batch"]
        assert not hasattr(wrapped, "lower")  # the compile-watch wrapper
        assert hasattr(raw, "lower"), \
            "lowerable_callables must return raw jax.jit callables"
    finally:
        telemetry.shutdown()
        telemetry.state.registry = None


# ----------------------------------------------------------- inference side --
@pytest.fixture(scope="module")
def v2():
    from deepspeed_tpu.utils import groups
    engine, cfg = build_v2_engine()
    rng = np.random.default_rng(0)
    engine.put([0], [rng.integers(0, cfg.vocab_size, 24)])
    engine.decode_loop([0], [np.asarray([1], np.int32)], 4)
    yield engine, cfg
    groups.destroy_mesh()


def test_engine_v2_lowerable_callables_track_buckets(v2):
    engine, _ = v2
    fns = engine.lowerable_callables()
    assert len(fns["forward"]) == 1 and len(fns["decode_loop"]) == 1
    (bucket, fwd), = fns["forward"].items()
    assert len(bucket) == 3 and hasattr(fwd, "lower")
    (dkey, dec), = fns["decode_loop"].items()
    assert dkey[1] == 4 and dkey[2] is False and hasattr(dec, "lower")


def test_lower_forward_default_and_explicit_bucket(v2):
    engine, _ = v2
    small = engine.lower_forward()
    big = engine.lower_forward((64, 8, 8))
    assert small.as_text().startswith("module")
    # bigger token bucket => more embed rows => different (larger) program
    assert len(big.as_text()) != len(small.as_text())


def test_lowering_does_not_touch_compile_watch_bucket_telemetry(v2):
    """Analysis-only lowering must not feed the bucket-churn recompile
    indicator — only executed batches do (via RaggedBatchWrapper.finalize)."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry.config import TelemetryConfig

    engine, _ = v2
    telemetry.shutdown()
    telemetry.state.registry = None
    try:
        telemetry.configure(TelemetryConfig(enabled=True))
        watch = telemetry.compile_watch.get()
        assert watch is not None
        before = watch._bucket_switches.value
        buckets_before = dict(watch._recent_buckets)
        engine.lower_forward()
        engine.lower_forward((64, 8, 8))
        engine.lower_decode_loop(2)
        assert watch._bucket_switches.value == before
        assert dict(watch._recent_buckets) == buckets_before
    finally:
        telemetry.shutdown()
        telemetry.state.registry = None


def test_lower_decode_loop_matches_executed_program(v2):
    """The lowered decode program and the one decode_loop actually runs must
    be the same jit (same cache key, identical HLO)."""
    import jax
    import jax.numpy as jnp

    engine, _ = v2
    (dkey, raw), = engine.lowerable_callables()["decode_loop"].items()
    lowered = engine.lower_decode_loop(4, bucket=dkey[0])
    model = engine.model
    dev = model._synthetic_batch(dkey[0])
    again = raw.lower(model._params, model.state_manager.kv_cache.cache, dev,
                      jnp.float32(0.0), jax.random.PRNGKey(0))
    assert lowered.as_text() == again.as_text()
