from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)
from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
