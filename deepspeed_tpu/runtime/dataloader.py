"""Data loading.

Reference: ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader, RepeatingLoader).
Under single-controller SPMD the loader yields *global* batches of host numpy arrays;
``engine.shard_batch`` places them over the data/seq mesh axes (the role the
per-rank DistributedSampler plays in the reference).
"""

import numpy as np


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, shuffle=False, seed=0, collate_fn=None, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last
        self._epoch = 0

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self._epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        for start in range(0, n - (self.batch_size - 1 if self.drop_last else 0), self.batch_size):
            sel = idx[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in sel])


class RepeatingLoader:
    """Reference dataloader.py RepeatingLoader: wrap an iterator to restart it."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "_epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])
