"""Per-replica circuit breaker + the shared bounded-jitter backoff policy.

The router's original failover was raw per-request exclusion: a dead replica
was re-tried by every request until the probe TTL noticed, and nothing
remembered failures across requests. The breaker is that memory — the
standard three-state machine:

- **CLOSED** — dispatch normally; ``failure_threshold`` *consecutive*
  failures (transport errors, 5xx admission refusals, probe exceptions —
  never 429 backpressure, which is load, not breakage) trip it OPEN.
- **OPEN** — the replica is skipped outright (no dispatch, no probe, no
  handler thread pinned on a black-holed socket) for a cooldown that doubles
  per consecutive OPEN episode up to a cap, then the breaker half-opens.
- **HALF_OPEN** — up to ``half_open_max_probes`` concurrent trial dispatches
  are let through (:meth:`CircuitBreaker.try_acquire`); one success closes
  the breaker and resets the episode scaling, one failure re-opens it.

``backoff_delay`` is the one backoff formula the fleet shares: router
failover retries, failed-probe re-probe spacing, and supervisor restart
scheduling all use it — exponential growth, a hard cap, and *bounded* jitter
(``d * (1 ± jitter_frac)``) so synchronized clients de-correlate without the
unbounded tail of full-jitter schemes.
"""

import threading
import time
from enum import Enum
from typing import Callable, Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import logger


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  jitter_frac: float = 0.0, u: Optional[float] = None,
                  multiplier: float = 2.0) -> float:
    """Delay before retry ``attempt`` (0-based): ``base * multiplier**attempt``
    capped at ``cap_s``, jittered into ``[d*(1-j), d*(1+j)]``. ``u`` is the
    jitter draw in [0, 1) — deterministic callers (the supervisor, the fault
    harness) pass their own; None means no jitter."""
    d = min(cap_s, base_s * (multiplier ** max(0, attempt)))
    if jitter_frac > 0.0 and u is not None:
        d *= 1.0 - jitter_frac + 2.0 * jitter_frac * u
    return max(0.0, d)


class BreakerState(Enum):
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class BreakerConfig(DeepSpeedConfigModel):
    """Per-replica circuit-breaker knobs (``FleetConfig.breaker``)."""

    enabled: bool = True
    """False = ``allow()`` always True (the pre-breaker raw-exclusion
    behavior); the object still exists so call sites stay branch-free."""

    failure_threshold: int = Field(3, ge=1)
    """Consecutive breaker-grade failures (transport/5xx/probe-error — not
    429) that trip CLOSED → OPEN."""

    open_cooldown_s: float = Field(2.0, gt=0)
    """OPEN dwell before the first HALF_OPEN trial window."""

    cooldown_multiplier: float = Field(2.0, ge=1)
    """Cooldown growth per consecutive OPEN episode (a replica that keeps
    failing its trial waits longer each time)."""

    max_cooldown_s: float = Field(60.0, gt=0)
    """Cooldown growth cap."""

    half_open_max_probes: int = Field(1, ge=1)
    """Concurrent trial dispatches allowed while HALF_OPEN."""


class CircuitBreaker:
    """One replica's failure memory. Thread-safe; the OPEN→HALF_OPEN
    transition is lazy (evaluated on the next ``allow``/``try_acquire``), so
    there is no timer thread per replica. ``on_transition(breaker, old, new)``
    observers fire outside the breaker lock."""

    def __init__(self, config: Optional[BreakerConfig] = None,
                 on_transition: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._config = config or BreakerConfig()
        self._on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._failures = 0        # consecutive, CLOSED only
        self._episodes = 0        # consecutive OPEN episodes (cooldown scaling)
        self._opened_at = 0.0
        self._trials = 0          # in-flight HALF_OPEN trial dispatches
        self._opens = 0           # lifetime transitions into OPEN
        self._closes = 0          # lifetime HALF_OPEN -> CLOSED recoveries

    # ------------------------------------------------------------------ state --
    @property
    def state(self) -> BreakerState:
        with self._lock:
            transitions = self._maybe_half_open()
            state = self._state
        self._notify(transitions)
        return state

    def _cooldown_s(self) -> float:
        cfg = self._config
        return backoff_delay(self._episodes - 1, cfg.open_cooldown_s,
                             cfg.max_cooldown_s,
                             multiplier=cfg.cooldown_multiplier)

    def _maybe_half_open(self) -> list:
        # caller holds the lock; returns transitions for _notify
        if (self._state is BreakerState.OPEN
                and self._clock() - self._opened_at >= self._cooldown_s()):
            self._trials = 0
            return [self._transition(BreakerState.HALF_OPEN)]
        return []

    def _transition(self, new: BreakerState):
        # caller holds the lock; returns the (old, new) pair for _notify
        old, self._state = self._state, new
        return (old, new)

    def _notify(self, transitions) -> None:
        if not self._on_transition:
            return
        for old, new in transitions:
            if old is new:
                continue
            try:
                self._on_transition(self, old, new)
            except Exception:  # pragma: no cover - an observer must never
                # take down the dispatch path it observes
                logger.exception("circuit breaker: on_transition raised")

    # ---------------------------------------------------------- dispatch gate --
    def allow(self) -> bool:
        """Non-consuming candidacy check: may this replica be dispatched to
        right now? (OPEN lazily half-opens when its cooldown has passed.)"""
        if not self._config.enabled:
            return True
        with self._lock:
            transitions = self._maybe_half_open()
            if self._state is BreakerState.CLOSED:
                out = True
            elif self._state is BreakerState.HALF_OPEN:
                out = self._trials < self._config.half_open_max_probes
            else:
                out = False
        self._notify(transitions)
        return out

    def try_acquire(self) -> bool:
        """Consume a dispatch slot: always True when CLOSED (or disabled);
        while HALF_OPEN, claims one of the bounded trial slots (the caller
        MUST then report ``record_success``/``record_failure`` — or
        ``release`` when no verdict was reached — so slots cannot leak)."""
        if not self._config.enabled:
            return True
        with self._lock:
            transitions = self._maybe_half_open()
            if self._state is BreakerState.CLOSED:
                out = True
            elif (self._state is BreakerState.HALF_OPEN
                  and self._trials < self._config.half_open_max_probes):
                self._trials += 1
                out = True
            else:
                out = False
        self._notify(transitions)
        return out

    def release(self) -> None:
        """A trial ended without a breaker-grade verdict (e.g. 429
        backpressure): free the slot, change nothing else."""
        with self._lock:
            self._trials = max(0, self._trials - 1)

    # --------------------------------------------------------------- outcomes --
    def record_success(self, trial: bool = True) -> None:
        """A dispatch was admitted (or a HALF_OPEN probe came back healthy).
        ``trial=False`` marks a probe-path signal that never held a slot."""
        transitions = []
        with self._lock:
            self._failures = 0
            if self._state is BreakerState.HALF_OPEN:
                if trial:
                    self._trials = max(0, self._trials - 1)
                self._episodes = 0
                self._closes += 1
                transitions.append(self._transition(BreakerState.CLOSED))
        self._notify(transitions)

    def record_probe_success(self) -> None:
        """A health probe answered healthy. Closes a HALF_OPEN breaker (the
        replica demonstrably recovered) but does NOT reset CLOSED-state
        failure counting — an upstream can answer probes while refusing every
        dispatch, and interleaved probe successes must not keep such a
        replica's breaker from ever tripping."""
        transitions = []
        with self._lock:
            transitions.extend(self._maybe_half_open())
            if self._state is BreakerState.HALF_OPEN:
                self._episodes = 0
                self._closes += 1
                transitions.append(self._transition(BreakerState.CLOSED))
        self._notify(transitions)

    def record_failure(self, trial: bool = True) -> None:
        """A breaker-grade failure (transport error, 5xx refusal, leg death,
        probe exception). NOT for 429 backpressure — use ``release``."""
        with self._lock:
            transitions = self._maybe_half_open()
            if self._state is BreakerState.HALF_OPEN:
                if trial:
                    self._trials = max(0, self._trials - 1)
                transitions.append(self._open())
            elif self._state is BreakerState.CLOSED:
                self._failures += 1
                if self._failures >= self._config.failure_threshold:
                    transitions.append(self._open())
            # already OPEN: nothing to count — the episode is one failure
        self._notify(transitions)

    def _open(self):
        # caller holds the lock
        self._failures = 0
        self._episodes += 1
        self._opened_at = self._clock()
        self._opens += 1
        return self._transition(BreakerState.OPEN)

    # ------------------------------------------------------------------ admin --
    def describe(self) -> dict:
        with self._lock:
            doc = {"state": self._state.name,
                   "consecutive_failures": self._failures,
                   "open_episodes": self._episodes,
                   "opens": self._opens, "closes": self._closes}
            if self._state is BreakerState.OPEN:
                doc["half_open_in_s"] = round(
                    max(0.0, self._cooldown_s() - (self._clock() - self._opened_at)), 3)
            return doc
