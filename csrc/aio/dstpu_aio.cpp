// dstpu async file I/O: thread-pool pread/pwrite engine behind the NVMe swap
// tier.
//
// Role parity: /root/reference/csrc/aio/ (py_ds_aio.cpp, deepspeed_aio_thread.cpp,
// deepspeed_aio_common.cpp — 2,958 LoC of libaio plumbing). The reference drives
// Linux libaio against O_DIRECT files with a pthread pool; swap tensors are
// torch CPU tensors. Here the consumers are pinned-host numpy/jax buffers and
// the engine is a std::thread pool issuing positional pread/pwrite — kernel
// page cache + queue depth give the overlap the reference gets from
// io_submit/io_getevents, with no libaio dependency (not in this image).
//
// C ABI only (loaded via ctypes — no pybind11 in the image). All entry points
// are thread-safe. Errors return negative errno.

#include <cerrno>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <future>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
    bool is_write;
    std::string path;
    void* buf;
    long nbytes;
    long offset;
    std::promise<long> done;
};

long do_io(Request& r) {
    int flags = r.is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(r.path.c_str(), flags, 0644);
    if (fd < 0) return -static_cast<long>(errno);
    long total = 0;
    char* p = static_cast<char*>(r.buf);
    while (total < r.nbytes) {
        ssize_t n = r.is_write ? ::pwrite(fd, p + total, r.nbytes - total, r.offset + total)
                               : ::pread(fd, p + total, r.nbytes - total, r.offset + total);
        if (n < 0) {
            if (errno == EINTR) continue;
            long e = -static_cast<long>(errno);
            ::close(fd);
            return e;
        }
        if (n == 0) break;  // short read (EOF)
        total += n;
    }
    int rc = 0;
    if (r.is_write) rc = ::fsync(fd);
    ::close(fd);
    if (rc != 0) return -static_cast<long>(errno);
    return total;
}

class AioHandle {
public:
    AioHandle(int thread_count, int queue_depth)
        : queue_depth_(queue_depth > 0 ? queue_depth : 64), stop_(false), next_id_(1) {
        int n = thread_count > 0 ? thread_count : 1;
        for (int i = 0; i < n; ++i)
            workers_.emplace_back([this] { worker_loop(); });
    }

    ~AioHandle() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    long submit(bool is_write, const char* path, void* buf, long nbytes, long offset) {
        auto* req = new Request{is_write, path, buf, nbytes, offset, {}};
        std::future<long> fut = req->done.get_future();
        long id;
        {
            std::unique_lock<std::mutex> lk(mu_);
            // bound the queue so a runaway producer can't hold every buffer live
            space_.wait(lk, [this] { return (long)queue_.size() < queue_depth_ || stop_; });
            if (stop_) {
                delete req;
                return -ECANCELED;
            }
            id = next_id_++;
            futures_.emplace(id, std::move(fut));
            queue_.push_back(req);
        }
        cv_.notify_one();
        return id;
    }

    long wait(long id) {
        std::future<long> fut;
        {
            std::unique_lock<std::mutex> lk(mu_);
            auto it = futures_.find(id);
            if (it == futures_.end()) return -EINVAL;
            fut = std::move(it->second);
            futures_.erase(it);
        }
        return fut.get();
    }

    long wait_all() {
        std::unordered_map<long, std::future<long>> pending;
        {
            std::unique_lock<std::mutex> lk(mu_);
            pending.swap(futures_);
        }
        long rc = 0;
        for (auto& kv : pending) {
            long r = kv.second.get();
            if (r < 0) rc = r;
        }
        return rc;
    }

private:
    void worker_loop() {
        for (;;) {
            Request* req;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                req = queue_.front();
                queue_.pop_front();
            }
            space_.notify_one();
            req->done.set_value(do_io(*req));
            delete req;
        }
    }

    long queue_depth_;
    bool stop_;
    long next_id_;
    std::deque<Request*> queue_;
    std::unordered_map<long, std::future<long>> futures_;
    std::vector<std::thread> workers_;
    std::mutex mu_;
    std::condition_variable cv_, space_;
};

}  // namespace

extern "C" {

void* dstpu_aio_new(int thread_count, int queue_depth) {
    return new AioHandle(thread_count, queue_depth);
}

void dstpu_aio_free(void* h) { delete static_cast<AioHandle*>(h); }

long dstpu_aio_submit_read(void* h, const char* path, void* buf, long nbytes, long offset) {
    return static_cast<AioHandle*>(h)->submit(false, path, buf, nbytes, offset);
}

long dstpu_aio_submit_write(void* h, const char* path, void* buf, long nbytes, long offset) {
    return static_cast<AioHandle*>(h)->submit(true, path, buf, nbytes, offset);
}

long dstpu_aio_wait(void* h, long id) { return static_cast<AioHandle*>(h)->wait(id); }

long dstpu_aio_wait_all(void* h) { return static_cast<AioHandle*>(h)->wait_all(); }

// synchronous one-shots (reference deepspeed_py_aio.cpp aio_read/aio_write)
long dstpu_aio_pread(const char* path, void* buf, long nbytes, long offset) {
    Request r{false, path, buf, nbytes, offset, {}};
    return do_io(r);
}

long dstpu_aio_pwrite(const char* path, void* buf, long nbytes, long offset) {
    Request r{true, path, const_cast<void*>(buf), nbytes, offset, {}};
    return do_io(r);
}

int dstpu_aio_version() { return 1; }

}  // extern "C"
