"""Inference engine (v1-equivalent).

Reference: ``deepspeed/inference/engine.py:39`` (InferenceEngine: TP group creation,
injection policy, CUDA-graph capture, forward/generate). The TPU formulation:

- TP group = the ``model`` mesh axis; parameters are placed by ``param_specs``
  (AutoTP's role of picking row/col sharding) and XLA inserts the per-layer
  collectives the reference's ``inference_all_reduce`` calls perform.
- CUDA-graph capture/replay == jit compile/execute; ``enable_cuda_graph`` is
  honored trivially.
- Kernel injection == the Pallas op tier, used by the model implementations.
"""

from typing import Any, Callable, Optional

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger


class InferenceEngine:

    def __init__(self, model, config: DeepSpeedInferenceConfig, params=None, param_specs=None):
        import jax

        self._config = config
        self.module = model

        tp = config.tensor_parallel.tp_size
        if not groups.mesh_is_initialized():
            groups.initialize_mesh(model_parallel_size=tp)
        self.mesh = groups.get_mesh()

        # resolve (apply_fn, params)
        if params is None and isinstance(model, dict):
            params = model.get("params")
            model = model.get("module")
            self.module = model
        if hasattr(model, "apply"):
            self._apply = lambda p, *a, **kw: model.apply({"params": p}, *a, **kw)
        elif callable(model):
            self._apply = model
        else:
            raise ValueError(f"Cannot build an inference engine from {type(model)}")

        self.params = None
        if params is not None:
            dtype = config.jnp_dtype
            from deepspeed_tpu.runtime.utils import cast_tree
            from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy
            # zero stage 0 here: inference params sharded only by TP specs
            policy = ZeroShardingPolicy(stage=0, mesh=self.mesh)
            shardings = policy.param_shardings(params, param_specs)
            self.params = jax.device_put(cast_tree(params, dtype), shardings)

        self._jit_forward = jax.jit(self._apply)

    def forward(self, *inputs, **kwargs):
        """Reference engine.py:584 — jit-compiled forward (graph replay analog)."""
        if self.params is not None:
            return self._jit_forward(self.params, *inputs, **kwargs)
        return self._jit_forward(*inputs, **kwargs)

    __call__ = forward

    def generate(self, *inputs, **kwargs):
        """Reference engine.py:613; full sampling loop arrives with the v2 ragged
        engine — here we delegate to a module-provided generate."""
        if hasattr(self.module, "generate"):
            return self.module.generate(*inputs, **kwargs)
        raise NotImplementedError("generate() requires a module with a generate method "
                                  "or the v2 ragged inference engine")

    def profile_model_time(self, use_cuda_events=True):
        logger.warning("model profiling on TPU: use jax.profiler traces")
