"""Cost attribution through the real serving path (ISSUE tentpole a): every
request carries a RequestCost from admission to finalize, per-tenant rollups
reconcile EXACTLY against the aggregate (the conservation gate), the cost
plane surfaces in /v1/stats rows and metric families — and all of it costs
zero registry calls with telemetry off (the disabled-hot-path satellite).
"""

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving import RequestState, ServingConfig, ServingScheduler
from deepspeed_tpu.telemetry.ledger import PHASES

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def test_costs_attach_and_conserve_end_to_end(make_engine):
    """The conservation gate on the REAL scheduler: a seeded multi-tenant
    workload runs to DONE; afterwards the per-tenant integer token sums, the
    request counts, and the per-request costs all reconcile exactly against
    the ledger aggregate — costs are conserved quantities, not samples."""
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        # uniform lengths: the decode batch size (and so the perf bucket)
        # repeats across ticks, so dispatches past the compile amnesty bill
        # real device seconds
        plan = [("a", 6), ("a", 6), ("b", 6), (None, 6)]
        reqs = [sched.submit(_prompt(), max_new_tokens=n, tenant=t)
                for t, n in plan]
        _run_until(sched, lambda: all(r.finished for r in reqs))
        assert all(r.state is RequestState.DONE for r in reqs)

        for req in reqs:
            assert req.cost is not None
            doc = req.cost.to_dict()
            assert doc["tokens"]["billed"] > 0
            # a request whose every dispatch first-sighted a (program, bucket)
            # is fully compile-amnestied: the wall time is accounted either
            # way, just never silently dropped
            assert doc["device_seconds"] + doc["amnesty_seconds"] > 0
            assert doc["kv_block_seconds"]["device"] > 0  # KV held for >0s
            assert doc["dispatches"] > 0
        # the warm requests (every program already sighted) billed real time
        assert sched.usage()["totals"]["device_seconds"] > 0

        usage = sched.usage()
        assert usage["enabled"] is True
        totals, tenants = usage["totals"], usage["tenants"]
        # every request billed to a concrete tenant (None -> default)
        assert set(tenants) == {"a", "b", "default"}
        assert tenants["a"]["requests"] == 2
        assert tenants["b"]["requests"] == tenants["default"]["requests"] == 1
        # conservation, three ways: tenant rows vs aggregate, per-request
        # costs vs aggregate, and request counts — all exact integer sums
        for phase in PHASES:
            assert sum(row["tokens"][phase] for row in tenants.values()) \
                == totals["tokens"][phase]
            assert sum(r.cost.tokens[phase] for r in reqs) \
                == totals["tokens"][phase]
        assert sum(row["tokens"]["billed"] for row in tenants.values()) \
            == totals["tokens"]["billed"]
        assert sum(row["requests"] for row in tenants.values()) \
            == totals["requests"] == len(reqs)

        # the cost families made it to the registry, labeled per tenant
        snap = telemetry.get_registry().snapshot()
        assert "serving_cost_billed_tokens_total" in snap
        tenant_tokens = {labels["tenant"]: v
                         for labels, v in snap["serving_tenant_tokens_total"]}
        assert tenant_tokens["a"] == tenants["a"]["tokens"]["billed"]
        assert sum(tenant_tokens.values()) == totals["tokens"]["billed"]
    finally:
        sched.stop(drain=False)


def test_cost_and_tenant_ride_the_stats_rows(make_engine):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        req = sched.submit(_prompt(), max_new_tokens=8, tenant="acme")
        # a few decode ticks in: the bucket has repeated, so the request has
        # billed device time past its compile amnesty and is still active
        _run_until(sched, lambda: len(req.tokens) >= 4)
        assert req.state is RequestState.DECODE
        (row,) = sched.stats()["requests"]
        assert row["tenant"] == "acme"
        assert row["cost"]["billed_tokens"] > 0
        assert row["cost"]["device_ms"] > 0
        # the flight recorder's provider view (a wedged-loop post-mortem)
        # carries the same attribution columns, queued rows included
        queued = sched.submit(_prompt(5), max_new_tokens=2, tenant="later")
        flight = sched.flight_state()
        (frow,) = flight["requests"]
        assert frow["tenant"] == "acme" and frow["cost"]["billed_tokens"] > 0
        assert frow["kv_blocks"] > 0
        assert [q["tenant"] for q in flight["queued_requests"]] == ["later"]
        _run_until(sched, lambda: req.finished and queued.finished)
    finally:
        sched.stop(drain=False)


def test_cost_plane_zero_cost_when_disabled(make_engine):
    """The disabled-hot-path satellite, multi-tenant edition: tenant-labeled
    requests through the full scheduler path with telemetry off touch the
    registry zero times, carry no RequestCost, and /v1/usage degrades to a
    feature probe."""
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    try:
        reqs = [sched.submit(_prompt(), max_new_tokens=2, tenant=t)
                for t in ("a", "b", None)]
        _run_until(sched, lambda: all(r.finished for r in reqs))
        assert all(r.state is RequestState.DONE for r in reqs)
        assert all(r.cost is None for r in reqs)
        assert reqs[2].tenant == "default"  # identity still assigned
        assert sched.usage() == {"enabled": False}
        assert sched.stats()["perf"] is None
        assert telemetry.get_registry().api_calls == 0  # not one touch
    finally:
        sched.stop(drain=False)
