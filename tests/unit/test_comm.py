"""Collective API tests (reference: tests/unit/comm/test_dist.py semantics, run on
the virtual 8-device mesh instead of a forked process pool)."""

import numpy as np
import pytest

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm import ReduceOp
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def mesh():
    groups.initialize_mesh(force=True)
    dist.init_distributed()
    yield


def test_all_reduce_sum():
    # shard i holds value i+1 → every shard becomes the sum 36
    x = np.arange(1.0, 9.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, op=ReduceOp.SUM))
    np.testing.assert_allclose(out, np.full((8, 1), 36.0))


def test_all_reduce_max():
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, op=ReduceOp.MAX))
    np.testing.assert_allclose(out, np.full((8, 1), 7.0))


def test_all_gather_into_tensor():
    x = np.arange(16.0).reshape(8, 2).astype(np.float32)  # each rank: [1,2]-slice
    out = np.asarray(dist.all_gather_into_tensor(x[:, None, :]))
    # torch semantics: concat of per-rank locals along dim0
    np.testing.assert_allclose(out.reshape(8, 2), x)


def test_reduce_scatter_tensor():
    # every rank holds the same [8*2] vector of ones → each rank's chunk = 8
    x = np.ones((8, 16), dtype=np.float32)
    out = np.asarray(dist.reduce_scatter_tensor(x))
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out, np.full((8, 2), 8.0))


def test_all_to_all_single():
    # rank r sends chunk c to rank c; chunk value = 10*r + c
    x = np.zeros((8, 8), dtype=np.float32)
    for r in range(8):
        for c in range(8):
            x[r, c] = 10 * r + c
    out = np.asarray(dist.all_to_all_single(x))
    expect = x.T  # rank r ends with [10*c + r for c in range(8)]
    np.testing.assert_allclose(out, expect)


def test_broadcast():
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.broadcast(x, src=3))
    np.testing.assert_allclose(out, np.full((8, 1), 3.0))


def test_subgroup_all_reduce():
    groups.initialize_mesh(model_parallel_size=2, force=True)
    # group = 'model' axis (size 2): dim0 splits into 2 contiguous chunks, chunk g
    # being group-rank g's local tensor; result: each chunk = chunk sum.
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, group="model"))
    chunk_sum = x[:4] + x[4:]
    expect = np.concatenate([chunk_sum, chunk_sum])
    np.testing.assert_allclose(out, expect)


def test_comms_logger_records():
    dist.configure(enabled=True, verbose=False)
    x = np.ones((8, 4), dtype=np.float32)
    dist.all_reduce(x)
    summary = dist.comm.comms_logger.log_all(print_log=False)
    assert "all_reduce" in summary
    dist.configure(enabled=False)


# ---- reference-surface breadth (reference comm.py exports) --------------------
def test_alias_and_list_collectives():
    x = np.arange(1.0, 9.0).reshape(8, 1).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(dist.all_gather(x)),
                                  np.asarray(dist.all_gather_into_tensor(x)))
    rs = np.ones((8, 16), np.float32)  # per-rank [16]; chunk per rank = [2]
    np.testing.assert_array_equal(np.asarray(dist.reduce_scatter(rs)),
                                  np.asarray(dist.reduce_scatter_tensor(rs)))
    outs = dist.all_reduce_coalesced([x, 2 * x])
    np.testing.assert_allclose(np.asarray(outs[1]), 2 * np.asarray(outs[0]))
    outs = dist.all_gather_coalesced([x])
    assert np.asarray(outs[0]).shape[0] == 8


def test_scatter_hands_each_rank_its_chunk():
    # src rank 0 holds chunks [0..7]; after scatter, rank r holds chunk r —
    # stacked per-rank layout == the identity
    x = np.tile(np.arange(8.0, dtype=np.float32).reshape(1, 8), (8, 1))
    out = np.asarray(dist.scatter(x, src=0))
    np.testing.assert_array_equal(out, np.arange(8.0, dtype=np.float32).reshape(8, 1))


def test_p2p_raises_with_guidance():
    with pytest.raises(NotImplementedError, match="ppermute"):
        dist.send(np.zeros(4), dst=1)
    with pytest.raises(NotImplementedError, match="ppermute"):
        dist.recv(np.zeros(4), src=0)


def test_monitored_barrier_single_process_passes():
    # world=1 reduces to an effects barrier; the timeout is trivially met
    dist.monitored_barrier(timeout=0.5)


def test_monitored_barrier_file_rendezvous_all_ranks(tmp_path):
    """The multi-process rendezvous core: N threads playing N ranks all
    arrive -> everyone passes; repeated barriers advance the generation."""
    import threading

    from deepspeed_tpu.comm.comm import _file_barrier

    errors = []

    def rank(r, gen):
        try:
            _file_barrier(str(tmp_path), "b", gen, r, 3, timeout_s=5.0)
        except Exception as e:  # surfaced on the main thread
            errors.append(e)

    for gen in range(3):  # three consecutive barriers (generation reuse)
        threads = [threading.Thread(target=rank, args=(r, gen)) for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors


def test_monitored_barrier_rejects_previous_jobs_stale_files(tmp_path):
    """A later job reusing the same rendezvous dir must not be satisfied by
    a previous job's leftover files: with ``min_unix`` armed (no DSTPU_JOB_ID
    scoping), anything stamped before this gang's init epoch is stale and a
    dead rank still times the barrier out."""
    import json
    import time

    from deepspeed_tpu.comm.comm import BarrierTimeoutError, _file_barrier

    # the "previous job": rank 1 arrived long ago at the same name/generation
    stale = tmp_path / "b.g0.rank1"
    stale.write_text(json.dumps({"rank": 1, "unix": time.time() - 3600}))
    with pytest.raises(BarrierTimeoutError, match=r"absent ranks \[1\]"):
        _file_barrier(str(tmp_path), "b", 0, 0, 2, timeout_s=0.3,
                      min_unix=time.time() - 60)
    # a FRESH peer file passes the same threshold
    fresh = tmp_path / "c.g0.rank1"
    fresh.write_text(json.dumps({"rank": 1, "unix": time.time()}))
    _file_barrier(str(tmp_path), "c", 0, 0, 2, timeout_s=2.0,
                  min_unix=time.time() - 60)


def test_monitored_barrier_timeout_names_absent_ranks(tmp_path):
    """The seed bug: monitored_barrier accepted a timeout and ignored it —
    a dead rank wedged its peers forever. Now the deadline is enforced and
    the error names exactly who never arrived."""
    from deepspeed_tpu.comm.comm import BarrierTimeoutError, _file_barrier

    with pytest.raises(BarrierTimeoutError, match=r"absent ranks \[1, 2\]"):
        _file_barrier(str(tmp_path), "t", 0, 0, 3, timeout_s=0.3)


def test_monitored_barrier_timeout_counts_metric(tmp_path):
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.comm.comm import BarrierTimeoutError, _file_barrier
    from deepspeed_tpu.telemetry import TelemetryConfig

    telemetry.configure(TelemetryConfig(enabled=True))
    try:
        with pytest.raises(BarrierTimeoutError):
            _file_barrier(str(tmp_path), "m", 0, 0, 2, timeout_s=0.1)
        assert telemetry.get_registry().counter("barrier_timeouts_total").value == 1
    finally:
        telemetry.shutdown()
        telemetry.state.registry = None


def test_group_and_capability_surface():
    assert dist.get_world_group() is None
    assert dist.new_group() is None
    assert dist.new_group(list(range(dist.get_world_size()))) is None  # world idiom
    with pytest.raises(NotImplementedError):
        dist.new_group([0, 2])
    with pytest.raises(NotImplementedError):
        dist.get_global_rank("model", 1)
    assert dist.get_global_rank(None, 3) == 3
    assert dist.get_all_ranks_from_group(None) == list(range(8))
    assert dist.is_available()
    assert dist.has_all_gather_into_tensor() and dist.has_reduce_scatter_tensor()
    assert dist.has_all_reduce_coalesced() and not dist.has_coalescing_manager()
    assert not dist.in_aml() and not dist.in_aws_sm() and not dist.in_dlts()
