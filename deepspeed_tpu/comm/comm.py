"""Public collectives API over XLA.

TPU-native analog of ``deepspeed/comm/comm.py`` (the torch.distributed-compatible
surface: all_reduce / all_gather_into_tensor / reduce_scatter_tensor /
all_to_all_single / broadcast / barrier, plus ``init_distributed`` with env
discovery and the ``@timed_op`` comms-profiling wrapper, comm.py:101-771).

SPMD semantics
--------------
The reference's collectives act on *per-rank local tensors*. Under single-controller
SPMD the equivalent is a jax.Array sharded over the group's mesh axes along its
leading dimension — shard i plays the role of rank i's local tensor:

  - ``all_reduce(x, group)``:    x:[G, ...] sharded on dim0 → each shard replaced by
                                 the elementwise reduction over shards (shape kept).
  - ``all_gather_into_tensor``:  x:[G, s, ...] sharded on dim0 → [G*s, ...] fully
                                 replicated (torch-style concat along dim0).
  - ``reduce_scatter_tensor``:   x:[G, G*s, ...] sharded dim0 → [G, s, ...] sharded
                                 dim0; shard i = sum over ranks of slice i.
  - ``all_to_all_single``:       x:[G, G, ...] sharded dim0 → transpose of rank/chunk.
  - ``broadcast(x, src)``:       every shard replaced by shard ``src``.

``group`` is a mesh-axis name or tuple of names (see utils/groups.py); None means
the dense data-parallel group. These eager wrappers are for host-driven code and
tests; inside a jitted train step use ``jax.lax`` collectives directly — the engine
does — so XLA can fuse and overlap them.
"""

import functools
import os
import time

import numpy as np

from deepspeed_tpu.comm.backend import Backend
from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils import groups as groups_mod
from deepspeed_tpu.utils.comms_logging import CommsLogger
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.jax_compat import shard_map as _compat_shard_map

cdb = None  # current distributed backend (reference: comm.py:41)
comms_logger = CommsLogger()
timers = {}


class XLABackend(Backend):
    """The one backend: XLA collectives over the global mesh (ICI/DCN)."""

    def __init__(self):
        import jax
        super().__init__(name="xla", rank=jax.process_index(), size=jax.process_count())
        self.init_process_group()


def is_initialized():
    return cdb is not None


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1):
    """Bootstrap multi-host JAX + build the global mesh.

    Reference: comm.py:604-771 (init_distributed with MPI/AML/SageMaker discovery
    feeding torch.distributed rendezvous). Here the rendezvous is JAX's coordination
    service: on multi-host launches we call ``jax.distributed.initialize`` with
    coordinator discovery from env (DSTPU_COORDINATOR / MASTER_ADDR, or OpenMPI vars
    as in the reference's ``mpi_discovery``).
    """
    global cdb
    if cdb is not None:
        return cdb
    import jax

    coord = os.environ.get("DSTPU_COORDINATOR") or os.environ.get("COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("DSTPU_NUM_PROCESSES", os.environ.get("WORLD_SIZE", "0")) or 0)
    proc_id = os.environ.get("DSTPU_PROCESS_ID", os.environ.get("RANK"))
    if coord is None and auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in os.environ:
        # OpenMPI discovery, reference comm.py mpi_discovery()
        nproc = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        proc_id = os.environ["OMPI_COMM_WORLD_RANK"]
        coord = f"{os.environ.get('MASTER_ADDR', 'localhost')}:{distributed_port}"
    if coord is not None and nproc > 1:
        _enable_cpu_cross_process_collectives()
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc,
                                   process_id=int(proc_id or 0))
        if verbose:
            logger.info(f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}")
    # initialize() blocks until every process joined the (freshly bound)
    # coordinator, so this instant is gang-synchronized to within the release
    # skew — monitored_barrier uses it to reject a PREVIOUS job's leftover
    # rendezvous files when no DSTPU_JOB_ID scopes the rendezvous dir
    global _init_done_unix
    _init_done_unix = time.time()
    cdb = XLABackend()
    return cdb


_init_done_unix = None  # set by init_distributed (gang-synchronized instant)


def _enable_cpu_cross_process_collectives():
    """CPU gangs need an explicit cross-process collectives backend: the
    default CPU client refuses multi-process computations outright
    ("Multiprocess computations aren't implemented on the CPU backend"), which
    is what broke ``test_local_two_process_training`` from seed. jaxlib ships
    gloo; selecting it *before* ``jax.distributed.initialize`` makes a
    multi-process CPU mesh a real gang — the tier-1 formulation every gang
    fault-tolerance gate trains on. TPU/GPU platforms are untouched (their
    collectives ride ICI/DCN/NCCL natively)."""
    import jax
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or os.environ.get("JAX_PLATFORMS") or "")
    if not platforms:
        # unset = jax autodetects; guessing CPU here would break TPU/GPU
        # hosts, but a CPU-only host WILL hit "Multiprocess computations
        # aren't implemented on the CPU backend" — say so up front
        logger.warning("multi-process init with JAX_PLATFORMS unset: if this "
                       "host resolves to the CPU backend, set "
                       "JAX_PLATFORMS=cpu so the gloo cross-process "
                       "collectives backend is selected")
        return
    if platforms.split(",")[0].strip().lower() != "cpu":
        return
    try:
        if getattr(jax.config, "jax_cpu_collectives_implementation", None) != "gloo":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            logger.info("CPU gang: cross-process collectives backend = gloo")
    except Exception as e:  # older jaxlibs without the option: surface, don't die
        logger.warning(f"could not select gloo CPU collectives ({e}); "
                       f"multi-process CPU computations may be unavailable")


def destroy_process_group(group=None):
    global cdb
    cdb = None


def get_rank(group=None):
    """Host process rank (reference rank == device rank; under SPMD one process
    drives many devices, so this is the process index)."""
    import jax
    return jax.process_index()


def get_world_size(group=None):
    """Number of devices in ``group`` (mesh axes), or all devices if None."""
    import jax
    if group is None:
        return len(jax.devices())
    return groups_mod._axis_size(group)


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", 0))


# ---- eager collective implementations --------------------------------------------


def _resolve_group(group):
    if group is None:
        group = groups_mod.get_data_parallel_axes()
    if isinstance(group, str):
        group = (group, )
    return tuple(group)


def _group_spec(axes):
    from jax.sharding import PartitionSpec as P
    return P(axes)


_REDUCE_FNS = None


def _reduce_fn(op):
    import jax
    import jax.numpy as jnp
    global _REDUCE_FNS
    if _REDUCE_FNS is None:
        _REDUCE_FNS = {
            ReduceOp.SUM: jax.lax.psum,
            ReduceOp.AVG: lambda x, ax: jax.lax.pmean(x, ax),
            ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
            ReduceOp.PRODUCT: lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)),
        }
    if op not in _REDUCE_FNS:
        raise NotImplementedError(f"ReduceOp {op} not supported")
    return _REDUCE_FNS[op]


def timed_op(func):
    """Profile collectives through the comms logger and/or the unified
    telemetry layer (reference: comm.py:101-134 @timed_op). Disabled (the
    default) the wrapper costs two boolean checks and nothing else — the
    telemetry registry/span sinks are only touched when ``telemetry.state
    .active``."""
    from deepspeed_tpu import telemetry

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        if not (comms_logger.enabled or telemetry.state.active):
            return func(*args, **kwargs)
        import jax
        name = func.__name__
        t0 = time.time()
        result = func(*args, **kwargs)
        jax.block_until_ready(result)
        elapsed = time.time() - t0
        tensor = args[0] if args else kwargs.get("tensor")
        size = int(np.prod(tensor.shape)) * tensor.dtype.itemsize if tensor is not None else 0
        if comms_logger.enabled:
            comms_logger.append(name, kwargs.get("log_name", name), elapsed, size)
        if telemetry.state.active:
            telemetry.record_comm_op(name, elapsed, size)
        return result

    return wrapper


def _shard_map(fn, in_specs, out_specs):
    import jax
    mesh = groups_mod.get_mesh()
    return _compat_shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


def _device_put_grouped(tensor, axes):
    """Lay ``tensor`` out with dim0 sharded over the group axes."""
    import jax
    from jax.sharding import NamedSharding
    mesh = groups_mod.get_mesh()
    sharding = NamedSharding(mesh, _group_spec(axes))
    return jax.device_put(tensor, sharding)


@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    axes = _resolve_group(group)
    red = _reduce_fn(op)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    return _shard_map(lambda x: red(x, axes), spec, spec)(tensor)


@timed_op
def inference_all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    return all_reduce(tensor, op=op, group=group)


@timed_op
def all_gather_into_tensor(tensor, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    from jax.sharding import PartitionSpec as P

    def f(x):
        # x: [G_local=1, s, ...] → concat over group → [G*s, ...]
        g = jax.lax.all_gather(x, axes, axis=0, tiled=True)
        return g.reshape((-1, ) + g.shape[2:])

    return _shard_map(f, spec, P())(tensor)


# legacy name used across the reference
allgather_fn = all_gather_into_tensor


@timed_op
def reduce_scatter_tensor(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    red = "sum" if op in (ReduceOp.SUM, ReduceOp.AVG) else None
    if red is None:
        raise NotImplementedError("reduce_scatter supports SUM/AVG")
    G = groups_mod._axis_size(axes)

    def f(x):
        # x: [1, G*s, ...] per rank → scatter dim1 into G chunks, sum over ranks
        chunks = x.reshape((G, -1) + x.shape[2:])  # [G, s, ...]
        out = jax.lax.psum_scatter(chunks, axes, scatter_dimension=0, tiled=False)
        if op == ReduceOp.AVG:
            out = out / G
        return out[None]  # [1, s, ...]

    return _shard_map(f, spec, spec)(tensor)


reduce_scatter_fn = reduce_scatter_tensor


@timed_op
def all_to_all_single(tensor, group=None, async_op=False, log_name=None):
    import jax
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)

    def f(x):
        # x: [1, G, ...] per rank; exchange chunk j with rank j.
        return jax.lax.all_to_all(x, axes, split_axis=1, concat_axis=0, tiled=False).reshape(x.shape)

    return _shard_map(f, spec, spec)(tensor)


@timed_op
def broadcast(tensor, src=0, group=None, async_op=False, log_name=None):
    import jax
    import jax.numpy as jnp
    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)

    def f(x):
        idx = jax.lax.axis_index(axes)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, axes)

    return _shard_map(f, spec, spec)(tensor)


@timed_op
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    # On an SPMD mesh a rooted reduce has no cost advantage over all_reduce.
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor, group=None, async_op=False, log_name=None):
    """Reference list-based all_gather; the SPMD form returns the stacked
    [G, ...] tensor (what the reference writes into its tensor_list)."""
    return all_gather_into_tensor(tensor, group=group)


def all_gather_coalesced(tensors, group=None, async_op=False):
    return [all_gather_into_tensor(t, group=group) for t in tensors]


def all_reduce_coalesced(tensors, op=ReduceOp.SUM, group=None, async_op=False):
    return [all_reduce(t, op=op, group=group) for t in tensors]


def all_to_all(tensor, group=None, async_op=False, log_name=None):
    return all_to_all_single(tensor, group=group)


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, async_op=False, log_name=None):
    return reduce_scatter_tensor(tensor, op=op, group=group)


def gather(tensor, dst=0, group=None, async_op=False, log_name=None):
    """Rooted gather: under SPMD the gathered result exists on every rank (a
    rooted variant has no cost advantage on a mesh) — reference semantics are
    a superset."""
    return all_gather_into_tensor(tensor, group=group)


def scatter(tensor, src=0, group=None, async_op=False, log_name=None):
    """Rank r receives chunk r of the SOURCE rank's row (stacked layout:
    dim0 = ranks, each row = the flattened scatter list) — the inverse of
    :func:`all_gather`."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.utils import groups as _g

    axes = _resolve_group(group)
    spec = _group_spec(axes)
    tensor = _device_put_grouped(tensor, axes)
    mesh = _g.get_mesh()
    G = 1
    for ax in (axes if isinstance(axes, (tuple, list)) else (axes, )):
        G *= mesh.shape.get(ax, 1)

    if tensor.ndim < 2:
        raise ValueError("scatter expects the stacked [ranks, chunks...] layout "
                         "(dim0 = ranks, dim1 = the flattened scatter list)")
    if tensor.shape[1] % G != 0:
        raise ValueError(f"scatter: dim-1 size {tensor.shape[1]} must divide evenly "
                         f"into {G} chunks (the reference rejects unequal chunks too)")

    def f(x):
        idx = jax.lax.axis_index(axes)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        full = jax.lax.psum(masked, axes)  # the source row, on every rank
        chunk = full.shape[1] // G
        return jax.lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=1)

    return _shard_map(f, spec, spec)(tensor)


# -- point-to-point: no user-level p2p under single-program SPMD ---------------
def send(tensor, dst, group=None, tag=0):
    raise NotImplementedError("point-to-point send/recv does not exist under "
                              "single-program SPMD; express neighbor exchange with "
                              "jax.lax.ppermute inside shard_map (see runtime/pipe)")


def recv(tensor, src, group=None, tag=0):
    raise NotImplementedError("see send(): use jax.lax.ppermute inside shard_map")


def isend(tensor, dst, group=None, tag=0):
    return send(tensor, dst, group, tag)


def irecv(tensor, src, group=None, tag=0):
    return recv(tensor, src, group, tag)


# -- groups / ranks -------------------------------------------------------------
def get_world_group():
    """The whole-mesh group (None = all axes in this API)."""
    return None


def new_group(ranks=None):
    """Mesh axes ARE the process groups here; arbitrary rank sets cannot be
    carved out of an SPMD mesh. The world group (all ranks, device-count
    convention like get_world_size) is allowed for compatibility."""
    if ranks is None or sorted(ranks) == list(range(get_world_size())):
        return None
    raise NotImplementedError("arbitrary-rank groups: use mesh axis names "
                              "(groups.initialize_mesh) as the group structure")


def get_global_rank(group=None, group_rank=0):
    if group is None:
        return int(group_rank)
    raise NotImplementedError(
        "an axis-name group has one replica per remaining-mesh coordinate, so "
        "group_rank alone does not determine a global rank; compute positions "
        "with jax.lax.axis_index inside shard_map instead")


def get_all_ranks_from_group(group=None):
    from deepspeed_tpu.utils import groups as _g
    axes = _resolve_group(group)
    size = 1
    mesh = _g.get_mesh()
    for ax in (axes if isinstance(axes, (tuple, list)) else (axes, )):
        size *= mesh.shape.get(ax, 1)
    return list(range(size))


# -- capability probes (reference has_* feature detection) ----------------------
def is_available() -> bool:
    return True


def has_all_gather_into_tensor() -> bool:
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_all_reduce_coalesced() -> bool:
    return True


def has_coalescing_manager() -> bool:
    return False  # XLA fuses collectives; there is no manual manager


def set_backend(backend_name=None):
    ...  # the XLA backend is the only one; kept for API parity


def init_deepspeed_backend(ds_backend=None, timeout=None, init_method=None):
    ...  # init_distributed covers this


def mpi_discovery(distributed_port=29500, verbose=True):
    """Populate the full DSTPU_* rendezvous contract from OpenMPI env
    (reference comm.py mpi_discovery: rank/size from env, the coordinator
    address broadcast from rank 0 via mpi4py — MASTER_ADDR/PORT there)."""
    import os
    import socket
    env = os.environ
    if "OMPI_COMM_WORLD_RANK" not in env:
        return
    env.setdefault("DSTPU_PROCESS_ID", env["OMPI_COMM_WORLD_RANK"])
    env.setdefault("DSTPU_NUM_PROCESSES", env["OMPI_COMM_WORLD_SIZE"])
    if "DSTPU_COORDINATOR" not in env:
        try:
            from mpi4py import MPI
            comm = MPI.COMM_WORLD
            host = comm.bcast(socket.gethostbyname(socket.gethostname()), root=0)
            env["DSTPU_COORDINATOR"] = f"{host}:{distributed_port}"
        except ImportError:
            logger.warning("mpi_discovery: mpi4py unavailable — set DSTPU_COORDINATOR "
                           "to rank-0's host:port yourself or use the dstpu launcher "
                           "(it exports the full contract)")
    if verbose:
        logger.info(f"mpi_discovery: rank={env['DSTPU_PROCESS_ID']} "
                    f"world={env['DSTPU_NUM_PROCESSES']} "
                    f"coordinator={env.get('DSTPU_COORDINATOR', 'UNSET')}")


# -- cloud-environment detectors (reference comm.py:586-676) --------------------
def in_aml() -> bool:
    import os
    return "AZUREML_EXPERIMENT_ID" in os.environ


def in_aws_sm() -> bool:
    import os
    return "SM_TRAINING_ENV" in os.environ


def in_dlts() -> bool:
    import os
    return "DLTS_JOB_ID" in os.environ


def patch_aml_env_for_torch_nccl_backend(*a, **k):
    ...  # NCCL env shims do not apply to the XLA backend


def patch_aws_sm_env_for_torch_nccl_backend(*a, **k):
    ...


def barrier(group=None):
    import jax
    jax.effects_barrier()


class BarrierTimeoutError(RuntimeError):
    """``monitored_barrier`` expired its deadline; the message names the
    absent ranks (the reference raises the first absent rank unless
    ``wait_all_ranks`` — here the full set is always collected, it costs
    nothing with a file rendezvous)."""


DEFAULT_BARRIER_TIMEOUT_S = 300.0

# per-(name) generation counters: barrier semantics require every rank to
# reach every barrier, so per-process counters agree across the gang
_barrier_generations = {}


def _barrier_timeouts_metric():
    from deepspeed_tpu import telemetry
    if not telemetry.is_active():
        return None
    return telemetry.get_registry().counter(
        "barrier_timeouts_total",
        "monitored_barrier deadline expiries (absent ranks named in the error)")


def _barrier_rendezvous_dir():
    """Where ranks rendezvous: the gang dir when the elastic agent armed one
    (shared-fs multi-host gangs set it explicitly), else a coordinator-keyed
    tempdir — same-host CPU gangs (the tier-1 formulation) share /tmp."""
    from deepspeed_tpu.elasticity.gang import GANG_DIR_ENV
    gang_dir = os.environ.get(GANG_DIR_ENV)
    if gang_dir:
        return os.path.join(gang_dir, "barriers")
    coord = os.environ.get("DSTPU_COORDINATOR") or os.environ.get("COORDINATOR_ADDRESS")
    if not coord:
        return None
    import hashlib
    import tempfile
    # key by coordinator AND the per-launch job nonce (launcher/launch.py,
    # DSElasticAgent both export one): a later job reusing the same
    # coordinator address must never rendezvous against this job's leftovers
    job = os.environ.get("DSTPU_JOB_ID", "")
    key = hashlib.sha1(f"{coord}|{job}".encode()).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(), f"dstpu_barrier_{key}")


def _file_barrier(bdir, name, generation, rank, world, timeout_s, poll_s=0.02,
                  min_unix=None, on_wait=None):
    """Rendezvous: every rank drops ``<name>.g<gen>.rank<k>`` and polls until
    all ``world`` files of this generation exist. Deadline expiry raises
    :class:`BarrierTimeoutError` naming the absent ranks. Files persist one
    generation (a rank may observe completion and race ahead before a slow
    peer has read the files), then each rank reaps its own older ones.

    ``min_unix``: only accept peer files stamped at or after it — the guard
    against a PREVIOUS job's leftovers in a shared rendezvous dir (a stale
    file predates the current job's coordinator bind, so any stamp from this
    gang's init epoch onward is fresh; only meaningful when all ranks share
    one clock). None = accept any file. ``on_wait`` is called once per poll
    iteration while waiting (liveness reporting)."""
    import time as _time
    os.makedirs(bdir, exist_ok=True)

    def fname(g, r):
        return os.path.join(bdir, f"{name}.g{g}.rank{r}")

    accepted = set()  # a once-fresh file can only be replaced by a fresher one

    def present(g, r):
        if r in accepted:
            return True
        fp = fname(g, r)
        if not os.path.exists(fp):
            return False
        if min_unix is not None:
            try:
                with open(fp) as f:
                    import json as _json
                    if _json.load(f).get("unix", 0) < min_unix:
                        return False
            except (OSError, ValueError):
                return False  # torn/stale: the owner rewrites it atomically
        accepted.add(r)
        return True

    from deepspeed_tpu.elasticity.gang import atomic_write_json
    atomic_write_json(fname(generation, rank), {"rank": rank, "unix": _time.time()})
    deadline = _time.monotonic() + timeout_s
    while True:
        absent = [r for r in range(world) if not present(generation, r)]
        if not absent:
            break
        if _time.monotonic() > deadline:
            m = _barrier_timeouts_metric()
            if m is not None:
                m.inc()
            raise BarrierTimeoutError(
                f"monitored_barrier {name!r} (generation {generation}) timed "
                f"out after {timeout_s:.1f}s: rank {rank} waited on absent "
                f"ranks {absent} of world {world}")
        if on_wait is not None:
            on_wait()
        _time.sleep(poll_s)
    # reap this rank's file from two generations back — old enough that every
    # peer has necessarily left that barrier (they are at generation-1+)
    if generation >= 2:
        try:
            os.unlink(fname(generation - 2, rank))
        except OSError:
            pass


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False, name="monitored"):
    """A barrier that actually enforces its ``timeout`` (the reference's
    torch.distributed ``monitored_barrier``; the seed version silently
    dropped it — a dead rank wedged its peers forever). ``timeout`` is
    seconds or a ``datetime.timedelta``; expiry raises
    :class:`BarrierTimeoutError` naming the absent ranks and counts
    ``barrier_timeouts_total``.

    Multi-process gangs rendezvous through files (the gang dir when the
    elastic agent armed one, else a coordinator-keyed tempdir — CPU gangs
    share a host). Single-process worlds reduce to an effects barrier. When
    no rendezvous dir is derivable (no gang dir, no coordinator), the
    deadline is unenforceable; that is logged loudly and the call falls
    back to the plain barrier."""
    import datetime
    import jax
    world = jax.process_count()
    if world <= 1:
        barrier(group)
        return
    if isinstance(timeout, datetime.timedelta):
        timeout_s = timeout.total_seconds()
    else:
        timeout_s = DEFAULT_BARRIER_TIMEOUT_S if timeout is None else float(timeout)
    bdir = _barrier_rendezvous_dir()
    if bdir is None:
        logger.warning("monitored_barrier: no rendezvous dir (set "
                       "DSTPU_GANG_DIR or DSTPU_COORDINATOR); the timeout "
                       "cannot be enforced — falling back to a plain barrier")
        barrier(group)
        return
    rank = jax.process_index()
    # scope by supervision life: a relaunched gang starts at generation 0
    # again, and the previous life's rendezvous files must not satisfy it
    name = f"{name}.l{os.environ.get('DSTPU_RESTART_COUNT', '0') or '0'}"
    generation = _barrier_generations.get(name, 0)
    _barrier_generations[name] = generation + 1
    # collective entry is a liveness event: a rank blocked here past the
    # deadline raises; a rank that never *arrives* shows a stale heartbeat.
    # While WAITING, keep beating (throttled): a rank legitimately parked at
    # a barrier behind a slow peer is making supervised progress — the hang
    # watchdog must not tear down a healthy gang for it
    from deepspeed_tpu.elasticity.gang import GANG_DIR_ENV, GangHeartbeat
    hb = GangHeartbeat.from_env(rank=rank)
    on_wait = None
    if hb is not None:
        hb.beat(phase=f"barrier:{name}")
        last_beat = [time.monotonic()]

        def on_wait():
            now = time.monotonic()
            if now - last_beat[0] >= 1.0:
                last_beat[0] = now
                hb.beat(phase=f"barrier:{name}")
    # without a job-scoped dir (manual launches: no DSTPU_JOB_ID) a previous
    # job on the same coordinator left files here; anything stamped before
    # this gang's init epoch (minus clock slack) is stale — a dead rank must
    # time the barrier out, not be impersonated by a leftover. Only armed on
    # the host-local tempdir path: a shared-fs gang dir spans hosts whose
    # wall clocks must not be compared
    min_unix = None
    if not os.environ.get("DSTPU_JOB_ID") and not os.environ.get(GANG_DIR_ENV) \
            and _init_done_unix is not None:
        min_unix = _init_done_unix - 5.0
    _file_barrier(bdir, name, generation, rank, world, timeout_s,
                  min_unix=min_unix, on_wait=on_wait)
    barrier(group)


def log_summary(show_straggler=False):
    """Print per-op communication statistics (reference: comm.py:422).

    With ``show_straggler=True`` on a multi-process job this is a COLLECTIVE
    (cross-rank latency allgather, as in the reference): call it on every
    process, not under an ``if rank == 0`` guard."""
    comms_logger.log_all(print_log=True, show_straggler=show_straggler)


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    comms_logger.configure(deepspeed_config=deepspeed_config,
                           enabled=enabled,
                           prof_all=prof_all,
                           prof_ops=prof_ops,
                           verbose=verbose,
                           debug=debug)
