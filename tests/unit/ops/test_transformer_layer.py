"""DeepSpeedTransformerLayer (reference ops/transformer/transformer.py — the
trainable BERT-style fused block)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer, init_params)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _cfg(**kw):
    base = dict(hidden_size=32, intermediate_size=64, heads=2,
                attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                num_hidden_layers=2, initializer_range=0.02, training=False)
    base.update(kw)
    return DeepSpeedTransformerConfig(**base)


def test_post_ln_matches_transformers_bert_layer():
    """pre_layer_norm=False is the reference's Post-LN mode — BertLayer math;
    parity against the torch implementation with mapped weights."""
    from transformers.models.bert.modeling_bert import BertLayer

    hf = transformers.BertConfig(hidden_size=32, num_attention_heads=2,
                                 intermediate_size=64, hidden_dropout_prob=0.0,
                                 attention_probs_dropout_prob=0.0,
                                 attn_implementation="eager")
    torch.manual_seed(0)
    tl = BertLayer(hf).eval()
    sd = {k: v.detach().numpy() for k, v in tl.state_dict().items()}

    def dense(pfx):
        return {"kernel": np.ascontiguousarray(sd[f"{pfx}.weight"].T),
                "bias": sd[f"{pfx}.bias"]}

    def ln(pfx):
        return {"scale": sd[f"{pfx}.weight"], "bias": sd[f"{pfx}.bias"]}

    params = {"layer": {
        "q_proj": dense("attention.self.query"),
        "k_proj": dense("attention.self.key"),
        "v_proj": dense("attention.self.value"),
        "attn_out": dense("attention.output.dense"),
        "attn_layernorm": ln("attention.output.LayerNorm"),
        "intermediate": dense("intermediate.dense"),
        "output": dense("output.dense"),
        "out_layernorm": ln("output.LayerNorm"),
    }}
    layer = DeepSpeedTransformerLayer(_cfg(pre_layer_norm=False))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8, 32)).astype(np.float32)
    with torch.no_grad():
        want = tl(torch.from_numpy(x))[0].numpy()
    got = np.asarray(layer.apply({"params": params}, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_pre_ln_differs_and_masks_apply():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)
    outs = {}
    for pre in (True, False):
        layer, params = init_params(_cfg(pre_layer_norm=pre))
        outs[pre] = np.asarray(layer.apply({"params": params}, x))
    assert not np.allclose(outs[True], outs[False])

    # [B, S] keep-mask: masking the tail must change the kept positions' output
    layer, params = init_params(_cfg(pre_layer_norm=True))
    mask = np.ones((2, 8), np.int32)
    mask[:, 5:] = 0
    full = np.asarray(layer.apply({"params": params}, x))
    masked = np.asarray(layer.apply({"params": params}, x, jnp.asarray(mask)))
    assert not np.allclose(full[:, :5], masked[:, :5])


def test_dropout_and_training_mode():
    """training=True + nonzero dropout is stochastic across rng keys and
    deterministic=True disables it."""
    cfg = _cfg(attn_dropout_ratio=0.3, hidden_dropout_ratio=0.3, training=True)
    layer, params = init_params(cfg)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)
    a = layer.apply({"params": params}, x, rngs={"dropout": jax.random.PRNGKey(1)})
    b = layer.apply({"params": params}, x, rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(a), np.asarray(b))
    c = layer.apply({"params": params}, x, None, True)  # deterministic=True
    d = layer.apply({"params": params}, x, None, True)
    np.testing.assert_array_equal(np.asarray(c), np.asarray(d))


def test_checkpoint_knobs_remat_without_changing_values():
    """gelu_checkpoint/attn_dropout_checkpoint/normalize_invertible map onto
    jax.checkpoint: same values, remat visible in the backward jaxpr."""
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 8, 32)), jnp.float32)
    plain, params = init_params(_cfg())
    remat = DeepSpeedTransformerLayer(_cfg(gelu_checkpoint=True))
    got_p = np.asarray(plain.apply({"params": params}, x))
    got_r = np.asarray(remat.apply({"params": params}, x))
    np.testing.assert_allclose(got_r, got_p, rtol=1e-6, atol=1e-6)

    def loss(p):
        return (remat.apply({"params": p}, x).astype(jnp.float32) ** 2).mean()

    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(params))
    assert "remat" in jaxpr or "checkpoint" in jaxpr
    g = jax.grad(loss)(params)
    assert all(np.isfinite(l).all() for l in jax.tree.leaves(g))


def test_return_tuple():
    layer, params = init_params(_cfg(return_tuple=True))
    x = jnp.zeros((1, 4, 32), jnp.float32)
    out = layer.apply({"params": params}, x)
    assert isinstance(out, tuple) and out[0].shape == (1, 4, 32)


def test_broadcast_integer_keep_mask_masks_not_adds():
    """A binary int [B,1,1,S] keep-mask must MASK (bool/int = keep-mask in any
    rank), not be silently added to the logits."""
    layer, params = init_params(_cfg(pre_layer_norm=True))
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)
    keep2d = np.ones((2, 8), np.int32)
    keep2d[:, 6:] = 0
    via_2d = np.asarray(layer.apply({"params": params}, x, jnp.asarray(keep2d)))
    via_4d = np.asarray(layer.apply({"params": params}, x,
                                    jnp.asarray(keep2d[:, None, None, :])))
    np.testing.assert_allclose(via_4d, via_2d, rtol=1e-6, atol=1e-6)
    # a float ADDITIVE mask of the same pattern (-1e30 on masked) also agrees
    additive = np.where(keep2d[:, None, None, :] > 0, 0.0, -1e30).astype(np.float32)
    via_add = np.asarray(layer.apply({"params": params}, x, jnp.asarray(additive)))
    np.testing.assert_allclose(via_add[:, :6], via_2d[:, :6], rtol=1e-5, atol=1e-5)


def test_3d_keep_mask_aligns_per_sample():
    """[B,Q,K] bool/int keep-masks broadcast per SAMPLE (not onto the heads
    axis): equivalent 2-D and 3-D forms of the same mask must agree."""
    layer, params = init_params(_cfg(pre_layer_norm=True))  # heads=2
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)  # B == heads == 2
    keep = np.ones((2, 8), np.int32)
    keep[0, 6:] = 0
    keep[1, 4:] = 0  # different pattern per sample — head-misalignment would show
    via_2d = np.asarray(layer.apply({"params": params}, x, jnp.asarray(keep)))
    m3 = np.broadcast_to(keep[:, None, :], (2, 8, 8)).copy()
    via_3d = np.asarray(layer.apply({"params": params}, x, jnp.asarray(m3)))
    np.testing.assert_allclose(via_3d, via_2d, rtol=1e-6, atol=1e-6)
