"""ServingScheduler hard paths: continuous admission, streaming, cancellation
mid-prefill, deadline expiry mid-decode, backpressure, KV-pressure eviction
with transparent restore, drain, and the engine.close() handshake.

Deterministic tests drive ``step()`` manually (``start=False``); integration
tests use the background thread.
"""

import threading
import time

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving import (QueueFullError, RequestState, SchedulerStopped,
                                   ServingConfig, ServingScheduler)

MAX_STEPS = 400  # safety bound for manual stepping loops


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _reference_greedy(llama_setup, prompt, n):
    """Training-model greedy continuation — the ground truth the paged-KV
    serving path must reproduce exactly."""
    import jax.numpy as jnp
    _, model, params = llama_setup
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = np.asarray(model.apply({"params": params["model"]},
                                        jnp.asarray(toks, jnp.int32)[None])[0])
        out.append(int(np.argmax(logits[-1])))
        toks.append(out[-1])
    return out


# --------------------------------------------------------------- happy path --
def test_overlapping_requests_stream_per_request(llama_setup, make_engine):
    """Acceptance: a persistent scheduler accepts requests submitted at
    different times and streams tokens back per-request."""
    cfg, _, _ = llama_setup
    engine = make_engine()
    rng = np.random.default_rng(0)
    p1 = rng.integers(0, cfg.vocab_size, 13).tolist()
    p2 = rng.integers(0, cfg.vocab_size, 5).tolist()

    sched = ServingScheduler(engine, ServingConfig())
    try:
        r1 = sched.submit(p1, max_new_tokens=6)
        assert r1.stream.get(timeout=60) == r1.tokens[0]  # streamed live (real TTFT)
        r2 = sched.submit(p2, max_new_tokens=4)           # overlaps with r1 in flight
        out1, out2 = r1.result(timeout=60), r2.result(timeout=60)
    finally:
        sched.stop(drain=False)
    assert out1 == _reference_greedy(llama_setup, p1, 6)
    assert out2 == _reference_greedy(llama_setup, p2, 4)
    assert r1.ttft_s is not None and r1.ttft_s <= r1.e2e_s
    assert engine._state_manager.n_tracked_sequences == 0


# ------------------------------------------------------------- cancellation --
def test_cancel_mid_prefill_frees_kv_blocks(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine(max_ragged_batch_size=16)  # 40-token prompt = 3 chunks
    free0 = engine.free_blocks
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    req = sched.submit((np.arange(40) % cfg.vocab_size).tolist(), max_new_tokens=8)

    sched.step()  # admits + prefills exactly one 16-token chunk
    assert req.state is RequestState.PREFILL and req._fed == 16
    assert engine.free_blocks < free0  # KV blocks held mid-prefill

    req.cancel()
    sched.step()
    assert req.state is RequestState.CANCELLED
    assert engine.free_blocks == free0  # blocks verifiably returned to the pool
    assert engine._state_manager.n_tracked_sequences == 0
    assert req.result(timeout=1) == []  # cancelled before any token
    sched.stop(drain=False)


def test_deadline_expiry_during_decode_frees_kv(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine()
    free0 = engine.free_blocks
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    req = sched.submit((np.arange(9) % cfg.vocab_size).tolist(),
                       max_new_tokens=1000, deadline_s=3600.0)

    _run_until(sched, lambda: req.state is RequestState.DECODE and len(req.tokens) >= 2)
    produced = list(req.tokens)
    req.deadline = time.monotonic() - 1.0  # the clock runs out mid-decode
    sched.step()
    assert req.state is RequestState.TIMED_OUT
    assert engine.free_blocks == free0
    assert req.result(timeout=1) == produced  # partial output survives the cut
    assert sched.stats()["counters"]["timed_out"] == 1
    sched.stop(drain=False)


def test_queued_request_past_deadline_never_touches_engine(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    req = sched.submit([1, 2, 3], max_new_tokens=4, deadline_s=0.001)
    time.sleep(0.01)
    sched.step()
    assert req.state is RequestState.TIMED_OUT and req.uid is None
    sched.stop(drain=False)


# -------------------------------------------------------------- backpressure --
def test_backpressure_reject_mode(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(queue_capacity=2), start=False)
    sched.submit([1], max_new_tokens=1)
    sched.submit([2], max_new_tokens=1)
    with pytest.raises(QueueFullError):
        sched.submit([3], max_new_tokens=1)
    assert sched.stats()["counters"]["rejected"] == 1
    sched.stop(drain=False)


def test_backpressure_block_mode_unblocks_on_admission(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(queue_capacity=1,
                                                   backpressure="block"), start=False)
    sched.submit([1, 2], max_new_tokens=1)
    admitted = []

    def blocked_submit():
        admitted.append(sched.submit([3, 4], max_new_tokens=1))

    t = threading.Thread(target=blocked_submit)
    t.start()
    time.sleep(0.15)
    assert t.is_alive() and not admitted  # genuinely blocked on the full queue
    sched.step()  # admission drains the queue -> submitter wakes
    t.join(timeout=10)
    assert not t.is_alive() and len(admitted) == 1
    _run_until(sched, lambda: all(r.finished for r in admitted) and sched.n_active == 0)
    sched.stop(drain=False)


# -------------------------------------------------- KV pressure and eviction --
def test_kv_pressure_evicts_and_restores_transparently(llama_setup, make_engine):
    """Two 64-token sequences fill an 8-block pool exactly; decode beyond the
    block boundary forces evict/restore alternation — outputs must equal the
    unconstrained run and all blocks must return to the pool."""
    cfg, _, _ = llama_setup
    rng = np.random.default_rng(3)
    pa = rng.integers(0, cfg.vocab_size, 64).tolist()
    pb = rng.integers(0, cfg.vocab_size, 64).tolist()

    engine = make_engine(num_blocks=8, block_size=16, max_context=128)
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    ra = sched.submit(pa, max_new_tokens=3)
    rb = sched.submit(pb, max_new_tokens=3)
    _run_until(sched, lambda: ra.finished and rb.finished)
    assert ra.state is RequestState.DONE and rb.state is RequestState.DONE
    assert sched.stats()["counters"]["evictions"] >= 2  # both directions thrashed
    assert engine.free_blocks == 8
    sched.stop(drain=False)

    assert ra.result() == _reference_greedy(llama_setup, pa, 3)
    assert rb.result() == _reference_greedy(llama_setup, pb, 3)


def test_prefill_chunk_shrinks_under_kv_pressure(make_engine, llama_setup):
    """A prompt larger than the free pool's worth of one chunk still prefills
    (halving), it just takes more ticks."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=4, block_size=16)  # 64-token pool
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    prompt = (np.arange(62) % cfg.vocab_size).tolist()
    req = sched.submit(prompt, max_new_tokens=2)
    _run_until(sched, lambda: req.finished)
    assert req.state is RequestState.DONE
    assert req.result() == _reference_greedy(llama_setup, prompt, 2)
    assert engine.free_blocks == 4
    sched.stop(drain=False)


def test_sampled_requests_are_reproducible_despite_cobatching(llama_setup, make_engine):
    """temperature>0 output depends only on (prompt, seed) — never on what
    else is in flight (each request owns a seeded host stream; the chunked
    device fast path is greedy-only)."""
    cfg, _, _ = llama_setup
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, cfg.vocab_size, 9).tolist()
    other = rng.integers(0, cfg.vocab_size, 14).tolist()

    def run(with_companion):
        engine = make_engine()
        sched = ServingScheduler(engine, ServingConfig(decode_chunk=4), start=False)
        req = sched.submit(prompt, max_new_tokens=5, temperature=1.0, seed=42)
        if with_companion:
            sched.submit(other, max_new_tokens=5, temperature=0.7, seed=7)
        _run_until(sched, lambda: req.finished)
        out = req.result()
        sched.stop(drain=False)
        return out

    assert run(with_companion=False) == run(with_companion=True)


# ------------------------------------------------------- infeasible requests --
def test_permanently_infeasible_requests_fail_fast(make_engine):
    engine = make_engine(num_blocks=4, block_size=16)  # 64-token pool, 512 ctx
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    too_long_ctx = sched.submit([1] * 600, max_new_tokens=1)   # > max_context
    too_many_blocks = sched.submit([1] * 100, max_new_tokens=1)  # 7 blocks > 4
    sched.step()
    assert too_long_ctx.state is RequestState.FAILED
    assert "max_context" in too_long_ctx.error
    assert too_many_blocks.state is RequestState.FAILED
    assert "KV blocks" in too_many_blocks.error
    with pytest.raises(RuntimeError, match="max_context"):
        too_long_ctx.result(timeout=1)
    sched.stop(drain=False)


def test_generate_wrapper_joins_attached_scheduler(llama_setup, make_engine):
    """generate() on an engine that is already serving routes through the live
    scheduler (requests join the batch mix) and leaves it running."""
    from deepspeed_tpu.inference.v2.engine_factory import generate
    cfg, _, _ = llama_setup
    engine = make_engine()
    prompt = (np.arange(8) % cfg.vocab_size).tolist()
    sched = ServingScheduler(engine, ServingConfig())
    try:
        out = generate(engine, [prompt], max_new_tokens=4)
        assert out[0] == _reference_greedy(llama_setup, prompt, 4)
        assert engine.serving_scheduler is sched  # still attached and running
        assert sched.stats()["counters"]["completed"] == 1
    finally:
        sched.stop(drain=False)


def test_generate_wrapper_raises_on_infeasible_prompt(make_engine):
    from deepspeed_tpu.inference.v2.engine_factory import generate
    engine = make_engine(num_blocks=4, block_size=16)
    with pytest.raises(RuntimeError, match="KV blocks"):
        generate(engine, [[1] * 100], max_new_tokens=2)
    assert engine.serving_scheduler is None  # wrapper detached its scheduler


def test_generate_on_shared_scheduler_cancels_orphans_on_error(make_engine):
    """A submit failure mid-generate() (queue full on the shared scheduler)
    must cancel the already-submitted requests — nobody will consume them."""
    from deepspeed_tpu.inference.v2.engine_factory import generate
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(queue_capacity=1), start=False)
    with pytest.raises(QueueFullError):
        generate(engine, [[1, 2], [3, 4], [5, 6]], max_new_tokens=4)
    sched.step()  # honors the cancel flags
    assert sched.n_active == 0 and sched.queue_depth == 0
    assert sched.stats()["counters"]["cancelled"] == 1
    sched.stop(drain=False)


def test_capacity_check_uses_pool_size_not_construction_free(make_engine, llama_setup):
    """A scheduler built while a warmup sequence holds blocks must still judge
    feasibility against the whole pool once that sequence is flushed."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=8, block_size=16)
    engine.put([999], [(np.arange(90) % cfg.vocab_size)])  # warmup holds 6 of 8 blocks
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    engine.flush(999)
    req = sched.submit((np.arange(100) % cfg.vocab_size).tolist(), max_new_tokens=2)
    _run_until(sched, lambda: req.finished)
    assert req.state is RequestState.DONE  # 7 blocks: fits the 8-block pool
    sched.stop(drain=False)


def test_chunked_decode_never_streams_past_max_context(make_engine, llama_setup):
    """The decode-loop fast path always runs K steps; near max_context it must
    fall back to single steps so no token beyond the window reaches a client."""
    cfg, _, _ = llama_setup
    engine = make_engine(max_context=32)
    sched = ServingScheduler(engine, ServingConfig(decode_chunk=4), start=False)
    req = sched.submit((np.arange(29) % cfg.vocab_size).tolist(), max_new_tokens=100)
    _run_until(sched, lambda: req.finished)
    assert req.state is RequestState.DONE and req.finish_reason == "context"
    assert len(req.tokens) == 32 - 29 + 1  # up to the window edge, not one past
    sched.stop(drain=False)


def test_context_window_exhaustion_is_a_clean_length_cut(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine(max_context=32)
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    req = sched.submit((np.arange(30) % cfg.vocab_size).tolist(), max_new_tokens=100)
    _run_until(sched, lambda: req.finished)
    assert req.state is RequestState.DONE
    assert req.finish_reason == "context"
    assert len(req.tokens) >= 1
    assert engine._state_manager.n_tracked_sequences == 0
    sched.stop(drain=False)


# ------------------------------------------------------------ stop and drain --
def test_stop_drains_in_flight_requests(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig())
    reqs = [sched.submit((np.arange(5 + i) % cfg.vocab_size).tolist(), max_new_tokens=3)
            for i in range(3)]
    sched.stop(drain=True, timeout=120)
    assert all(r.state is RequestState.DONE for r in reqs)
    assert sched.stats()["counters"]["completed"] == 3
    assert engine._state_manager.n_tracked_sequences == 0
    with pytest.raises(SchedulerStopped):
        sched.submit([1], max_new_tokens=1)


def test_stop_without_drain_cancels_everything(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    reqs = [sched.submit([1, 2], max_new_tokens=5) for _ in range(2)]
    sched.stop(drain=False)
    assert all(r.state is RequestState.CANCELLED for r in reqs)
    assert all(r.stream.closed for r in reqs)


def test_one_scheduler_per_engine(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    with pytest.raises(RuntimeError, match="already has an attached"):
        ServingScheduler(engine, ServingConfig(), start=False)
    sched.stop(drain=False)
    # detached on stop: a new scheduler may attach
    ServingScheduler(engine, ServingConfig(), start=False).stop(drain=False)


def test_engine_close_stops_scheduler_and_clears_tracer(llama_setup):
    """Satellite: close() must stop an attached scheduler AND deregister the
    module-global tracer so state cannot leak into the next engine."""
    import jax
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_factory import build_engine
    from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                                   DSStateManagerConfig,
                                                                   MemoryConfig)
    from deepspeed_tpu.inference.v2.tracer import get_tracer

    cfg, _, params = llama_setup

    def build(trace):
        mgr = DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=16),
            max_context=256)
        ec = RaggedInferenceEngineConfig(state_manager=mgr, kv_block_size=16)
        ec.trace_enabled = trace
        return build_engine(params, cfg, ec)

    e1 = build(trace=True)
    assert get_tracer() is e1.tracer
    sched = ServingScheduler(e1, ServingConfig())
    e1.close()
    assert e1.serving_scheduler is None and sched._stopped
    assert get_tracer() is None  # the leak this satellite fixes

    # a newer engine's tracer must survive an older engine's close()
    e1 = build(trace=True)
    e2 = build(trace=True)
    assert get_tracer() is e2.tracer
    e1.close()
    assert get_tracer() is e2.tracer
    e2.close()
    assert get_tracer() is None


# ---------------------------------------------------- telemetry and heartbeat --
def test_serving_metrics_zero_cost_when_disabled(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    req = sched.submit([1, 2, 3], max_new_tokens=2)
    _run_until(sched, lambda: req.finished)
    sched.stop(drain=False)
    assert telemetry.get_registry().api_calls == 0  # not one registry touch


def test_serving_metrics_record_when_enabled(make_engine):
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(), start=False)
    done = sched.submit([1, 2, 3, 4], max_new_tokens=3)
    _run_until(sched, lambda: done.finished)
    with pytest.raises(QueueFullError):
        # drop capacity so the reject counter fires too
        sched._config = sched._config.model_copy(update={"queue_capacity": 0})
        sched.submit([1], max_new_tokens=1)
    sched.stop(drain=False)

    snap = telemetry.get_registry().snapshot()
    assert snap["serving_completions_total"][0][1] == 1
    assert snap["serving_rejections_total"][0][1] == 1
    assert snap["serving_ttft_seconds_count"][0][1] == 1
    assert snap["serving_inter_token_seconds_count"][0][1] == 2  # 3 tokens -> 2 gaps
    assert snap["serving_e2e_latency_seconds_count"][0][1] == 1


def test_idle_heartbeat_runs_empty_batches(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(heartbeat_enabled=True,
                                                   heartbeat_interval_s=0.0))
    try:
        deadline = time.monotonic() + 30
        while sched.stats()["counters"]["heartbeats"] < 2:
            assert time.monotonic() < deadline, "no heartbeat within 30s"
            time.sleep(0.01)
    finally:
        sched.stop(drain=False)


# ------------------------------------------------------- kill + readiness --
def test_kill_fails_everything_terminal_and_frees_kv(make_engine, llama_setup):
    """The abrupt-death disposition (fleet fault tolerance): every queued and
    in-flight request ends FAILED with the 'replica killed' marker, streams
    close, KV returns to the pool — what the router and the supervisor key
    their recovery on."""
    from deepspeed_tpu.serving.scheduler import KILLED_ERROR_PREFIX
    cfg, _, _ = llama_setup
    engine = make_engine()
    free0 = engine.free_blocks
    sched = ServingScheduler(engine, ServingConfig())
    active = sched.submit((np.arange(9) % cfg.vocab_size).tolist(),
                          max_new_tokens=500)
    deadline = time.monotonic() + 60
    while active.first_token_s is None:  # mid-decode, KV held
        assert time.monotonic() < deadline
        time.sleep(0.005)
    queued = sched.submit([1, 2, 3], max_new_tokens=5)
    sched.kill("injected fault")
    for req in (active, queued):
        assert req.state is RequestState.FAILED
        assert req.error.startswith(KILLED_ERROR_PREFIX)
        assert req.stream.closed
    assert engine._state_manager.n_tracked_sequences == 0
    assert engine.free_blocks == free0
    assert not sched.ready
    with pytest.raises(SchedulerStopped):
        sched.submit([1], max_new_tokens=1)
    sched.kill()            # idempotent
    sched.stop(drain=False)  # and stop() after kill() is a no-op


def test_ready_gates_on_the_loop_ticking(make_engine):
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig())
    deadline = time.monotonic() + 30
    while not sched.ready:
        assert time.monotonic() < deadline, "scheduler never became ready"
        time.sleep(0.001)
    sched.stop(drain=False)
    assert not sched.ready  # a stopped scheduler is not dispatchable
    # a manually-driven scheduler (start=False) is ready by construction
    engine2 = make_engine()
    manual = ServingScheduler(engine2, ServingConfig(), start=False)
    assert manual.ready
    manual.stop(drain=False)
