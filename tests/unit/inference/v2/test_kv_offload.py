"""KV-cache host offload/restore (reference inference/v2/ragged/kv_cache.py:166
offload / :176 restore — declared there, unimplemented; the ZeRO-Inference
KV-offload leg of BASELINE.md depends on them)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import build_engine
from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               KVCacheConfig, MemoryConfig)
from deepspeed_tpu.inference.v2.scheduling_utils import SchedulingError
from deepspeed_tpu.models.llama import LlamaConfig, init_params

BS = 16


def _cache(num_blocks=8, offload_path=None):
    return BlockedKVCache(
        KVCacheConfig(block_size=BS, cache_shape=(2, 2, 8), cache_dtype="float32"),
        MemoryConfig(mode=AllocationMode.ALLOCATE, size=num_blocks),
        offload_path=offload_path)


@pytest.mark.parametrize("nvme", [False, True])
def test_block_offload_restore_roundtrip(tmp_path, nvme):
    """Offload frees the device blocks; restore returns FRESH ids holding the
    exact contents; other blocks are untouched."""
    kv = _cache(offload_path=str(tmp_path) if nvme else None)
    ids = kv.reserve(3)
    rng = np.random.default_rng(0)
    payload = rng.normal(size=(2, 2, 3, 2, BS, 8)).astype(np.float32)
    kv.set_cache(kv.cache.at[:, :, jnp.asarray(ids)].set(jnp.asarray(payload)))
    other = kv.reserve(2)
    sentinel = np.full((2, 2, 2, 2, BS, 8), 7.0, np.float32)
    kv.set_cache(kv.cache.at[:, :, jnp.asarray(other)].set(jnp.asarray(sentinel)))

    free_before = kv.free_blocks
    h = kv.offload(ids)
    assert kv.free_blocks == free_before + 3
    # freed blocks are reusable while the payload lives on host
    squatter = kv.reserve(3)
    kv.set_cache(kv.cache.at[:, :, jnp.asarray(squatter)].set(-1.0))
    kv.free(squatter)

    new_ids = kv.restore(h)
    assert len(new_ids) == 3
    got = np.asarray(kv.cache[:, :, jnp.asarray(new_ids)])
    np.testing.assert_array_equal(got, payload)
    np.testing.assert_array_equal(np.asarray(kv.cache[:, :, jnp.asarray(other)]), sentinel)
    with pytest.raises(KeyError):
        kv.restore(h)  # single-shot handle


def test_restore_failure_keeps_payload(tmp_path):
    kv = _cache(num_blocks=4)
    ids = kv.reserve(3)
    h = kv.offload(ids)
    blocker = kv.reserve(3)  # leaves 1 free — restore needs 3
    with pytest.raises(ValueError):
        kv.restore(h)
    kv.free(blocker)
    assert len(kv.restore(h)) == 3  # payload survived the failed attempt


def _engine(params, cfg, num_blocks, **mgr_kw):
    mgr = DSStateManagerConfig(memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE,
                                                          size=num_blocks),
                               max_context=256, **mgr_kw)
    return build_engine(params, cfg, RaggedInferenceEngineConfig(state_manager=mgr,
                                                                 kv_block_size=BS))


@pytest.fixture(scope="module")
def llama():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    _, params = init_params(cfg)
    return cfg, params


def test_engine_eviction_choreography(llama):
    """Fill PAST the device block budget by offloading cold sequences; touch
    restores transparently; logits identical to an engine that never evicted."""
    cfg, params = llama
    rng = np.random.default_rng(1)
    A = rng.integers(0, cfg.vocab_size, 40)   # 3 blocks
    B = rng.integers(0, cfg.vocab_size, 40)   # 3 blocks
    C = rng.integers(0, cfg.vocab_size, 40)   # 3 blocks — total 9 > 8 budget
    tok = np.asarray([5])

    # baseline: big engine, no eviction
    big = _engine(params, cfg, num_blocks=64)
    big.put([0], [A]); big.put([1], [B]); big.put([2], [C])
    want_a = np.asarray(big.put([0], [tok]))
    want_b = np.asarray(big.put([1], [tok]))

    small = _engine(params, cfg, num_blocks=8)
    small.put([0], [A])
    small.put([1], [B])                      # 6/8 blocks live
    with pytest.raises(SchedulingError):
        small.put([2], [C])                  # C does NOT fit
    small.offload_sequence(0)                # evict cold A -> 3 free + ...
    assert small.is_offloaded(0)
    small.put([2], [C])                      # now it does
    small.offload_sequence(2)                # make room to touch A again
    got_a = np.asarray(small.put([0], [tok]))  # restore-on-touch
    assert not small.is_offloaded(0)
    np.testing.assert_allclose(got_a, want_a, rtol=2e-5, atol=2e-5)
    small.offload_sequence(0)
    got_b = np.asarray(small.put([1], [tok]))
    np.testing.assert_allclose(got_b, want_b, rtol=2e-5, atol=2e-5)


def test_decode_loop_after_restore(llama):
    """Device-loop generation continues correctly from restored KV."""
    cfg, params = llama
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, 33)

    ref = _engine(params, cfg, num_blocks=64)
    first = int(np.argmax(np.asarray(ref.put([0], [prompt]))[0]))
    want = ref.decode_loop([0], [np.array([first])], 5)

    eng = _engine(params, cfg, num_blocks=64)
    first2 = int(np.argmax(np.asarray(eng.put([0], [prompt]))[0]))
    assert first2 == first
    eng.offload_sequence(0)
    got = eng.decode_loop([0], [np.array([first])], 5)  # restores, then scans
    np.testing.assert_array_equal(got, want)


def test_flush_drops_offloaded_payload(tmp_path, llama):
    cfg, params = llama
    eng = _engine(params, cfg, num_blocks=8, offload_path=str(tmp_path))
    eng.put([0], [np.arange(20) % cfg.vocab_size])
    eng.offload_sequence(0)
    files = list(tmp_path.glob("kv_offload_*.bin"))
    assert files, "NVMe spill file must exist while offloaded"
    eng.flush(0)
    assert not list(tmp_path.glob("kv_offload_*.bin"))
    assert eng.free_blocks == 8


def test_admission_counts_restore_cost(llama):
    """can_schedule must treat an offloaded sequence's blocks as needing
    re-allocation: admission fails with a SchedulingError, never a raw
    allocator crash mid-restore (regression)."""
    from deepspeed_tpu.inference.v2.scheduling_utils import SchedulingResult

    cfg, params = llama
    eng = _engine(params, cfg, num_blocks=8)
    rng = np.random.default_rng(5)
    eng.put([0], [rng.integers(0, cfg.vocab_size, 40)])  # 3 blocks
    eng.offload_sequence(0)
    eng.put([1], [rng.integers(0, cfg.vocab_size, 100)])  # 7 blocks -> 1 free
    # touching uid 0 needs 3 restored blocks but only 1 is free
    assert eng.can_schedule([0], [1]) == SchedulingResult.KVCacheLimitExceeded
    with pytest.raises(SchedulingError):
        eng.put([0], [np.array([3])])
    assert eng.is_offloaded(0)  # payload untouched by the rejected admission
    eng.flush(1)
    got = eng.put([0], [np.array([3])])  # now restores and runs
    assert got.shape == (1, cfg.vocab_size)
