"""Native async-IO engine (reference tests/unit/ops/aio/test_aio.py role)."""

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AsyncIOHandle, aio_available
from deepspeed_tpu.ops.op_builder import ALL_OPS, get_op_builder


def test_builder_registry_and_compat():
    assert "async_io" in ALL_OPS
    b = get_op_builder("async_io")
    # the image ships g++, so the native path must actually be available
    assert b.is_compatible(), b.error_log


def test_native_lib_loads():
    assert aio_available()


@pytest.mark.parametrize("dtype", [np.float32, np.int8])
def test_roundtrip(tmp_path, dtype):
    h = AsyncIOHandle(thread_count=2, queue_depth=4)
    src = (np.arange(4096) % 251).astype(dtype)
    dst = np.zeros_like(src)
    p = str(tmp_path / "buf.bin")
    wid = h.async_pwrite(src, p)
    assert h.wait(wid) == src.nbytes
    rid = h.async_pread(dst, p)
    assert h.wait(rid) == src.nbytes
    np.testing.assert_array_equal(src, dst)
    h.close()


def test_many_overlapping_requests(tmp_path):
    """More requests than queue depth: the bounded queue must not deadlock and
    every buffer must land intact."""
    h = AsyncIOHandle(thread_count=4, queue_depth=2)
    n = 16
    bufs = [np.full(1024, i, np.float32) for i in range(n)]
    paths = [str(tmp_path / f"f{i}.bin") for i in range(n)]
    for b, p in zip(bufs, paths):
        h.async_pwrite(b, p)
    h.wait_all()
    outs = [np.zeros(1024, np.float32) for _ in range(n)]
    ids = [h.async_pread(o, p) for o, p in zip(outs, paths)]
    for rid in ids:
        h.wait(rid)
    for i, o in enumerate(outs):
        assert (o == i).all()
    h.close()


def test_offset_io(tmp_path):
    h = AsyncIOHandle(thread_count=1)
    p = str(tmp_path / "off.bin")
    a = np.arange(256, dtype=np.float64)
    h.sync_pwrite(a, p)
    tail = np.zeros(128, np.float64)
    h.sync_pread(tail, p, offset=128 * 8)
    np.testing.assert_array_equal(tail, a[128:])
    h.close()


def test_read_error_surfaces(tmp_path):
    h = AsyncIOHandle(thread_count=1)
    buf = np.zeros(16, np.float32)
    rid = h.async_pread(buf, str(tmp_path / "missing.bin"))
    with pytest.raises(OSError):
        h.wait(rid)
    h.close()


def test_py_fallback_concurrent_first_writes_no_truncation(tmp_path, monkeypatch):
    """Python fallback: concurrent writes to a NEW file must not truncate each
    other (regression: exists-check + 'wb' raced, zeroing the earlier shard)."""
    from deepspeed_tpu.ops.aio import aio_op

    monkeypatch.setattr(aio_op, "_LIB", None)
    monkeypatch.setattr(aio_op, "_LIB_TRIED", True)
    for trial in range(5):  # several trials to give a race a chance
        p = str(tmp_path / f"fresh_{trial}.bin")
        h = AsyncIOHandle(thread_count=8)
        assert h._handle is None and h._pool is not None  # really the fallback
        shards = [np.full(4096, i, dtype=np.float32) for i in range(8)]
        ids = [h.async_pwrite(s, p, offset=i * s.nbytes) for i, s in enumerate(shards)]
        for rid in ids:
            assert h.wait(rid) == shards[0].nbytes
        out = np.zeros(8 * 4096, np.float32)
        h.sync_pread(out, p)
        h.close()
        for i in range(8):
            assert (out[i * 4096:(i + 1) * 4096] == i).all(), f"shard {i} corrupted"


def test_py_fallback_short_read_reports_bytes(tmp_path, monkeypatch):
    from deepspeed_tpu.ops.aio import aio_op

    monkeypatch.setattr(aio_op, "_LIB", None)
    monkeypatch.setattr(aio_op, "_LIB_TRIED", True)
    h = AsyncIOHandle(thread_count=1)
    p = str(tmp_path / "small.bin")
    src = np.arange(16, dtype=np.float32)
    h.sync_pwrite(src, p)
    big = np.zeros(64, np.float32)
    assert h.sync_pread(big, p) == src.nbytes  # EOF -> short read, not a hang
    np.testing.assert_array_equal(big[:16], src)
    h.close()
