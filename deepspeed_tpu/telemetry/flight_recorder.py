"""Crash flight recorder: a signal-, atexit-, and watchdog-triggered
black-box dump.

When a serving process hangs or dies, the operator's first question is "what
was the scheduler doing?" — and the answer must not require the process to be
healthy enough to serve ``/metrics``. The recorder keeps everything needed for
a post-mortem in memory and dumps it as one parseable JSON file on demand:

- the last-N spans (with trace ids, so the dump joins against request traces),
- the registry's recent JSONL events and a full metrics snapshot,
- every registered *state provider*'s live view (the serving scheduler
  registers queue depths, per-request states and KV occupancy).

Triggers:

- ``SIGUSR1`` (``kill -USR1 <pid>``) — dump without stopping the process;
- ``dump()`` — the API trigger (also exposed as ``GET /flight`` on the
  telemetry HTTP endpoint);
- ``atexit`` (opt-in ``dump_on_exit``) — a last snapshot on interpreter exit;
- the **watchdog** — components under watch call ``heartbeat(name)`` from
  their progress loop; a watchdog thread fires one dump per stall episode
  when a heartbeat goes stale past ``watchdog_stall_s`` and, for the serving
  scheduler channel, increments the ``serving_stalled_total`` metric.

Dumps are written atomically (tmp + rename) to ``config.dir`` with the pid,
a sequence number and the trigger in the filename.
"""

import atexit
import json
import os
import signal
import threading
import time

from deepspeed_tpu.utils.logging import logger

# heartbeat-channel prefix the serving scheduler registers under (one channel
# per scheduler instance, e.g. "serving_scheduler:0"); the watchdog maps a
# stall on any such channel to the serving_stalled_total metric
SERVING_SCHEDULER_CHANNEL = "serving_scheduler"

METRIC_NAMES = ("flight_recorder_dumps_total", "serving_stalled_total")


class FlightRecorder:

    def __init__(self, config, registry, spans=None):
        self._config = config
        self._registry = registry
        self._spans = spans
        self._lock = threading.Lock()
        self._providers = {}          # name -> callable() -> JSON-able state
        self._heartbeats = {}         # name -> (last beat monotonic s, owner thread ident)
        self._stalled = set()         # channels already dumped this episode
        self._dump_seq = 0
        self._dump_metrics = {}       # trigger -> counter
        self._stall_counter = registry.counter(
            "serving_stalled_total",
            "Watchdog detections of a stalled serving scheduler loop")
        self._prev_sigusr1 = None
        self._atexit_hook = None
        self._watchdog = None
        self._watchdog_stop = threading.Event()
        self._closed = False

    # -------------------------------------------------------------- install --
    def install(self):
        """Arm the signal/atexit/watchdog triggers (idempotent-safe to skip
        pieces that cannot arm: SIGUSR1 needs the main thread)."""
        if self._config.signal_enabled:
            try:
                self._prev_sigusr1 = signal.signal(signal.SIGUSR1, self._on_signal)
            except ValueError:  # not the main thread: API/watchdog still work
                logger.warning("flight recorder: SIGUSR1 handler needs the main "
                               "thread; signal trigger disabled")
        if self._config.dump_on_exit:
            self._atexit_hook = lambda: self._safe_dump("atexit")
            atexit.register(self._atexit_hook)
        if self._config.watchdog_enabled:
            self._watchdog = threading.Thread(target=self._watchdog_loop,
                                              name="dstpu-flight-watchdog",
                                              daemon=True)
            self._watchdog.start()
        return self

    def close(self):
        """Disarm every trigger and restore the previous SIGUSR1 handler."""
        if self._closed:
            return
        self._closed = True
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5.0)
            self._watchdog = None
        if self._prev_sigusr1 is not None:
            try:
                # restore only if the handler is still OURS: a newer recorder
                # may have installed over us, and stomping its live handler
                # with our (possibly SIG_DFL) predecessor would turn the
                # documented `kill -USR1` dump into process termination
                if signal.getsignal(signal.SIGUSR1) == self._on_signal:
                    signal.signal(signal.SIGUSR1, self._prev_sigusr1)
            except ValueError:  # pragma: no cover - non-main-thread close
                pass
            self._prev_sigusr1 = None
        if self._atexit_hook is not None:
            atexit.unregister(self._atexit_hook)
            self._atexit_hook = None

    # ------------------------------------------------------------ providers --
    def register_provider(self, name, fn):
        """Register a live-state callable included in every dump under
        ``state[name]`` (the serving scheduler registers its queue/request/KV
        view here). Re-registering a name replaces it."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name):
        with self._lock:
            self._providers.pop(name, None)

    # ------------------------------------------------------------ heartbeats --
    def watch_heartbeat(self, name):
        """Put ``name`` under watchdog watch; the owner must now call
        ``heartbeat(name)`` at least every ``watchdog_stall_s`` seconds."""
        with self._lock:
            self._heartbeats[name] = (time.monotonic(), None)
            self._stalled.discard(name)

    def unwatch_heartbeat(self, name):
        with self._lock:
            self._heartbeats.pop(name, None)
            self._stalled.discard(name)

    def heartbeat(self, name):
        """Record liveness (called from the owner's progress loop; the
        calling thread is remembered so the watchdog attributes in-compile
        amnesty to this loop's thread, not to any watched call anywhere)."""
        self._heartbeats[name] = (time.monotonic(), threading.get_ident())

    @staticmethod
    def _in_wrapped_engine_call(thread_ident=None) -> bool:
        from deepspeed_tpu.telemetry import compile_watch
        watch = compile_watch.get()
        return watch is not None and watch.in_wrapped_call(thread_ident)

    def _watchdog_loop(self):
        poll = max(0.01, self._config.watchdog_poll_s)
        stall = self._config.watchdog_stall_s
        hard = max(stall, self._config.watchdog_hard_stall_s)
        while not self._watchdog_stop.wait(poll):
            now = time.monotonic()
            with self._lock:
                beats = dict(self._heartbeats)
            for name, (last, ident) in beats.items():
                age = now - last
                if age <= stall:
                    with self._lock:
                        self._stalled.discard(name)  # episode over: re-arm
                    continue
                # a loop blocked inside a (long) XLA compile is busy, not
                # wedged — grant ITS thread the hard-stall budget before
                # declaring it (a channel that never heartbeat carries no
                # owner and falls back to any-thread occupancy)
                if age <= hard and self._in_wrapped_engine_call(ident):
                    continue
                with self._lock:
                    # re-check under the lock: a concurrent unwatch_heartbeat
                    # (scheduler stop) must not get a dump re-added for it
                    if name not in self._heartbeats or name in self._stalled:
                        continue
                    self._stalled.add(name)          # one dump per stall episode
                if name.split(":", 1)[0] == SERVING_SCHEDULER_CHANNEL:
                    self._stall_counter.inc()
                logger.error(f"flight recorder: heartbeat '{name}' stale for "
                             f"{age:.1f}s (> {stall}s); dumping")
                self._safe_dump(f"watchdog_{name.split(':', 1)[0]}")

    # ----------------------------------------------------------------- dump --
    def _on_signal(self, signum, frame):
        # the handler runs on the main thread between bytecodes — dumping
        # inline would self-deadlock on self._lock if the interrupted code
        # holds it (register_provider, an API dump); a worker thread just
        # waits its turn
        threading.Thread(target=self._safe_dump, args=("sigusr1", ),
                         name="dstpu-flight-sigusr1", daemon=True).start()

    def _safe_dump(self, trigger):
        try:
            return self.dump(trigger)
        except Exception:  # pragma: no cover - a failing dump must never take
            # down the process it is meant to post-mortem
            logger.exception("flight recorder: dump failed")
            return None

    def dump(self, trigger="api", return_doc=False):
        """Write one black-box JSON dump; returns its path — or
        ``(path, doc)`` with ``return_doc`` so callers serving the dump over
        HTTP need not re-read and re-parse the file just written."""
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
            providers = dict(self._providers)
            beats = dict(self._heartbeats)
        doc = {
            "meta": {"version": 1, "ts": time.time(), "pid": os.getpid(),
                     "trigger": trigger, "seq": seq},
            "heartbeats_age_s": {name: time.monotonic() - last
                                 for name, (last, _) in beats.items()},
            "spans": (self._spans.tail(self._config.max_spans)
                      if self._spans is not None else []),
            "spans_dropped": (self._spans.dropped
                              if self._spans is not None else 0),
            "events": self._registry.recent_events_snapshot(),
            "metrics": self._registry.snapshot(),
            "state": {},
        }
        for name, fn in providers.items():
            try:
                doc["state"][name] = fn()
            except Exception as e:  # a wedged provider must not block the dump
                doc["state"][name] = {"error": f"provider raised: {e!r}"}
        out_dir = os.path.abspath(self._config.dir)
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"flight_{os.getpid()}_{seq:04d}_{trigger}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        counter = self._dump_metrics.get(trigger)
        if counter is None:
            counter = self._registry.counter("flight_recorder_dumps_total",
                                             "Flight-recorder dumps written",
                                             labels={"trigger": trigger})
            self._dump_metrics[trigger] = counter
        counter.inc()
        logger.info(f"flight recorder: wrote {path} ({trigger})")
        return (path, doc) if return_doc else path
