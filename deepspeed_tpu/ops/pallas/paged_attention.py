"""Pallas paged (blocked) attention over the ragged KV cache.

Reference role: ``deepspeed/inference/v2/kernels/ragged_ops/blocked_flash/
blocked_flash.cpp:101`` + ``blocked_kv_rotary.cu:385`` (the KV insert) —
attention that walks each sequence's block table instead of densifying
history, so decode cost scales with *live* tokens, not the padded table
width (VERDICT r2 weak #4).

TPU design, one fused kernel per layer:

- the paged cache is ALIASED in/out of the kernel (``input_output_aliases``)
  and updated in place — an XLA-side scatter would force the multi-GB cache
  to round-trip HBM at every pallas boundary (measured 74 ms/step for a 2 GB
  cache vs 0.2 ms with in-kernel insert);
- grid over the (bucket-padded) token dim, sequentially executed: program t
  first DMAs its own new K/V tile into its sequence's block (so later tokens
  of the same prefill read it), then walks the block table in CHUNKS of 8
  blocks — 16 outstanding async DMAs double-buffered against the previous
  chunk's online-softmax update;
- a chunk's 8 ``[KVH, bs, D]`` tiles form a 128-lane ``[KVH, rep, 8*bs]``
  logits tile — one VPU-native softmax step per chunk. Padding tokens have
  zero blocks and skip everything; HBM traffic per token is its sequence's
  live KV bytes, never the bucket ceiling.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
CHUNK = 8  # KV blocks fetched per loop iteration


def _kernel(li, S, MB, bs, rep, scale,
            # scalar prefetch
            table_ref, seq_ref, pos_ref, valid_ref,
            # inputs
            q_ref, kn_ref, vn_ref, cache_ref,
            # outputs
            out_ref, cache_out_ref,
            # scratch
            k_buf, v_buf, kv_stage, sems, wsem):
    t = pl.program_id(0)
    seq = jnp.minimum(seq_ref[t], S - 1)
    pos = pos_ref[t]
    valid = valid_ref[t] > 0
    nblocks = jnp.where(valid, jnp.minimum(pos // bs + 1, MB), 0)
    nchunks = pl.cdiv(nblocks, CHUNK)

    KVH, _, D = k_buf.shape[2:]
    q = q_ref[0].reshape(KVH, rep, D).astype(jnp.float32) * scale

    # ---- insert this token's K/V into its block (reference blocked_kv_rotary).
    # Full-block read-modify-write: Mosaic only DMAs contiguous tiles, and one
    # [KVH, bs, D] block round-trip per token is noise next to the table walk.
    own_bid = jnp.maximum(table_ref[seq, jnp.minimum(pos // bs, MB - 1)], 0)
    off = pos % bs

    @pl.when(valid)
    def _():
        ck = pltpu.make_async_copy(cache_out_ref.at[li, 0, own_bid], kv_stage.at[0],
                                   wsem.at[0])
        cv = pltpu.make_async_copy(cache_out_ref.at[li, 1, own_bid], kv_stage.at[1],
                                   wsem.at[1])
        ck.start()
        cv.start()
        ck.wait()
        cv.wait()
        # masked whole-block select: dynamic sublane stores need 8-alignment
        # Mosaic can't prove, a lane-wise where needs nothing
        row = jax.lax.broadcasted_iota(jnp.int32, (KVH, bs, 1), 1)
        kv_stage[0] = jnp.where(row == off, kn_ref[0][:, None, :], kv_stage[0])
        kv_stage[1] = jnp.where(row == off, vn_ref[0][:, None, :], kv_stage[1])
        wk = pltpu.make_async_copy(kv_stage.at[0], cache_out_ref.at[li, 0, own_bid],
                                   wsem.at[0])
        wv = pltpu.make_async_copy(kv_stage.at[1], cache_out_ref.at[li, 1, own_bid],
                                   wsem.at[1])
        wk.start()
        wv.start()
        wk.wait()
        wv.wait()

    # ---- walk the block table, double-buffered chunks ------------------------
    def chunk_copies(c, slot):
        copies = []
        for j in range(CHUNK):
            b = jnp.minimum(c * CHUNK + j, MB - 1)
            bid = jnp.maximum(table_ref[seq, b], 0)
            copies.append(pltpu.make_async_copy(cache_out_ref.at[li, 0, bid],
                                                k_buf.at[slot, j], sems.at[0, slot, j]))
            copies.append(pltpu.make_async_copy(cache_out_ref.at[li, 1, bid],
                                                v_buf.at[slot, j], sems.at[1, slot, j]))
        return copies

    @pl.when(nchunks > 0)
    def _():
        for cp in chunk_copies(0, 0):
            cp.start()

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nchunks)
        def _():
            for cp in chunk_copies(c + 1, jax.lax.rem(c + 1, 2)):
                cp.start()

        for cp in chunk_copies(c, slot):
            cp.wait()
        logit_parts = []
        v_parts = []
        for j in range(CHUNK):
            k = k_buf[slot, j].astype(jnp.float32)  # [KVH, bs, D]
            logit_parts.append(jax.lax.dot_general(
                q, k, (((2, ), (2, )), ((0, ), (0, ))),
                preferred_element_type=jnp.float32))  # [KVH, rep, bs]
            v_parts.append(v_buf[slot, j].astype(jnp.float32))
        logits = jnp.concatenate(logit_parts, axis=-1)       # [KVH, rep, CHUNK*bs]
        v = jnp.concatenate(v_parts, axis=1)                 # [KVH, CHUNK*bs, D]

        kv_pos = c * (CHUNK * bs) + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, CHUNK * bs), 2)
        mask = kv_pos <= pos
        logits = jnp.where(mask, logits, NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(mask, jnp.exp(logits - m_new[..., None]), 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        pv = jax.lax.dot_general(p, v, (((2, ), (1, )), ((0, ), (0, ))),
                                 preferred_element_type=jnp.float32)  # [KVH, rep, D]
        return m_new, l_new, acc * alpha[..., None] + pv

    m0 = jnp.full((KVH, rep), NEG_INF, jnp.float32)
    l0 = jnp.zeros((KVH, rep), jnp.float32)
    acc0 = jnp.zeros((KVH, rep, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nchunks, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out = jnp.where(valid, out, 0.0)
    out_ref[0] = out.reshape(1, KVH * rep, D).astype(out_ref.dtype)[0]


@functools.partial(jax.jit, static_argnames=("layer_idx", "interpret"), donate_argnums=(3, ))
def paged_attention_update(q, k_new, v_new, cache, layer_idx, block_table, token_seq,
                           token_pos, token_valid, interpret=None):
    """Fused KV-insert + blocked attention for one layer.

    q: [T, H, D]; k_new/v_new: [T, KVH, D]; cache: [L, 2, NB, KVH, bs, D]
    (donated; updated in place). Returns (attn_out [T, H, D], cache)."""
    T, H, D = q.shape
    L, _, NB, KVH, bs, Dc = cache.shape
    assert D == Dc and H % KVH == 0
    S, MB = block_table.shape
    rep = H // KVH
    scale = 1.0 / (D**0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(T, ),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda t, *_: (t, 0, 0)),
            pl.BlockSpec((1, KVH, D), lambda t, *_: (t, 0, 0)),
            pl.BlockSpec((1, KVH, D), lambda t, *_: (t, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),  # cache in HBM, aliased in/out
        ],
        out_specs=[
            pl.BlockSpec((1, H, D), lambda t, *_: (t, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, CHUNK, KVH, bs, D), cache.dtype),
            pltpu.VMEM((2, CHUNK, KVH, bs, D), cache.dtype),
            pltpu.VMEM((2, KVH, bs, D), cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2, CHUNK)),
            pltpu.SemaphoreType.DMA((2, )),
        ],
    )
    kernel = functools.partial(_kernel, layer_idx, S, MB, bs, rep, scale)
    out, new_cache = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((T, H, D), q.dtype),
                   jax.ShapeDtypeStruct(cache.shape, cache.dtype)],
        input_output_aliases={7: 1},  # cache operand (after 4 scalar-prefetch args)
        interpret=interpret,
    )(block_table.astype(jnp.int32), token_seq.astype(jnp.int32),
      token_pos.astype(jnp.int32), token_valid.astype(jnp.int32),
      q, k_new.astype(cache.dtype), v_new.astype(cache.dtype), cache)
    return out, new_cache
