"""KV block allocator.

Reference: ``deepspeed/inference/v2/ragged/blocked_allocator.py`` (BlockedAllocator:11
— a free-list over torch tensors). Pure host logic; numpy-backed here.

Blocks are **reference counted** so the prefix cache (``prefix_cache.py``) can
share one physical block between the radix trie and any number of live
sequences: ``allocate`` hands out blocks at refcount 1, ``incref`` adds a
sharer, and ``free`` is a *decref* — the block returns to the free list only
when its last reference drops. Unshared blocks behave exactly as before
(allocate → refcount 1 → one ``free`` releases), so non-caching callers never
see the mechanism; double-frees, which the old allocator silently corrupted
the free list with, now raise.
"""

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"Blocked allocator requires at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        # free-list as a linked list in an array: _next[i] = next free after i
        self._next = np.arange(1, num_blocks + 1, dtype=np.int64)
        self._head = 0
        self._free_blocks = num_blocks
        self._refs = np.zeros(num_blocks, dtype=np.int64)

    @property
    def free_blocks(self) -> int:
        return self._free_blocks

    def allocate(self, num_blocks: int) -> np.ndarray:
        if num_blocks > self._free_blocks:
            raise ValueError(f"Allocator has {self._free_blocks} free blocks, but {num_blocks} were requested")
        out = np.empty(num_blocks, dtype=np.int64)
        for i in range(num_blocks):
            out[i] = self._head
            self._refs[self._head] = 1
            self._head = int(self._next[self._head])
        self._free_blocks -= num_blocks
        return out

    def free(self, blocks) -> None:
        """Drop one reference per listed block; a block whose count reaches
        zero returns to the free list. Freeing an already-free block raises
        (double-free would otherwise cycle the free list and hand the same
        block to two sequences)."""
        blocks = np.atleast_1d(np.asarray(blocks, dtype=np.int64))
        for b in blocks:
            b = int(b)
            self._check_range(b)
            if self._refs[b] <= 0:
                raise ValueError(f"Block {b} freed more times than it was referenced "
                                 f"(double free)")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._next[b] = self._head
                self._head = b
                self._free_blocks += 1

    def incref(self, blocks) -> None:
        """Add one reference per listed block (the prefix-cache share path).
        Only live blocks can gain sharers — increffing a free block would
        resurrect memory another allocation is about to claim."""
        blocks = np.atleast_1d(np.asarray(blocks, dtype=np.int64))
        for b in blocks:
            b = int(b)
            self._check_range(b)
            if self._refs[b] <= 0:
                raise ValueError(f"Block {b} is not allocated; cannot incref")
        for b in blocks:
            self._refs[int(b)] += 1

    def ref_count(self, block: int) -> int:
        block = int(block)
        self._check_range(block)
        return int(self._refs[block])

    def _check_range(self, b: int) -> None:
        if b < 0 or b >= self._num_blocks:
            raise ValueError(f"Block {b} is out of range [0, {self._num_blocks})")
