"""Pipeline engine tests (reference: tests/unit/runtime/pipe/test_pipe.py —
pipeline+DP training must match non-pipelined training)."""

import flax.linen as nn
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule
from deepspeed_tpu.utils import groups

HIDDEN = 16


class InProj(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(HIDDEN)(x)


class Block(nn.Module):
    @nn.compact
    def __call__(self, x):
        return x + nn.tanh(nn.Dense(HIDDEN)(x))


class OutProj(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(1)(x)


def mse(out, labels):
    return jnp.mean((out.squeeze(-1) - labels)**2)


def _batches(n, bs, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(HIDDEN, )).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.normal(size=(bs, HIDDEN)).astype(np.float32)
        out.append((x, (x @ w).astype(np.float32)))
    return out


def _pipe_module(n_blocks=4, num_stages=2):
    layers = [LayerSpec(InProj)] + [LayerSpec(Block) for _ in range(n_blocks)] + [LayerSpec(OutProj)]
    return PipelineModule(layers=layers, num_stages=num_stages, loss_fn=mse)


def _cfg(gas=4, micro=2):
    return {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
    }


@pytest.mark.parametrize("num_stages", [2, 4])
def test_pipeline_trains(num_stages):
    groups.initialize_mesh(pipe_parallel_size=num_stages, force=True)
    module = _pipe_module(num_stages=num_stages)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=_cfg(),
                                               example_batch=example)
    dp = 8 // num_stages
    bs = 2 * 4 * dp  # micro * gas * dp = global batch rows
    losses = []
    for b in _batches(10, bs):
        losses.append(float(engine.train_batch(batch=b)))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_pipeline_matches_sequential():
    """P=2 pipeline == the same stack run unpipelined (same init, same data)."""
    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = _pipe_module(n_blocks=4, num_stages=2)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=_cfg(gas=4, micro=2),
                                               example_batch=example, rng_seed=7)
    p0 = jax.device_get(engine.params)  # snapshot before training
    BS = 2 * 4 * 4  # micro * gas * dp
    pipe_losses = [float(engine.train_batch(batch=b)) for b in _batches(5, BS)]
    layers = [InProj()] + [Block() for _ in range(4)] + [OutProj()]

    def seq_loss(params, batch):
        x, y = batch
        x = layers[0].apply({"params": params["pre"]["0"]}, x)
        for i in range(4):
            blk = jax.tree.map(lambda l: l[i], params["stack"])
            x = layers[1].apply({"params": blk}, x)
        x = layers[-1].apply({"params": params["post"]["0"]}, x)
        return mse(x, y)

    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    opt = FusedAdam(lr=1e-2, weight_decay=0.0)
    state = opt.init(p0)
    params = p0
    seq_losses = []
    for b in _batches(5, BS):
        loss, g = jax.value_and_grad(seq_loss)(params, b)
        params, state = opt.update(g, state, params, 1e-2)
        seq_losses.append(float(loss))

    np.testing.assert_allclose(pipe_losses, seq_losses, rtol=1e-4, atol=1e-5)


def test_pipeline_with_data_parallel():
    """pp=2 x dp=4 on the 8-device mesh."""
    groups.initialize_mesh(pipe_parallel_size=2, force=True)  # data gets 4
    module = _pipe_module(n_blocks=2, num_stages=2)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=_cfg(gas=2, micro=1),
                                               example_batch=example)
    losses = [float(engine.train_batch(batch=b)) for b in _batches(6, 1 * 2 * 4)]
    assert losses[-1] < losses[0]


def test_pipeline_with_zero2():
    """pp=2 x dp=4 with ZeRO-2 sharded grads/opt-state (VERDICT r2 weak #6:
    PP x ZeRO>=1 interaction was untested)."""
    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = _pipe_module(n_blocks=2, num_stages=2)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    cfg = _cfg(gas=2, micro=1)
    cfg["zero_optimization"] = {"stage": 2}
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=cfg,
                                               example_batch=example)
    losses = [float(engine.train_batch(batch=b)) for b in _batches(6, 1 * 2 * 4)]
    assert losses[-1] < losses[0]


def test_pipeline_forward_raises():
    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = _pipe_module(num_stages=2)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=_cfg(),
                                               example_batch=example)
    from deepspeed_tpu.runtime.pipe.engine import PipelineError
    with pytest.raises(PipelineError):
        engine.forward((np.ones((2, HIDDEN)), np.ones(2)))


def test_pipeline_requires_example_batch():
    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = _pipe_module(num_stages=2)
    from deepspeed_tpu.runtime.pipe.engine import PipelineError
    with pytest.raises(PipelineError):
        deepspeed_tpu.initialize(model=module, config=_cfg())


def test_pipeline_eval_batch():
    groups.initialize_mesh(pipe_parallel_size=2, force=True)
    module = _pipe_module(num_stages=2)
    example = (jnp.ones((2, HIDDEN)), jnp.ones((2, )))
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, config=_cfg(),
                                               example_batch=example)
    loss = engine.eval_batch(batch=_batches(1, 2 * 4 * 4)[0])
    assert np.isfinite(float(loss))
