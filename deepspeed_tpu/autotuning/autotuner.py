"""Autotuner: measured search over engine configurations.

Reference: ``deepspeed/autotuning/autotuner.py:42`` (Autotuner — profiles the
model, generates experiment configs from templates over ZeRO stage /
micro-batch / other knobs, schedules them through the launcher, picks the
fastest) with grid/random/model-based tuners under ``autotuning/tuner/``.

TPU formulation: two execution modes.

- ``exec_mode: "subprocess"`` (default when a ``model_factory`` is given —
  reference parity): every candidate runs as its own ``dstpu``-launched
  process via ``autotuning/scheduler.py``, so an OOM-killed or XLA-aborted
  candidate fails alone, world size can vary per candidate, and no XLA
  state leaks between trials. ``model_factory`` is an importable
  ``"pkg.mod:fn"`` (see ``exp_runner``) because live models don't cross
  process boundaries — the same reason the reference passes a user script.
- ``exec_mode: "in_process"``: each candidate builds an engine in this
  process and times a few ``train_batch`` steps; XLA's compile cache keeps
  repeat shapes cheap. Faster for small searches, but a hard OOM kills the
  tuner too.

The search space follows the reference's config schema (``autotuning``
block: ``tuner_type`` grid|random|model_based, ``max_experiments``,
user-overridable space); results are written to ``results.json`` like the
reference's autotuning_metric_path.
"""

import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

DEFAULT_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
}

# the model-based tuner searches the reference's wider knob set
DEFAULT_MODEL_BASED_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
    "gradient_accumulation_steps": [1, 2, 4],
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}


def _set_nested(cfg: dict, dotted: str, value):
    node = cfg
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


class Autotuner:

    def __init__(self, model=None, base_config: dict = None, batch_fn=None,
                 model_parameters=None, space: Optional[Dict[str, List[Any]]] = None,
                 steps: int = 3, warmup: int = 1, results_dir: Optional[str] = None,
                 model_factory: Optional[str] = None):
        """``batch_fn(micro_batch_size) -> batch`` supplies a global batch for
        a candidate micro size (the reference reads it off the dataloader).
        ``model_factory`` ("pkg.mod:fn", see exp_runner) enables the
        launcher-scheduled subprocess mode; ``model``/``batch_fn`` then only
        serve the profile pass and may be omitted."""
        self.model = model
        self.model_parameters = model_parameters
        self.base_config = base_config or {}
        self.batch_fn = batch_fn
        at = self.base_config.get("autotuning", {})
        self.space = space or at.get("space", DEFAULT_SPACE)
        self.tuner_type = at.get("tuner_type", "gridsearch")
        self.max_experiments = at.get("max_experiments", 32)
        self.steps = steps
        self.warmup = warmup
        self.results_dir = results_dir or at.get("results_dir", "autotuning_results")
        self.model_factory = model_factory or at.get("model_factory")
        self.exec_mode = at.get("exec_mode",
                                "subprocess" if self.model_factory else "in_process")
        if self.exec_mode == "subprocess" and not self.model_factory:
            raise ValueError("autotuning exec_mode 'subprocess' needs a model_factory "
                             "('pkg.mod:fn'; live models don't cross process boundaries)")
        self._resource_manager = None
        if self.exec_mode == "subprocess":
            from deepspeed_tpu.autotuning.scheduler import (DEFAULT_EXPERIMENT_TIMEOUT_S,
                                                            ResourceManager)
            self._resource_manager = ResourceManager(
                self.results_dir, self.model_factory, steps=steps, warmup=warmup,
                timeout_s=int(at.get("experiment_timeout", DEFAULT_EXPERIMENT_TIMEOUT_S)),
                num_chips=int(at.get("num_chips", 1)))
        self._exp_seq = 0
        self.results: List[dict] = []

    def _candidates(self):
        keys = list(self.space.keys())
        combos = list(itertools.product(*(self.space[k] for k in keys)))
        if self.tuner_type == "random":
            rng = np.random.default_rng(0)
            rng.shuffle(combos)
        return [dict(zip(keys, c)) for c in combos[:self.max_experiments]]

    def _candidate_config(self, overrides: dict) -> dict:
        import copy
        cfg = copy.deepcopy(self.base_config)
        cfg.pop("autotuning", None)
        for k, v in overrides.items():
            _set_nested(cfg, k, v)
        return cfg

    def _run_experiment(self, overrides: dict) -> Optional[float]:
        if self.exec_mode == "subprocess":
            return self._run_experiment_subprocess(overrides)
        return self._run_experiment_in_process(overrides)

    def _run_experiment_subprocess(self, overrides: dict) -> Optional[float]:
        """Reference scheduler.run_experiment:375 — the candidate runs as its
        own launcher job; a dead process is a failed candidate, not a dead
        tuner."""
        self._exp_seq += 1
        result = self._resource_manager.run_experiment(self._exp_seq,
                                                       self._candidate_config(overrides))
        tput = result.get("throughput_samples_per_sec")
        if tput is None:
            logger.warning(f"autotuning experiment {overrides} failed: "
                           f"{result.get('error', 'unknown')[:160]}")
            return None
        return float(tput)

    def _run_experiment_in_process(self, overrides: dict) -> Optional[float]:
        import deepspeed_tpu
        from deepspeed_tpu.utils import groups

        cfg = self._candidate_config(overrides)
        micro = cfg.get("train_micro_batch_size_per_gpu", 1)
        try:
            groups.initialize_mesh(force=True)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, model_parameters=self.model_parameters, config=cfg)
            batch = self.batch_fn(micro)
            for _ in range(self.warmup):
                float(engine.train_batch(batch=batch))
            t0 = time.perf_counter()
            loss = None
            for _ in range(self.steps):
                loss = engine.train_batch(batch=batch)
            float(loss)
            dt = (time.perf_counter() - t0) / self.steps
            tput = engine.train_batch_size() / dt
            del engine
            return tput
        except Exception as e:
            logger.warning(f"autotuning experiment {overrides} failed: {str(e)[:120]}")
            return None

    def tune(self) -> dict:
        """Reference Autotuner.tune():404 — run the space, keep the fastest.
        ``tuner_type`` model_based routes through the cost-model search."""
        if self.tuner_type == "model_based":
            return self.tune_model_based()
        best = None
        for overrides in self._candidates():
            tput = self._run_experiment(overrides)
            rec = {"config": overrides, "throughput_samples_per_sec":
                   None if tput is None else round(tput, 2)}
            self.results.append(rec)
            logger.info(f"autotuning: {rec}")
            if tput is not None and (best is None or tput > best[1]):
                best = (overrides, tput)
        return self._write_results(best)

    def _write_results(self, best) -> dict:
        os.makedirs(self.results_dir, exist_ok=True)
        summary = {"experiments": self.results,
                   "best": None if best is None else
                   {"config": best[0], "throughput_samples_per_sec": round(best[1], 2)}}
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump(summary, f, indent=2)
        if best is None:
            raise RuntimeError("autotuning: every experiment failed")
        return summary["best"]

    # --------------------------------------------------------- model-based --
    def _profile(self) -> dict:
        """One static profile pass (reference model_info_path role): parameter
        count + ZeRO degree + device HBM feed the analytic cost model."""
        import jax
        from deepspeed_tpu.autotuning.cost_model import device_memory_bytes
        from deepspeed_tpu.utils import groups

        params = self.model_parameters
        if params is not None:
            n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
        elif self.model_factory:
            # subprocess mode may not hand us live params — profile in a
            # subprocess too: a model too big for this process (the very case
            # subprocess mode exists for) must not OOM the tuner
            n_params = self._profile_n_params_subprocess()
        else:
            n_params = 0
        zero_degree = 1
        if groups.mesh_is_initialized():
            mesh = groups.get_mesh()
            zero_degree = int(np.prod([mesh.shape[ax] for ax in groups.get_zero_partition_axes()
                                       if ax in mesh.shape]))
        return {"n_params": n_params, "zero_degree": max(1, zero_degree),
                "hbm_bytes": device_memory_bytes()}

    def _profile_n_params_subprocess(self) -> int:
        """Parameter count via ``exp_runner --profile`` in its own process;
        0 (prune nothing) when the profile itself fails."""
        import json as _json
        import subprocess
        import sys
        import tempfile

        fd, cfg_path = tempfile.mkstemp(suffix=".json", prefix="tune_profile_")
        with os.fdopen(fd, "w") as f:
            _json.dump(self._candidate_config({}), f)
        try:
            r = subprocess.run(
                [sys.executable, "-m", "deepspeed_tpu.autotuning.exp_runner",
                 "--profile", self.model_factory, cfg_path],
                capture_output=True, text=True,
                timeout=self._resource_manager.timeout_s if self._resource_manager else 900)
            for line in reversed(r.stdout.strip().splitlines()):
                if line.startswith("{"):
                    return int(_json.loads(line)["n_params"])
            logger.warning(f"autotuning profile produced no count (rc={r.returncode}): "
                           f"{(r.stderr or '').strip()[-160:]}")
        except Exception as e:  # noqa: BLE001 — degraded profile, not a dead tuner
            logger.warning(f"autotuning profile subprocess failed: {e}")
        finally:
            os.unlink(cfg_path)
        return 0

    def tune_model_based(self) -> dict:
        """Cost-model-guided search (reference tuner/model_based_tuner.py +
        cost_model.py): the analytic prior prunes OOM configs and orders the
        rest; after each measurement a ridge regression re-ranks the remaining
        candidates; stops at ``max_experiments`` or when the regressor predicts
        no remaining candidate beats the best measured. results.json records
        the estimate next to every measurement."""
        from deepspeed_tpu.autotuning.cost_model import AnalyticCostModel, LearnedCostModel

        space = self.space if self.space is not DEFAULT_SPACE else DEFAULT_MODEL_BASED_SPACE
        keys = list(space.keys())
        candidates = [dict(zip(keys, c)) for c in itertools.product(*(space[k] for k in keys))]

        prof = self._profile()
        prior = AnalyticCostModel(prof["n_params"], prof["zero_degree"], prof["hbm_bytes"])
        pruned = [c for c in candidates if not prior.fits(c)]
        candidates = [c for c in candidates if prior.fits(c)]
        for c in pruned:
            self.results.append({"config": c, "pruned": "predicted OOM",
                                 "predicted_bytes": int(prior.memory_bytes(c))})
        candidates.sort(key=prior.throughput_prior, reverse=True)

        learned = LearnedCostModel()
        best = None
        measured = 0
        while candidates and measured < self.max_experiments:
            if learned.trained:
                candidates.sort(key=learned.predict, reverse=True)
                # convergence: nothing left is predicted to beat the best
                if best is not None and learned.predict(candidates[0]) <= best[1]:
                    logger.info("autotuning(model_based): converged — no remaining "
                                "candidate predicted to beat the best measured")
                    break
            overrides = candidates.pop(0)
            predicted = learned.predict(overrides) if learned.trained else None
            tput = self._run_experiment(overrides)
            measured += 1
            rec = {"config": overrides,
                   "predicted_samples_per_sec": None if predicted is None else round(predicted, 2),
                   "prior_rank_score": round(prior.throughput_prior(overrides), 4),
                   "throughput_samples_per_sec": None if tput is None else round(tput, 2)}
            self.results.append(rec)
            logger.info(f"autotuning(model_based): {rec}")
            if tput is not None:
                learned.observe(overrides, tput)
                if best is None or tput > best[1]:
                    best = (overrides, tput)
        return self._write_results(best)
