"""Eigenvalue, progressive layer drop, random-LTD, SparseTensor, TiledLinear
(reference: runtime/eigenvalue.py, runtime/progressive_layer_drop.py,
data_pipeline/data_routing/, runtime/sparse_tensor.py, runtime/zero/tiling.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import make_simple_model, random_batches


# ------------------------------------------------------------------ eigenvalue --
def test_eigenvalue_quadratic():
    """For loss = 0.5 xᵀ A x the Hessian is A: power iteration must find the
    dominant eigenvalue per block (then scale the max to 1.0)."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

    a_eigs = np.array([4.0, 1.0, 0.25])
    b_eigs = np.array([8.0, 2.0])
    A = jnp.asarray(np.diag(a_eigs), jnp.float32)
    B = jnp.asarray(np.diag(b_eigs), jnp.float32)
    params = {"a": jnp.ones((3, ), jnp.float32), "b": jnp.ones((2, ), jnp.float32)}

    def loss_fn(p, batch):
        return 0.5 * p["a"] @ A @ p["a"] + 0.5 * p["b"] @ B @ p["b"]

    ev = Eigenvalue(max_iter=200, tol=1e-6)
    out = ev.compute_eigenvalue(loss_fn, params, batch=None)
    # raw eigs 4 and 8 → normalized to max 1.0
    np.testing.assert_allclose(out["b"], 1.0, rtol=1e-3)
    np.testing.assert_allclose(out["a"], 0.5, rtol=1e-3)


def test_eigenvalue_engine_wiring():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=16, batch_size=16)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
           "eigenvalue": {"enabled": True, "max_iter": 10, "tol": 1e-2}}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)
    assert eng.eigenvalue is not None and eng.eigenvalue.max_iter == 10


# --------------------------------------------------------------------------- PLD --
def test_pld_theta_schedule():
    """θ(t) = (1-θ̄)exp(-γt) + θ̄: starts at 1, decays monotonically to θ̄."""
    from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop, keep_prob

    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    thetas = []
    for t in range(0, 1000, 100):
        pld.update_state(t)
        thetas.append(pld.get_theta())
    assert all(a >= b for a, b in zip(thetas, thetas[1:]))
    assert abs(thetas[-1] - 0.5) < 0.01
    # early layers keep more often than late ones
    assert keep_prob(0, 12, 0.5) == 1.0
    assert keep_prob(11, 12, 0.5) < keep_prob(6, 12, 0.5) < 1.0


def test_pld_layer_drop_transform():
    from deepspeed_tpu.runtime.progressive_layer_drop import layer_drop

    x = jnp.ones((4, 8))
    fn = lambda t: t * 2.0
    # eval mode: always runs
    np.testing.assert_array_equal(layer_drop(fn, x, None, 0.0), x * 2)
    # p_keep=1: runs; p_keep=0: identity
    rng = jax.random.PRNGKey(0)
    np.testing.assert_array_equal(layer_drop(fn, x, rng, 1.0), x * 2)
    np.testing.assert_array_equal(layer_drop(fn, x, rng, 0.0), x)
    # gradient flows through both branches
    g = jax.grad(lambda t: jnp.sum(layer_drop(fn, t, rng, 1.0)))(x)
    assert np.all(np.asarray(g) == 2.0)


def test_pld_engine_updates_theta():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=16, batch_size=16)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
           "progressive_layer_drop": {"enabled": True, "theta": 0.5, "gamma": 0.1}}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)
    assert eng.progressive_layer_drop is not None
    for b in random_batches(3, 16, 16):
        loss = eng.forward(b)
        eng.backward(loss)
        eng.step()
    assert eng.progressive_layer_drop.get_theta() < 1.0


# -------------------------------------------------------------------- random-LTD --
def test_random_ltd_schedule():
    from deepspeed_tpu.runtime.data_pipeline.data_routing import RandomLTDScheduler

    s = RandomLTDScheduler(min_value=128, max_value=1024, require_steps=100,
                           increase_step=16, total_layer_num=12,
                           random_ltd_layer_num=10, global_batch_size=4)
    assert s.get_value(0) == 128
    assert s.get_value(100) == 1024
    assert s.get_value(200) == 1024  # clipped
    mid = s.get_value(50)
    assert 128 < mid < 1024 and mid % 16 == 0
    assert s.get_total_layer_tokens(10) > 0


def test_random_ltd_gather_scatter_roundtrip():
    from deepspeed_tpu.runtime.data_pipeline.data_routing import (gather_tokens, random_token_indices,
                                                                  scatter_tokens)

    rng = jax.random.PRNGKey(1)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32)
    idx = random_token_indices(rng, 16, 6)
    assert idx.shape == (6, ) and bool(jnp.all(idx[1:] > idx[:-1]))  # sorted, unique
    part = gather_tokens(x, idx)
    assert part.shape == (2, 6, 8)
    # scatter processed tokens back; untouched positions keep their values
    out = scatter_tokens(x, part * 2.0, idx)
    np.testing.assert_allclose(np.asarray(out[:, idx]), np.asarray(x[:, idx]) * 2, rtol=1e-6)
    mask = np.ones(16, bool)
    mask[np.asarray(idx)] = False
    np.testing.assert_array_equal(np.asarray(out[:, mask]), np.asarray(x[:, mask]))
    # gradients flow only through kept tokens for the processed branch
    g = jax.grad(lambda h: jnp.sum(gather_tokens(h, idx)))(x)
    assert float(jnp.sum(g[:, mask])) == 0.0


# ------------------------------------------------------------------ SparseTensor --
def test_sparse_tensor_roundtrip_and_add():
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor

    x = np.zeros((10, 4), np.float32)
    x[2] = 1.0
    x[7] = 3.0
    st = SparseTensor.from_dense(x)
    assert st.sparse_size() == (8, 40)
    np.testing.assert_array_equal(np.asarray(st.to_dense()), x)

    y = np.zeros((10, 4), np.float32)
    y[7] = 1.0
    y[9] = 2.0
    both = st.add(SparseTensor.from_dense(y))
    np.testing.assert_array_equal(np.asarray(both.to_dense()), x + y)  # dup row 7 sums

    padded = SparseTensor.from_dense(x, max_rows=5)
    np.testing.assert_array_equal(np.asarray(padded.to_dense()), x)


# ------------------------------------------------------------------- TiledLinear --
def test_tiled_linear_matches_dense():
    from deepspeed_tpu.runtime.zero.tiling import (TiledLinear, dense_kernel_to_tiles,
                                                   tiles_to_dense_kernel)
    import flax.linen as nn

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    dense = nn.Dense(24)
    dp = dense.init(jax.random.PRNGKey(0), x)["params"]
    tiled = TiledLinear(features=24, in_splits=4, out_splits=3)
    tiles = dense_kernel_to_tiles(dp["kernel"], 4, 3)
    tp = {"kernel": tiles, "bias": dp["bias"].reshape(3, 8)}
    np.testing.assert_allclose(np.asarray(tiled.apply({"params": tp}, x)),
                               np.asarray(dense.apply({"params": dp}, x)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(tiles_to_dense_kernel(tiles)),
                                  np.asarray(dp["kernel"]))


def test_tiled_linear_zero3_shards_tiles():
    """Under ZeRO-3 the tile axes shard: an allgather materializes one tile row,
    never the whole [in, out] matrix (the reference's memory claim)."""
    from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear

    mesh = groups.initialize_mesh(force=True)  # data=8
    x = jnp.ones((2, 32), jnp.float32)
    m = TiledLinear(features=32, in_splits=8, out_splits=4, use_bias=False)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    policy = ZeroShardingPolicy(stage=3, mesh=mesh)
    sh = policy.param_shardings(params)
    spec = sh["kernel"].spec
    assert spec[0] is not None, f"tile axis must carry the ZeRO sharding, got {spec}"
