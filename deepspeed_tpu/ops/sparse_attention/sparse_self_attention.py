"""Block-sparse self-attention over a sparsity layout.

Reference: ``deepspeed/ops/sparse_attention/sparse_self_attention.py``
(SparseSelfAttention:15 — Triton block-sparse sdd/dsd matmuls + masked
softmax). Two implementations:

- ``impl="kernel"`` (default where it applies): the Pallas block-sparse flash
  kernel (``ops/pallas/block_sparse_attention.py``) — compute and HBM scale
  with the layout density, the role of the reference's Triton sdd/dsd tier.
  Long sequences (8k+) where dense S² scores OOM run here.
- ``impl="masked"``: dense scores + layout mask — the semantic reference and
  the path for per-batch masks (key_padding/attn_mask), which the kernel does
  not take.
"""

from typing import Optional

import numpy as np


def layout_to_dense_mask(layout, block: int):
    """[H, nb, nb] block layout → [H, S, S] boolean token mask."""
    import jax.numpy as jnp
    lay = jnp.asarray(layout, bool)
    return jnp.repeat(jnp.repeat(lay, block, axis=1), block, axis=2)


def sparse_self_attention(q, k, v, layout, block: int, scale: Optional[float] = None,
                          key_padding_mask=None, attn_mask=None, impl: str = "auto"):
    """q/k/v: [B, H, S, D]; layout: [H, nb, nb]; returns [B, H, S, D].

    ``key_padding_mask`` [B, S] and ``attn_mask`` [S, S] follow the reference's
    additive/boolean semantics: True (or 0) = keep, False (or -inf) = drop.
    ``impl``: "kernel" = Pallas block-sparse flash (density-scaling compute),
    "masked" = dense scores + mask, "auto" = kernel when no per-batch masks.
    """
    import jax.numpy as jnp

    if impl == "auto":
        impl = "masked" if (key_padding_mask is not None or attn_mask is not None) \
            else "kernel"
    if impl == "kernel":
        if key_padding_mask is not None or attn_mask is not None:
            raise ValueError("the block-sparse kernel takes the layout only; "
                             "fold per-batch masks into the layout or use impl='masked'")
        from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention
        return block_sparse_attention(q, k, v, layout, block, scale=scale)

    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale

    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    mask = layout_to_dense_mask(layout, block)[None]  # [1, H, S, S]
    scores = jnp.where(mask, scores, neg)
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask, bool)[:, None, None, :]
        scores = jnp.where(kpm, scores, neg)
    if attn_mask is not None:
        am = jnp.asarray(attn_mask, bool)[None, None]
        scores = jnp.where(am, scores, neg)

    row_max = jnp.max(scores, axis=-1, keepdims=True)
    probs = jnp.exp(scores - row_max)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs / jnp.maximum(denom, 1e-20)
    # rows with no attended key (empty layout row, or padding masking a whole
    # row) contribute zeros, not NaN — and not the uniform average that
    # exp(min - min) = 1 would produce
    probs = jnp.where(row_max > neg / 2, probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class SparseSelfAttention:
    """Layout-holding wrapper (reference SparseSelfAttention module surface)."""

    def __init__(self, sparsity_config, key_padding_mask_mode="add", attn_mask_mode="mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layouts = {}

    def get_layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        layout = self.get_layout(query.shape[-2])
        return sparse_self_attention(query, key, value, layout,
                                     self.sparsity_config.block,
                                     key_padding_mask=key_padding_mask,
                                     attn_mask=attn_mask)
