"""Config system tests (reference: tests/unit/runtime/test_ds_config_dict.py etc.)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def mesh():
    groups.initialize_mesh(force=True)  # dp = 8
    yield


def test_batch_triangle_complete():
    cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2})
    assert cfg.gradient_accumulation_steps == 2
    assert cfg.train_batch_size == 32


def test_batch_from_micro_and_gas():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 3})
    assert cfg.train_batch_size == 48


def test_batch_only_train_batch():
    cfg = DeepSpeedConfig({"train_batch_size": 16})
    assert cfg.train_micro_batch_size_per_gpu == 2
    assert cfg.gradient_accumulation_steps == 1


def test_batch_missing_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({})


def test_batch_inconsistent_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig({
            "train_batch_size": 10,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2
        })


def test_fp16_bf16_exclusive():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "fp16": {"enabled": True},
            "bf16": {"enabled": True}
        })


def test_zero_config_fields():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 3,
            "stage3_param_persistence_threshold": 123,
            "offload_optimizer": {"device": "cpu"}
        }
    })
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.param_persistence_threshold == 123
    assert cfg.zero_config.offload_optimizer.device == "cpu"
    assert cfg.zero_config.overlap_comm is True  # defaulted by stage


def test_zero_deprecated_field_warns():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 2, "cpu_offload": True}
    })
    assert cfg.zero_config.stage == 2


def test_duplicate_json_keys_raise(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p))


def test_config_from_file(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text(json.dumps({"train_batch_size": 8, "optimizer": {"type": "AdamW", "params": {"lr": 0.1}}}))
    cfg = DeepSpeedConfig(str(p))
    assert cfg.optimizer_name == "adamw"
    assert cfg.optimizer_params["lr"] == 0.1


def test_auto_values_ignored():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "zero_optimization": {"stage": 1, "reduce_bucket_size": "auto"}})
    assert cfg.zero_config.reduce_bucket_size == int(5e8)
