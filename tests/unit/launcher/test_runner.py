"""Launcher tests.

Reference: ``tests/unit/launcher/test_run.py`` (hostfile + filter parsing) and
``test_multinode_runner.py`` (command construction) — pure logic; plus an
end-to-end 2-process local launch that trains through the engine with a real
``jax.distributed`` coordination-service rendezvous (the reference's
DistributedExec analog, but through the actual CLI path)."""

import os
import subprocess
import sys
import socket
import textwrap
from collections import OrderedDict

import pytest

from deepspeed_tpu.launcher.launch import decode_world_info, encode_world_info
from deepspeed_tpu.launcher.runner import fetch_hostfile, parse_resource_filter, _world_info


def _write(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(textwrap.dedent(text))
    return str(p)


def test_fetch_hostfile(tmp_path):
    path = _write(tmp_path, """\
        # comment
        worker-0 slots=4
        worker-1 slots=2
        """)
    pool = fetch_hostfile(path)
    assert pool == OrderedDict([("worker-0", 4), ("worker-1", 2)])


def test_fetch_hostfile_bad_line(tmp_path):
    path = _write(tmp_path, "worker-0 slots=four\n")
    with pytest.raises(ValueError):
        fetch_hostfile(path)


def test_fetch_hostfile_missing():
    assert fetch_hostfile("/nonexistent/hostfile") is None


def test_include_filter(tmp_path):
    pool = fetch_hostfile(_write(tmp_path, "a slots=4\nb slots=4\n"))
    active = parse_resource_filter(pool, include_str="a:0,2@b")
    assert active == OrderedDict([("a", [0, 2]), ("b", [0, 1, 2, 3])])


def test_exclude_filter(tmp_path):
    pool = fetch_hostfile(_write(tmp_path, "a slots=2\nb slots=2\n"))
    active = parse_resource_filter(pool, exclude_str="b:1")
    assert active == OrderedDict([("a", [0, 1]), ("b", [0])])
    active = parse_resource_filter(pool, exclude_str="a")
    assert active == OrderedDict([("b", [0, 1])])


def test_include_exclude_mutually_exclusive(tmp_path):
    pool = fetch_hostfile(_write(tmp_path, "a slots=2\n"))
    with pytest.raises(ValueError):
        parse_resource_filter(pool, include_str="a", exclude_str="a")


def test_world_info_roundtrip():
    active = OrderedDict([("a", [0, 1]), ("b", [0])])
    world = _world_info(active)
    assert world == OrderedDict([("a", [0, 1]), ("b", [2])])
    assert decode_world_info(encode_world_info(world)) == {"a": [0, 1], "b": [2]}


def test_pdsh_cmd_construction():
    from deepspeed_tpu.launcher.multinode_runner import PDSHRunner

    args = type("A", (), dict(master_addr="10.0.0.1", master_port=29500, module=False,
                              no_python=False, user_script="train.py",
                              user_args=["--epochs", "2"]))()
    world = OrderedDict([("a", [0, 1]), ("b", [2, 3])])
    cmd = PDSHRunner(args, world).get_cmd({"PYTHONPATH": "/repo"}, OrderedDict([("a", [0, 1]), ("b", [0, 1])]))
    assert cmd[0] == "pdsh"
    assert "a,b" in cmd
    assert "export PYTHONPATH=/repo;" in cmd
    assert "%n" in cmd  # per-node rank expansion
    assert cmd[-2:] == ["--epochs", "2"]


def test_slurm_cmd_construction():
    from deepspeed_tpu.launcher.multinode_runner import SlurmRunner

    args = type("A", (), dict(master_addr="10.0.0.1", master_port=29500, module=False,
                              no_python=False, slurm_comment="", user_script="train.py",
                              user_args=[]))()
    world = OrderedDict([("a", [0]), ("b", [1])])
    cmd = SlurmRunner(args, world).get_cmd({}, world)
    assert cmd[:3] == ["srun", "--nodes", "2"]
    assert any("$SLURM_NODEID" in c for c in cmd)


TRAIN_SCRIPT = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu
deepspeed_tpu.comm.init_distributed()  # must precede any backend-initializing jax call
from deepspeed_tpu.utils import groups

import flax.linen as nn
import jax.numpy as jnp

class Loss(nn.Module):
    @nn.compact
    def __call__(self, batch):
        x, y = batch
        out = nn.Dense(8)(x)
        return jnp.mean((out - y) ** 2)

model = Loss()
rng = np.random.default_rng(0)
batch = (rng.normal(size=(8, 8)).astype(np.float32), rng.normal(size=(8, 8)).astype(np.float32))
params = model.init(jax.random.PRNGKey(0), batch)["params"]
cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
       "optimizer": {"type": "AdamW", "params": {"lr": 0.01}},
       "zero_optimization": {"stage": 2}}
engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=cfg)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8
l0 = float(engine.train_batch(batch=batch))
l1 = float(engine.train_batch(batch=batch))
assert l1 < l0, (l0, l1)
with open(os.environ["MARKER_DIR"] + f"/rank{jax.process_index()}", "w") as f:
    f.write(f"{l0} {l1}")
"""


@pytest.mark.nightly
def test_local_two_process_training(tmp_path):
    """dstpu CLI end-to-end: 2 local processes x 4 virtual chips rendezvous via
    the coordination service and run ZeRO-2 train_batch on the joint mesh."""
    script = tmp_path / "train2.py"
    script.write_text(TRAIN_SCRIPT)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = os.environ.copy()
    env["MARKER_DIR"] = str(tmp_path)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    rc = subprocess.call([sys.executable, "-m", "deepspeed_tpu.launcher.runner",
                          "--hostfile", "/nonexistent", "--num_chips", "2",
                          "--master_port", str(port), str(script)],
                         env=env, timeout=540)
    assert rc == 0
    assert (tmp_path / "rank0").exists() and (tmp_path / "rank1").exists()
