"""Fused LAMB.

Reference: ``deepspeed/ops/lamb/fused_lamb.py:14`` over ``csrc/lamb/fused_lamb_cuda.cu``.
LAMB = Adam step rescaled per-layer by trust ratio ||p|| / ||update||.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, _tree_zeros_like


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any


class FusedLamb(TpuOptimizer):

    name = "lamb"

    def __init__(self,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 weight_decay=0.0,
                 max_grad_norm=0.0,
                 max_coeff=10.0,
                 min_coeff=0.01,
                 amsgrad=False):
        super().__init__(lr=lr, weight_decay=weight_decay)
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant")
        self.betas = betas
        self.eps = eps
        self.bias_correction = bias_correction
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init(self, params):
        return LambState(step=jnp.zeros([], jnp.int32),
                         exp_avg=_tree_zeros_like(params),
                         exp_avg_sq=_tree_zeros_like(params))

    def update(self, grads, state, params, lr):
        b1, b2 = self.betas
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1**stepf if self.bias_correction else 1.0
        bc2 = 1.0 - b2**stepf if self.bias_correction else 1.0
        wd = self.weight_decay

        def upd(p, g, m, v):
            g = g.astype(p.dtype)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * (g * g)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if wd != 0.0:
                u = u + wd * p
            p_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32))
            trust = jnp.where((p_norm > 0) & (u_norm > 0),
                              jnp.clip(p_norm / u_norm, self.min_coeff, self.max_coeff), 1.0)
            return p - lr * trust * u, m, v

        p_flat, treedef = jax.tree.flatten(params)
        g_flat = treedef.flatten_up_to(grads)
        m_flat = treedef.flatten_up_to(state.exp_avg)
        v_flat = treedef.flatten_up_to(state.exp_avg_sq)
        out = [upd(p, g, m, v) for p, g, m, v in zip(p_flat, g_flat, m_flat, v_flat)]
        return (jax.tree.unflatten(treedef, [o[0] for o in out]),
                LambState(step=step,
                          exp_avg=jax.tree.unflatten(treedef, [o[1] for o in out]),
                          exp_avg_sq=jax.tree.unflatten(treedef, [o[2] for o in out])))
