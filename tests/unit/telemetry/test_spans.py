"""SpanRecorder: ring bound, Chrome-trace export, timer wrapping."""

import json
import time

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry import SpanRecorder, TelemetryConfig, TracingTimers
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer


def test_ring_buffer_bound_and_drop_count():
    rec = SpanRecorder(max_spans=4)
    for i in range(10):
        rec.record(f"s{i}", ts_us=i, dur_us=1)
    assert len(rec) == 4
    assert rec.dropped == 6
    names = [e["name"] for e in rec.chrome_trace()["traceEvents"]]
    assert names == ["s6", "s7", "s8", "s9"]


def test_ring_overflow_increments_spans_dropped_total(tmp_path):
    """ISSUE satellite: ring overflow is VISIBLE — the session's recorder
    feeds ``spans_dropped_total``, the ``/trace`` doc carries the drop count,
    and flight dumps record it too."""
    session = telemetry.configure(TelemetryConfig(
        enabled=True, max_spans=4,
        flight_recorder={"enabled": True, "dir": str(tmp_path),
                         "watchdog_enabled": False}))
    try:
        rec = telemetry.get_span_recorder()
        for i in range(10):
            rec.record(f"s{i}", ts_us=i, dur_us=1)
        counter = telemetry.get_registry().counter("spans_dropped_total")
        assert counter.value == 6
        assert rec.chrome_trace()["spansDropped"] == 6
        path = telemetry.get_flight_recorder().dump("api")
        with open(path) as f:
            assert json.load(f)["spans_dropped"] == 6
        # export_since surfaces the same count for the fleet collector
        assert rec.export_since(0)["dropped"] == 6
    finally:
        session.close()
    # a bare recorder (no session) stays registry-free: no counter, no crash
    bare = SpanRecorder(max_spans=2)
    for i in range(5):
        bare.record(f"b{i}", ts_us=i)
    assert bare.dropped == 3


def test_export_since_filters_by_timestamp():
    rec = SpanRecorder()
    rec.record("old", ts_us=100, dur_us=1)
    rec.record("new", ts_us=5000, dur_us=1)
    doc = rec.export_since(1000)
    assert [s["name"] for s in doc["spans"]] == ["new"]
    assert doc["pid"] > 0 and doc["now_us"] > 0 and doc["dropped"] == 0


def test_span_context_manager_measures():
    rec = SpanRecorder()
    with rec.span("work", cat="test", args={"k": 1}):
        time.sleep(0.01)
    (ev, ) = rec.chrome_trace()["traceEvents"]
    assert ev["name"] == "work" and ev["cat"] == "test"
    assert ev["ph"] == "X" and ev["dur"] >= 9000
    assert ev["args"] == {"k": 1}


def test_chrome_trace_export_is_loadable(tmp_path):
    rec = SpanRecorder()
    # recorded out of order on purpose: export must sort by ts
    rec.record("late", ts_us=500, dur_us=10)
    rec.record("early", ts_us=100, dur_us=10)
    rec.record("mid", ts_us=300, dur_us=10)
    path = rec.export_chrome_trace(str(tmp_path / "trace.json"))

    with open(path) as f:
        trace = json.load(f)  # must be valid JSON
    evs = trace["traceEvents"]
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    assert all(e["ph"] == "X" for e in evs)  # complete events: no B/E pairing to break
    assert all(isinstance(e["dur"], int) and e["dur"] >= 0 for e in evs)


def test_tracing_timers_wrap_wall_clock_timers():
    rec = SpanRecorder()
    timers = TracingTimers(SynchronizedWallClockTimer(), rec)
    t = timers("fwd")
    t.start()
    time.sleep(0.005)
    t.stop()
    t.start()
    t.stop()
    evs = rec.chrome_trace()["traceEvents"]
    assert [e["name"] for e in evs] == ["fwd", "fwd"]
    assert evs[0]["cat"] == "engine" and evs[0]["dur"] >= 4000
    # the inner timer still accumulates (the engine's log() path keeps working)
    assert timers("fwd").elapsed(reset=False) > 0
    assert "fwd" in timers.get_timers()
