"""Request lifecycle primitives for the serving layer.

Reference role: DeepSpeed-MII's ``RaggedRequest``/``RaggedRequestMsg`` (the
request objects FastGen's persistent deployment schedules); here the request
additionally owns a thread-safe streaming output channel so time-to-first-token
is a real, observable event — the scheduler thread pushes tokens as they are
sampled and any number of consumer threads (an SSE handler, ``generate()``)
iterate them live.

State machine::

    QUEUED -> PREFILL -> DECODE -> DONE
       \\         \\         \\---> CANCELLED | FAILED | TIMED_OUT
        \\         \\--------------^
         \\------------------------^

Terminal transitions happen on the scheduler thread only (engine state — KV
blocks, sequence descriptors — is freed there); ``cancel()`` from any thread
just raises a flag the scheduler honors on its next tick.
"""

import itertools
import queue
import threading
import time
from enum import Enum
from typing import Iterator, List, Optional

import numpy as np

from deepspeed_tpu.serving.overload import (DEFAULT_PRIORITY, validate_priority,
                                            validate_tenant)
from deepspeed_tpu.telemetry import now_us


class RequestState(Enum):
    QUEUED = 0
    PREFILL = 1
    DECODE = 2
    DONE = 3
    CANCELLED = 4
    FAILED = 5
    TIMED_OUT = 6


TERMINAL_STATES = frozenset(
    {RequestState.DONE, RequestState.CANCELLED, RequestState.FAILED, RequestState.TIMED_OUT})

_END = object()

# process-unique steal handles (request.handle): the fleet router addresses a
# victim's in-flight request across the HTTP boundary by handle, never by uid
# (uids are per-scheduler and unassigned until admission)
_HANDLE_IDS = itertools.count()


class TokenStream:
    """Thread-safe single-producer token channel: the scheduler ``put()``s,
    consumers iterate (blocking) or poll ``get(timeout)``. Closing wakes every
    consumer; iteration then stops."""

    def __init__(self):
        self._q = queue.SimpleQueue()
        self._closed = threading.Event()

    def put(self, token: int) -> None:
        self._q.put(token)

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            self._q.put(_END)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token, or None once the stream is closed and drained.
        Raises ``queue.Empty`` on timeout."""
        item = self._q.get(timeout=timeout)
        if item is _END:
            self._q.put(_END)  # keep the sentinel for other/later consumers
            return None
        return item

    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _END:
                self._q.put(_END)
                return
            yield item


class Request:
    """One generation request: prompt in, token stream out.

    ``deadline_s`` is a *relative* budget from submission; the scheduler
    enforces the absolute ``deadline`` (monotonic clock) at every tick and
    mid-decode. ``max_new_tokens``/``eos_token_id``/``temperature``/``seed``
    are per-request sampling parameters (the seed feeds a private numpy
    stream so concurrent requests sample independently).
    """

    def __init__(self,
                 prompt,
                 max_new_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 seed: int = 0,
                 priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.deadline_s = deadline_s
        self.seed = int(seed)
        self.priority = validate_priority(priority)
        # tenant identity for the cost-attribution plane: the scheduler
        # normalizes None to the configured default tenant at submission;
        # cost is the per-request ledger accumulator
        # (telemetry.ledger.RequestCost), None while telemetry is off — the
        # zero-cost contract makes every charging site one None check
        self.tenant = validate_tenant(tenant)
        self.cost = None

        self.uid: Optional[int] = None  # assigned at admission by the scheduler
        # stable cross-thread identity from birth: the work-stealing path
        # must address a request while it is still QUEUED (uid is None)
        self.handle: str = f"r{next(_HANDLE_IDS)}"
        # distributed-tracing identity: the scheduler assigns both when a
        # telemetry session is active; every lifecycle span parents under
        # root_span_id and the HTTP layer returns trace_id to the client.
        # A request arriving through the fleet router inherits its trace_id
        # and parents its root under the router's span (parent_span_id).
        self.trace_id: Optional[str] = None
        self.root_span_id: Optional[int] = None
        self.parent_span_id: Optional[int] = None
        # fleet KV handoff: a handoff-requested request exports its engine
        # state as a portable payload when it finishes DONE (prefill role);
        # a resume request carries a peer's payload in and enters DECODE
        # directly once the scheduler imports it (decode role)
        self.handoff_requested = False
        self.handoff_payload: Optional[bytes] = None
        self._resume_payload: Optional[bytes] = None
        self._resume_header: Optional[dict] = None
        self._resume_kv = None  # parsed KV view into _resume_payload
        # tiered KV parking: a park-requested request exports a v2 park frame
        # at finish (length OR eos — a new turn can continue either) for the
        # router's park store; a rehydrate request carries a parked frame in
        # PLUS the new turn's full prompt and enters PREFILL for the suffix
        # only (the parked turns' KV imports, zero prefill for cached turns)
        self.park_requested = False
        self.park_payload: Optional[bytes] = None
        self._rehydrate = False
        self.kv_tier_source: Optional[str] = None  # tier the KV was served from
        self.tokens: List[int] = []
        # prompt tokens served from the prefix cache at admission (0 = cold);
        # surfaced in /v1/stats rows and the final response doc so clients and
        # the loadgen can split latency by hit/miss
        self.cached_tokens = 0
        # the prompt's chained block digests, hashed once at admission and
        # extended (never recomputed) at each publish point
        self._prefix_digests = None
        self.stream = TokenStream()
        self.error: Optional[str] = None
        self.finish_reason: Optional[str] = None  # "eos" | "length" | "context"
        # overload control (serving/overload.py): shed_reason marks a request
        # dropped before any engine work (admission estimate or queue shed);
        # retry_after_s rides the 429/SSE error so clients back off
        # proportionally; degraded_mode lists every brownout degradation
        # applied (clamped budget, disabled speculation) — never silent
        self.shed_reason: Optional[str] = None
        self.retry_after_s: Optional[float] = None
        self.degraded_mode: List[str] = []
        # speculative decoding (inference/v2/spec/): per-request drafting
        # stats and the acceptance EWMA driving the adaptive k. The EWMA is
        # the drafter state a fleet handoff carries so a decode-role peer
        # continues adaptation where the donor stopped.
        self.spec_drafted = 0     # draft tokens proposed into verify feeds
        self.spec_accepted = 0    # of those, accepted by the target model
        self.decode_steps = 0     # decode dispatches this request consumed

        self.arrival_s = time.monotonic()
        self.arrival_us = now_us()  # span-clock arrival (perf_counter domain)
        self.deadline = (self.arrival_s + deadline_s) if deadline_s is not None else None
        self.first_token_s: Optional[float] = None
        self.finished_s: Optional[float] = None

        self._state = RequestState.QUEUED
        self._state_lock = threading.Lock()
        self._done = threading.Event()
        self._cancel_requested = threading.Event()

        # scheduler-private bookkeeping (touched on the scheduler thread only)
        self._fed = 0                 # prompt tokens already put() into the engine
        self._next: Optional[int] = None  # next decode input token
        self._deferred = 0            # consecutive ticks skipped under pressure
        self._last_touch_s = self.arrival_s  # eviction coldness ordering
        self._last_token_s: Optional[float] = None  # ITL measurement
        self._rng: Optional[np.random.Generator] = None
        self._spec_ewma: Optional[float] = None  # acceptance EWMA (None = cold)
        # drafting history buffer (prompt + generated), grown incrementally by
        # the scheduler so per-step drafting copies O(new tokens), not O(all)
        self._spec_history: Optional[np.ndarray] = None
        self._spec_history_len = 0
        # learned / auto drafter state (scheduler thread only): the target's
        # hidden state behind the next decode input (valid only while
        # _spec_hidden_pos equals the current history length), the per-drafter
        # acceptance EWMAs "auto" arbitrates over (carried across handoffs),
        # the drafter that built the in-flight feed, and the in-flight
        # TokenTree awaiting verify (None = linear/plain feed this tick)
        self._spec_hidden: Optional[np.ndarray] = None
        self._spec_hidden_pos = -1
        self._spec_ewmas: dict = {}
        self._spec_last_drafter: Optional[str] = None
        self._spec_tree = None
        # client-requested drafter pin (``submit(drafter=...)``): overrides
        # "auto" arbitration for THIS request — the loadgen's A/B lever
        self._spec_drafter_pin: Optional[str] = None

    # ----------------------------------------------------------------- state --
    @property
    def state(self) -> RequestState:
        return self._state

    @property
    def finished(self) -> bool:
        return self._state in TERMINAL_STATES

    def _set_state(self, state: RequestState) -> None:
        with self._state_lock:
            if self._state in TERMINAL_STATES:
                return  # terminal states are sticky
            self._state = state
            if state in TERMINAL_STATES:
                self.finished_s = time.monotonic()
                self.stream.close()
                self._done.set()

    def cancel(self) -> None:
        """Request cancellation (any thread); the scheduler finalizes — frees
        the sequence's KV blocks — on its next tick."""
        self._cancel_requested.set()

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested.is_set()

    # ----------------------------------------------------------------- waits --
    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request reaches a terminal state."""
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block for completion and return the generated tokens. FAILED raises
        (the scheduler's error message); CANCELLED/TIMED_OUT return the tokens
        produced before the cut — the caller can inspect ``state``."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.uid} not finished within {timeout}s")
        if self._state is RequestState.FAILED:
            raise RuntimeError(self.error or "request failed")
        return list(self.tokens)

    # ----------------------------------------------------------------- stats --
    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.arrival_s

    @property
    def e2e_s(self) -> Optional[float]:
        if self.finished_s is None:
            return None
        return self.finished_s - self.arrival_s

    def __repr__(self):
        return (f"Request(uid={self.uid}, state={self._state.name}, "
                f"prompt={self.prompt.size}t, generated={len(self.tokens)}t)")
