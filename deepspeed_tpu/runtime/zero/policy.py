"""ZeRO stages as sharding policies.

The TPU-native realization of the reference's ZeRO optimizers
(``stage_1_and_2.py:91`` DeepSpeedZeroOptimizer, ``stage3.py:73``
DeepSpeedZeroOptimizer_Stage3, ``partition_parameters.py:786`` zero.Init):
instead of hand-partitioned flat buffers, grad hooks and bucketed
reduce-scatter/allgather loops, each stage is a *placement policy* — a
PartitionSpec assignment for params / gradients / optimizer state over the ZeRO
mesh axes (('data','expert','seq'), the reference's seq-data-parallel group).
XLA's SPMD partitioner then inserts and overlaps exactly the collectives the
reference implements by hand:

  stage 0 — everything replicated; batch sharding makes grad psum implicit.
  stage 1 — optimizer state sharded → step() becomes per-shard update +
            allgather of updated params (reference stage_1_and_2.py:1786).
  stage 2 — + gradient accumulation buffer sharded → backward emits
            reduce-scatter (reference reduce_ipg_grads/average_tensor:1020).
  stage 3 — + parameters sharded → forward emits per-layer allgather,
            prefetched/overlapped by the XLA scheduler (the reference's
            PartitionedParameterCoordinator:59 trace-based prefetcher).

Parameters whose shapes don't divide the ZeRO degree stay replicated (the
reference handles the remainder by padding flat partitions; the persistence
threshold keeps small params resident too — same effect).
"""

from typing import Optional

import numpy as np

from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger


class ZeroShardingPolicy:

    def __init__(self, stage: int, mesh=None, zero_axes=None, tp_axis=groups.MODEL_AXIS,
                 persistence_threshold: int = 0, param_axes=None):
        """``param_axes`` restricts stage-3 *parameter* placement to a subset of
        the ZeRO axes — ZeRO++ hpZ's secondary partition (reference
        zero/config.py zero_hpz_partition_size): the forward/backward
        all-gathers then ride only the small intra-node axis while optimizer
        state and gradients stay sharded over the full group. Passing a
        restricted ``zero_axes`` instead shards *everything* over the subgroup
        and replicates across the rest — MiCS (reference runtime/zero/mics.py):
        gradient sync across replica groups becomes the plain psum XLA inserts
        for the replicated axes."""
        self.stage = stage
        self.mesh = mesh if mesh is not None else groups.get_mesh()
        self.zero_axes = tuple(zero_axes) if zero_axes is not None else groups.get_zero_partition_axes()
        # drop axes of size 1 so specs stay minimal
        self.zero_axes = tuple(ax for ax in self.zero_axes if self.mesh.shape.get(ax, 1) > 1)
        self.zero_size = int(np.prod([self.mesh.shape[ax] for ax in self.zero_axes])) if self.zero_axes else 1
        self.param_axes = tuple(ax for ax in param_axes if self.mesh.shape.get(ax, 1) > 1) \
            if param_axes is not None else None
        self.tp_axis = tp_axis
        self.persistence_threshold = persistence_threshold

    # ---- spec construction -----------------------------------------------------
    def _add_zero_axes(self, shape, base_spec, axes_set=None):
        """Extend ``base_spec`` (TP/EP placement) with the ZeRO axes on the first
        free dimension divisible by the ZeRO degree. Axes already used by the base
        spec are excluded — an expert-sharded parameter is ZeRO-partitioned only
        over the remaining axes, which is exactly the reference's
        expert-data-parallel group (engine.py:2417, groups.py:113-295)."""
        from jax.sharding import PartitionSpec as P
        base = tuple(base_spec) if base_spec is not None else ()
        base = base + (None, ) * (len(shape) - len(base))
        used = set()
        for entry in base:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry, )):
                used.add(ax)
        axes_set = axes_set if axes_set is not None else self.zero_axes
        axes = tuple(ax for ax in axes_set if ax not in used)
        size_prod = int(np.prod([self.mesh.shape[ax] for ax in axes])) if axes else 1
        if not axes or size_prod == 1:
            return P(*base)
        if int(np.prod(shape)) <= self.persistence_threshold:
            return P(*base)
        for dim, size in enumerate(shape):
            if base[dim] is not None:
                continue  # taken by TP/EP
            if size % size_prod == 0 and size > 0:
                new = list(base)
                new[dim] = axes if len(axes) > 1 else axes[0]
                return P(*new)
        return P(*base)  # nothing divides — stay replicated

    def param_spec(self, shape, base_spec=None):
        from jax.sharding import PartitionSpec as P
        base_spec = base_spec if base_spec is not None else P()
        if self.stage >= 3:
            return self._add_zero_axes(shape, base_spec, self.param_axes)
        return base_spec

    def grad_spec(self, shape, base_spec=None):
        """Sharding of the gradient-accumulation buffer."""
        from jax.sharding import PartitionSpec as P
        base_spec = base_spec if base_spec is not None else P()
        if self.stage >= 2:
            return self._add_zero_axes(shape, base_spec)
        return self.param_spec(shape, base_spec)

    def opt_spec(self, shape, base_spec=None):
        from jax.sharding import PartitionSpec as P
        base_spec = base_spec if base_spec is not None else P()
        if self.stage >= 1:
            return self._add_zero_axes(shape, base_spec)
        return base_spec

    # ---- tree helpers ----------------------------------------------------------
    def _tree_shardings(self, tree, spec_fn, base_specs=None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(leaf, base):
            shape = getattr(leaf, "shape", ())
            if len(shape) == 0:
                return NamedSharding(self.mesh, P())
            return NamedSharding(self.mesh, spec_fn(shape, base))

        if base_specs is None:
            return jax.tree.map(lambda l: one(l, None), tree)
        return jax.tree.map(one, tree, base_specs)

    def param_shardings(self, params, base_specs=None):
        return self._tree_shardings(params, self.param_spec, base_specs)

    def grad_shardings(self, params, base_specs=None):
        return self._tree_shardings(params, self.grad_spec, base_specs)

    def opt_shardings(self, opt_state_shapes, base_specs=None):
        # optimizer-state leaves mirror param shapes; the shape-driven rule places
        # them consistently with their parameter.
        return self._tree_shardings(opt_state_shapes, self.opt_spec, base_specs)
