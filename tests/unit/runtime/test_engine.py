"""Engine + ZeRO stage tests.

Reference: ``tests/unit/runtime/zero/test_zero.py`` — the core correctness gate:
same model trained with the engine at every ZeRO stage must match a plain JAX/optax
reference run (the reference compares against torch baselines).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups

from ..simple_model import SimpleModel, make_simple_model, random_batches

HIDDEN = 16


def _reference_adam_run(params, model, batches, lr=0.01, steps=None):
    """Hand-rolled AdamW reference (bias-corrected, eps outside sqrt)."""
    import jax
    import jax.numpy as jnp

    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    t = 0

    def loss_fn(p, batch):
        return model.apply({"params": p}, batch)

    losses = []
    for batch in batches:
        t += 1
        loss, g = jax.value_and_grad(loss_fn)(params, batch)
        m = jax.tree.map(lambda mm, gg: 0.9 * mm + 0.1 * gg, m, g)
        v = jax.tree.map(lambda vv, gg: 0.999 * vv + 0.001 * gg * gg, v, g)
        bc1 = 1 - 0.9**t
        bc2 = 1 - 0.999**t
        params = jax.tree.map(lambda p, mm, vv: p - lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + 1e-8), params, m, v)
        losses.append(float(loss))
    return params, losses


def _engine_config(stage=0, micro=2, gas=1, extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 0.01, "weight_decay": 0.0}},
        "zero_optimization": {"stage": stage},
    }
    if extra:
        cfg.update(extra)
    return cfg


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stage_matches_reference(stage):
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(5, 16, HIDDEN)

    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               model_parameters=params0,
                                               config=_engine_config(stage=stage, micro=2))
    for batch in batches:
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()

    ref_params, _ = _reference_adam_run(params0, model, batches)
    import jax
    got = jax.device_get(engine.params)
    want = jax.device_get(ref_params)
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-5)


def test_param_sharding_by_stage():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN)

    cfg = _engine_config(stage=3, micro=1)
    cfg["zero_optimization"]["stage3_param_persistence_threshold"] = 0
    e3, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)
    import jax
    # stage-3: at least the big kernels must be sharded over the zero axes
    kernel = e3.params["Dense_0"]["kernel"]
    assert not kernel.sharding.is_fully_replicated

    groups.destroy_mesh()
    groups.initialize_mesh(force=True)
    e0, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=0, micro=1))
    assert e0.params["Dense_0"]["kernel"].sharding.is_fully_replicated


def test_gradient_accumulation_equivalence():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(4, 16, HIDDEN)

    # gas=2 over half-batches == gas=1 over full batches
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                               config=_engine_config(stage=1, micro=1, gas=2))
    for batch in batches:
        x, y = batch
        for half in range(2):
            sl = slice(half * 8, (half + 1) * 8)
            loss = engine.forward((x[sl], y[sl]))
            engine.backward(loss)
            engine.step()
    assert engine.global_steps == len(batches)

    ref, _ = _reference_adam_run(params0, model, batches)
    import jax
    for g, w in zip(jax.tree.leaves(jax.device_get(engine.params)), jax.tree.leaves(jax.device_get(ref))):
        np.testing.assert_allclose(g, w, rtol=3e-3, atol=3e-4)


def test_train_batch_fast_path_matches_micro_loop():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(3, 16, HIDDEN)

    e1, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=2, micro=2, gas=1))
    for b in batches:
        e1.train_batch(batch=b)

    groups.destroy_mesh()
    groups.initialize_mesh(force=True)
    e2, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=2, micro=2, gas=1))
    for b in batches:
        loss = e2.forward(b)
        e2.backward(loss)
        e2.step()

    import jax
    for a, b in zip(jax.tree.leaves(jax.device_get(e1.params)), jax.tree.leaves(jax.device_get(e2.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_bf16_runs_and_converges():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=16)
    batches = random_batches(20, 16, HIDDEN, seed=7)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config=_engine_config(stage=2, micro=2, extra={"bf16": {"enabled": True}}))
    losses = []
    for b in batches:
        losses.append(float(engine.train_batch(batch=b)))
    assert losses[-1] < losses[0]


def test_fp16_dynamic_loss_scale_skips_on_overflow():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config=_engine_config(stage=0, micro=1,
                              extra={"fp16": {"enabled": True, "initial_scale_power": 4, "hysteresis": 2}}))
    scale0 = engine.loss_scale
    assert scale0 == 2.0**4

    x = np.full((8, HIDDEN), 1e30, dtype=np.float32)  # force overflow in fp16 compute
    y = np.ones((8, ), dtype=np.float32)
    # first overflow: step skipped, hysteresis consumed, scale UNCHANGED (reference
    # DynamicLossScaler semantics with delayed_shift=2)
    loss = engine.forward((x, y))
    engine.backward(loss)
    engine.step()
    assert engine.get_skipped_steps() == 1
    assert engine.loss_scale == scale0

    # second overflow: hysteresis exhausted -> scale halves
    loss = engine.forward((x, y))
    engine.backward(loss)
    engine.step()
    assert engine.get_skipped_steps() == 2
    assert engine.loss_scale == scale0 / 2.0

    # healthy step does not skip and refills nothing prematurely
    bx = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    loss = engine.forward((bx, y))
    engine.backward(loss)
    engine.step()
    assert engine.get_skipped_steps() == 2


def test_gradient_clipping_applied():
    import jax
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    clip = 1e-4
    lr = 0.5
    cfg = _engine_config(stage=0, micro=1, extra={"gradient_clipping": clip})
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": lr}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0, config=cfg)
    b = random_batches(1, 8, HIDDEN)[0]
    loss = engine.forward(b)
    engine.backward(loss)
    engine.step()
    # reported norm is the pre-clip global norm (reference semantics) ...
    assert engine.get_global_grad_norm() > clip
    # ... but the applied update is clipped: ||delta|| = lr * clip for SGD
    delta = jax.tree.map(lambda a, b: a - b, jax.device_get(engine.params), jax.device_get(params0))
    delta_norm = float(np.sqrt(sum(np.sum(d**2) for d in jax.tree.leaves(delta))))
    assert delta_norm == pytest.approx(lr * clip, rel=1e-2)


def test_checkpoint_save_load_roundtrip(tmp_path):
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    batches = random_batches(3, 8, HIDDEN)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                               config=_engine_config(stage=2, micro=1))
    for b in batches:
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path), client_state={"note": 7})

    groups.destroy_mesh()
    groups.initialize_mesh(force=True)
    e2, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=2, micro=1))
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client["note"] == 7
    assert e2.global_steps == engine.global_steps
    import jax
    for a, b in zip(jax.tree.leaves(jax.device_get(engine.params)), jax.tree.leaves(jax.device_get(e2.params))):
        np.testing.assert_allclose(a, b)


def test_checkpoint_reshard_across_stages(tmp_path):
    """Save at stage 3, load at stage 1 (the universal-checkpoint acceptance test,
    SURVEY.md §4: 'save at dp=4 / load at dp=2' analog)."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    e3, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=3, micro=1))
    e3.train_batch(batch=random_batches(1, 8, HIDDEN)[0])
    e3.save_checkpoint(str(tmp_path))

    groups.destroy_mesh()
    groups.initialize_mesh(model_parallel_size=2, force=True)
    e1, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                           config=_engine_config(stage=1, micro=1))
    path, _ = e1.load_checkpoint(str(tmp_path))
    assert path is not None
    import jax
    for a, b in zip(jax.tree.leaves(jax.device_get(e3.params)), jax.tree.leaves(jax.device_get(e1.params))):
        np.testing.assert_allclose(a, b)


def test_sgd_with_param_specs_none_state():
    """SGD momentum=0 has a None state slot; param_specs must not crash init
    (regression: _broadcast_param_specs returned P() for None subtrees)."""
    import jax
    from jax.sharding import PartitionSpec as P
    groups.initialize_mesh(model_parallel_size=2, force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    specs = jax.tree.map(lambda p: P(), params0)
    cfg = _engine_config(stage=1, micro=1)
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": 0.1}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params0,
                                               config=cfg, param_specs=specs)
    loss = engine.train_batch(batch=random_batches(1, 8, HIDDEN)[0])
    assert np.isfinite(float(loss))


def test_lr_scheduler_integration():
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    engine, _, _, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config=_engine_config(stage=0, micro=1,
                              extra={"scheduler": {"type": "WarmupLR",
                                                   "params": {"warmup_max_lr": 0.1, "warmup_num_steps": 5,
                                                              "warmup_type": "linear"}}}))
    assert sched is not None
    lrs = []
    for b in random_batches(6, 8, HIDDEN):
        engine.train_batch(batch=b)
        lrs.append(engine.get_lr()[0])
    assert lrs[-1] == pytest.approx(0.1)


def test_fp16_overflow_does_not_advance_lr_schedule():
    """Reference _take_model_step (engine.py:2100-2106): overflow-skipped steps
    leave warmup/decay schedules untouched."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    engine, _, _, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params0,
        config=_engine_config(stage=0, micro=1, extra={
            "fp16": {"enabled": True, "initial_scale_power": 4, "hysteresis": 1},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                     "warmup_num_steps": 10}},
        }))
    it0 = sched.last_batch_iteration

    x = np.full((8, HIDDEN), 1e30, dtype=np.float32)  # overflow in fp16 compute
    y = np.ones((8, ), dtype=np.float32)
    engine.backward(engine.forward((x, y)))
    engine.step()
    assert engine.get_skipped_steps() == 1
    assert sched.last_batch_iteration == it0  # schedule frozen on skipped step

    bx = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    engine.backward(engine.forward((bx, y)))
    engine.step()
    assert sched.last_batch_iteration == it0 + 1  # healthy step advances


def test_eval_forward_deterministic_no_grads():
    """ADVICE: eval() forward is a plain loss pass — no cached grads, deterministic."""
    groups.initialize_mesh(force=True)
    model, params0 = make_simple_model(hidden_dim=HIDDEN, batch_size=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params0, config=_engine_config(stage=0, micro=1))
    bx = np.random.default_rng(0).normal(size=(8, HIDDEN)).astype(np.float32)
    y = np.ones((8, ), dtype=np.float32)
    engine.eval()
    l1 = float(engine.forward((bx, y)))
    l2 = float(engine.forward((bx, y)))
    assert l1 == l2
    assert engine._cached_grads is None
    engine.train()
    l3 = engine.forward((bx, y))
    assert engine._cached_grads is not None
    engine.backward(l3)
    engine.step()
