"""Checkpoint save/load for the engine — crash-consistent.

Reference: ``deepspeed/runtime/engine.py:3052-3548`` (save/load incl. ZeRO shards)
and ``deepspeed/runtime/checkpoint_engine/`` (CheckpointEngine ABC / torch / nebula).
The TPU design (SURVEY.md §5.4): ONE logical checkpoint in sharded-array format
(orbax → tensorstore). Every host writes only its shards; restore reshards into
whatever mesh/topology is current — which is the reference's "universal checkpoint"
(ds_to_universal.py) for free.

Crash consistency (ISSUE 11): every committed checkpoint carries a
``MANIFEST.json`` written *last* via atomic tmp+rename — the commit marker.
It records per-array CRC32 checksums (the handoff ``kv_crc32`` idea applied to
training state), per-file size+CRC32 of everything the commit wrote, the
step/RNG/loss-scale state and the world shape that produced it. A checkpoint
directory without a manifest is *torn* (the crash landed mid-commit); one whose
files disagree with the manifest is *corrupt*. ``load_engine_state`` verifies
before restoring and, when asked for the latest checkpoint, falls back LOUDLY
(log + ``checkpoint_load_fallbacks_total``) to the newest verified-good tag
instead of dying. Keep-last-K retention prunes old tags but never deletes the
newest committed one.
"""

import json
import os
import re
import shutil
import zlib
import pickle

import numpy as np

from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"
MANIFEST_FILE = "MANIFEST.json"
PREEMPT_MARKER = "PREEMPTED.json"
MANIFEST_FORMAT = 1

# filenames the reference (torch) DeepSpeed writes per rank; their presence
# means the directory is a reference checkpoint, not an orbax one
_REFERENCE_SHARD_PREFIXES = ("zero_pp_rank_", "mp_rank_", "bf16_zero_pp_rank_")


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed manifest verification (torn or corrupt) and no
    fallback was possible (explicit tag, or no verified-good tag remains)."""


class ReferenceCheckpointError(RuntimeError):
    """The directory holds reference-DeepSpeed torch shards, not an orbax
    checkpoint — loudly reject with the migration path (ROADMAP item 5)."""


def _metrics():
    """Checkpoint counter family; None when telemetry is disabled (the one
    boolean check contract)."""
    from deepspeed_tpu import telemetry
    if not telemetry.is_active():
        return None
    reg = telemetry.get_registry()
    return {
        "saves": reg.counter("checkpoint_saves_total",
                             "Committed (manifest-sealed) checkpoint saves"),
        "verify_failures": reg.counter(
            "checkpoint_verify_failures_total",
            "Checkpoint tags that failed manifest verification (torn/corrupt)"),
        "fallbacks": reg.counter(
            "checkpoint_load_fallbacks_total",
            "Loads that skipped a bad tag and fell back to an older good one"),
        "pruned": reg.counter("checkpoint_pruned_total",
                              "Checkpoint tags deleted by keep-last-K retention"),
    }


def _count(name):
    m = _metrics()
    if m is not None:
        m[name].inc()


class CheckpointEngine:
    """Reference: checkpoint_engine/checkpoint_engine.py (ABC)."""

    def __init__(self, config_params=None):
        ...

    def create(self, tag):
        logger.info(f"[TPU] Saving checkpoint tag {tag}")

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded async-capable checkpoint engine over orbax/tensorstore."""

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ckptr = ocp.StandardCheckpointer() if not use_async else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, state_dict, path: str):
        self._ckptr.save(path, state_dict, force=True)

    def load(self, path: str, map_location=None, target=None):
        if target is not None:
            return self._ckptr.restore(path, target=target)
        return self._ckptr.restore(path)

    def finish(self):
        """Join the in-flight commit WITHOUT closing (the async engine is
        reused across saves)."""
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()

    def wait(self):
        # orbax finalizes array commits on background threads even for the
        # "synchronous" checkpointer; a caller (or interpreter exit) racing
        # them sees a missing/partial state dir. close() joins them.
        self.finish()
        self._ckptr.close()


def _ckpt_path(save_dir, tag):
    return os.path.join(os.path.abspath(save_dir), str(tag))


def checkpoint_barrier(engine):
    """Join any in-flight async save (Nebula-class): the barrier the next
    save/load takes, so at most one commit is ever outstanding. A commit
    that FAILED in the background re-raises here — save_checkpoint already
    returned, so the barrier is the first point the failure can surface."""
    st = getattr(engine, "_async_ckpt", None)
    if st and st.get("thread") is not None:
        st["thread"].join()
        st["thread"] = None
        err = st.pop("error", None)
        if err is not None:
            raise RuntimeError(f"async checkpoint commit failed: {err[1]}") from err[1]


def close_async_checkpointer(engine):
    """Drain + close the engine's async checkpointer (engine.destroy path):
    the last save commits (or its failure surfaces) and orbax's background
    threads are joined, so interpreter teardown can never tear a commit."""
    checkpoint_barrier(engine)
    st = getattr(engine, "_async_ckpt", None)
    if st and st.get("ckptr") is not None:
        ck, st["ckptr"] = st["ckptr"], None
        ck.wait()


def _atexit_barrier(engine_ref):
    """atexit hook (weakref'd): an in-flight async commit always lands before
    the interpreter tears down orbax's machinery — the regression was a save
    dispatched moments before exit leaving a torn state dir."""
    engine = engine_ref()
    if engine is None:
        return
    try:
        close_async_checkpointer(engine)
    except Exception as e:  # exit path: report, never mask other teardown
        logger.error(f"async checkpoint commit failed during interpreter "
                     f"exit: {e}")


# -------------------------------------------------------------- gang seals --
SEAL_DIR = ".seals"


def _seal_path(path, rank):
    return os.path.join(path, SEAL_DIR, f"rank{int(rank)}.sealed")


def _clear_rank_seal(path, rank):
    """Drop this rank's seal from a previous save of the same tag (rollback
    replays re-save tags): while the state dir is being rewritten, a stale
    seal must not satisfy rank 0's all-ranks-sealed check."""
    try:
        os.unlink(_seal_path(path, rank))
    except OSError:
        pass


def _write_rank_seal(path, rank):
    """This rank's array commit is durable. Written atomically AFTER the
    orbax commit and BEFORE rank 0 may write the manifest — the per-rank half
    of the gang commit protocol."""
    import time
    from deepspeed_tpu.elasticity.gang import atomic_write_json
    os.makedirs(os.path.join(path, SEAL_DIR), exist_ok=True)
    atomic_write_json(_seal_path(path, rank),
                      {"rank": int(rank), "pid": os.getpid(), "unix": time.time()})


def _await_gang_seals(path, process_count, timeout_s, poll_s=0.05):
    """Rank 0's half of the gang commit: block until EVERY rank's shard seal
    exists, then (and only then) is the manifest allowed to be written. A
    rank that died mid-save never seals, the deadline expires, and the tag
    stays torn — which ``load_checkpoint`` already falls back past loudly.
    Raises RuntimeError naming the absent ranks on expiry."""
    import time
    deadline = time.monotonic() + timeout_s
    while True:
        absent = [r for r in range(process_count)
                  if not os.path.isfile(_seal_path(path, r))]
        if not absent:
            return
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"gang checkpoint commit: ranks {absent} never sealed their "
                f"shards within {timeout_s:.1f}s — leaving {path} torn "
                f"(no manifest); a peer likely died mid-save")
        time.sleep(poll_s)


def _maybe_die_during_save(engine, path):
    """``die_during_save`` chaos point (runtime/faults.py): the targeted rank
    SIGKILLs itself between its array commit and its shard seal — the
    mid-save death whose only acceptable outcome is a torn tag."""
    inj = getattr(engine, "_train_faults", None)
    if inj is None:
        return
    import jax
    rank = jax.process_index()
    n = inj.fire_rank("die_during_save", rank)
    if n is not None:
        import signal
        logger.error(f"chaos: rank {rank} dying during save #{n} of {path} "
                     f"(array commit done, shard seal withheld)")
        os.kill(os.getpid(), signal.SIGKILL)


# --------------------------------------------------------------- checksums --
def _crc32_bytes(data, crc=0):
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def _walk_files(root):
    """{relpath: {"size", "crc32"}} for every regular file under ``root``,
    excluding the manifest itself (it seals the others)."""
    out = {}
    for dirpath, _, filenames in os.walk(root):
        for fname in sorted(filenames):
            fp = os.path.join(dirpath, fname)
            rel = os.path.relpath(fp, root)
            if rel == MANIFEST_FILE or not os.path.isfile(fp):
                continue
            out[rel] = {"size": os.path.getsize(fp), "crc32": _file_crc32(fp)}
    return out


def array_checksums(tree):
    """Per-leaf ``{path: {crc32, dtype, shape}}`` over a pytree of arrays —
    the training-state analog of the handoff frame's ``kv_crc32``. Computed
    from a host copy leaf-at-a-time (peak extra memory = one leaf). Returns
    None when any leaf is not fully addressable from this process (multi-host
    meshes: the file-level manifest still covers integrity)."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for keypath, leaf in leaves:
        if leaf is None:
            continue
        if not getattr(leaf, "is_fully_addressable", True):
            return None
        arr = np.asarray(jax.device_get(leaf))
        out[jax.tree_util.keystr(keypath)] = {
            # crc over the buffer itself (no payload-sized .tobytes() copy —
            # the same memoryview treatment the handoff kv_crc32 got)
            "crc32": _crc32_bytes(memoryview(np.ascontiguousarray(arr)).cast("B")),
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
    return out


def _verify_array_checksums(tree, want):
    """Diff a restored pytree against the manifest's per-array CRCs; returns
    the list of mismatched paths."""
    got = array_checksums(tree)
    if got is None:
        return []
    bad = []
    for path, info in (want or {}).items():
        g = got.get(path)
        if g is None or g["crc32"] != info["crc32"]:
            bad.append(path)
    return bad


# ---------------------------------------------------------------- manifest --
def write_manifest(path, meta):
    """Seal a checkpoint directory: walk + checksum every committed file,
    then write MANIFEST.json atomically (tmp + rename) — the LAST write, so
    manifest-present ⟺ commit-completed."""
    manifest = dict(meta)
    manifest["format"] = MANIFEST_FORMAT
    manifest["files"] = _walk_files(path)
    tmp = os.path.join(path, f".{MANIFEST_FILE}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, MANIFEST_FILE))
    return manifest


def read_manifest(path):
    """The manifest dict, or None when absent (torn). Malformed JSON raises
    ValueError (corrupt)."""
    mf = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(mf):
        return None
    try:
        with open(mf) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise ValueError(f"manifest unreadable: {e}") from e


def verify_checkpoint(path):
    """Integrity verdict for one checkpoint directory:

    - ``("good", detail)`` — manifest present, every sealed file exists with
      matching size and CRC32;
    - ``("torn", detail)`` — no manifest (crash mid-commit) or a sealed file
      is missing;
    - ``("corrupt", detail)`` — manifest unreadable, or a sealed file's
      size/CRC32 disagrees with the manifest;
    - ``("reference", detail)`` — reference-DeepSpeed torch shards (the load
      path raises :class:`ReferenceCheckpointError` for these instead).
    """
    if not os.path.isdir(path):
        return "torn", "checkpoint directory does not exist"
    try:
        detect_reference_checkpoint(path)
    except ReferenceCheckpointError as e:
        return "reference", str(e)
    try:
        manifest = read_manifest(path)
    except ValueError as e:
        return "corrupt", str(e)
    if manifest is None:
        return "torn", f"no {MANIFEST_FILE} (commit never completed)"
    for rel, info in manifest.get("files", {}).items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            return "torn", f"sealed file missing: {rel}"
        size = os.path.getsize(fp)
        if size != info["size"]:
            return "corrupt", f"{rel}: size {size} != sealed {info['size']}"
        if _file_crc32(fp) != info["crc32"]:
            return "corrupt", f"{rel}: crc32 mismatch"
    return "good", f"{len(manifest.get('files', {}))} files verified"


def detect_reference_checkpoint(path):
    """Raise :class:`ReferenceCheckpointError` when ``path`` holds the
    reference (torch) DeepSpeed's per-rank shard files — the GPU→TPU
    migration trap (ROADMAP item 5, reject half): an orbax restore over them
    dies with an opaque tensorstore error; name the problem and the path."""
    if not os.path.isdir(path):
        return
    hits = [name for name in sorted(os.listdir(path))
            if name.startswith(_REFERENCE_SHARD_PREFIXES)]
    if hits:
        raise ReferenceCheckpointError(
            f"{path} is a reference DeepSpeed (torch) checkpoint — found "
            f"per-rank shard files {hits[:4]}{'...' if len(hits) > 4 else ''}. "
            f"deepspeed_tpu loads sharded orbax/tensorstore checkpoints. "
            f"Migration path: convert with the reference's "
            f"checkpoint/ds_to_universal.py (universal checkpoint) and ingest "
            f"via the orbax reshard-on-load path (ROADMAP item 5), or re-save "
            f"from this engine with engine.save_checkpoint().")


def list_tags(save_dir):
    """Candidate checkpoint tags under ``save_dir``, NEWEST FIRST, each as
    ``{"tag", "path", "manifest", "status", "detail"}``. Newest = highest
    manifest ``global_steps`` (mtime tiebreak; manifest-less dirs sort by
    mtime only). ``status`` here is the cheap verdict (manifest presence /
    readability); full CRC verification is :func:`verify_checkpoint`."""
    save_dir = os.path.abspath(save_dir)
    if not os.path.isdir(save_dir):
        return []
    out = []
    for name in os.listdir(save_dir):
        path = os.path.join(save_dir, name)
        if not os.path.isdir(path):
            continue
        looks_like_ckpt = (os.path.isfile(os.path.join(path, MANIFEST_FILE))
                           or os.path.isfile(os.path.join(path, "host_state.pkl"))
                           or os.path.isdir(os.path.join(path, "state")))
        if not looks_like_ckpt:
            continue
        entry = {"tag": name, "path": path, "manifest": None,
                 "status": "torn", "detail": f"no {MANIFEST_FILE}",
                 "mtime": os.path.getmtime(path)}
        try:
            manifest = read_manifest(path)
            if manifest is not None:
                entry.update(manifest=manifest, status="committed",
                             detail="manifest present")
        except ValueError as e:
            entry.update(status="corrupt", detail=str(e))
        out.append(entry)

    def sort_key(entry):
        # torn tags have no manifest: fall back to the step number embedded
        # in conventional tag names (global_stepN / preempt_stepN), then mtime
        manifest = entry["manifest"] or {}
        step = manifest.get("global_steps")
        if step is None:
            match = re.search(r"(\d+)$", entry["tag"])
            step = int(match.group(1)) if match else -1
        return (step, entry["mtime"])

    out.sort(key=sort_key, reverse=True)
    return out


def retention_plan(save_dir, keep_last_k):
    """``(keep, drop)`` tag-entry lists for keep-last-K retention. The newest
    K tags survive; the newest *committed* (manifest-sealed) tag ALWAYS
    survives even when older than the window — retention must never delete
    the last good checkpoint. Sealed ≠ CRC-verified (a full CRC walk per
    save would read every checkpoint back): a sealed-but-corrupted-in-place
    newest tag can satisfy the protection, which is why chaos/flaky-disk
    environments should run ``keep_last_k`` ≥ 2 (README)."""
    tags = list_tags(save_dir)
    if keep_last_k is None or keep_last_k <= 0 or len(tags) <= keep_last_k:
        return tags, []
    keep = tags[:keep_last_k]
    drop = tags[keep_last_k:]
    if not any(e["status"] == "committed" for e in keep):
        for e in list(drop):
            if e["status"] == "committed":
                drop.remove(e)
                keep.append(e)
                break
    return keep, drop


def prune_checkpoints(save_dir, keep_last_k):
    """Apply :func:`retention_plan`: delete the dropped tags. Returns the
    deleted tag names."""
    _, drop = retention_plan(save_dir, keep_last_k)
    deleted = []
    for entry in drop:
        try:
            shutil.rmtree(entry["path"])
            deleted.append(entry["tag"])
            _count("pruned")
        except OSError as e:  # a stuck delete must not fail the save
            logger.warning(f"checkpoint retention: could not delete "
                           f"{entry['path']}: {e}")
    if deleted:
        logger.info(f"checkpoint retention: pruned {deleted} "
                    f"(keep_last_k={keep_last_k})")
    return deleted


# -------------------------------------------------------------------- save --
def _world_meta(engine):
    import jax
    return {
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "mesh": {str(k): int(v) for k, v in dict(engine.mesh.shape).items()},
    }


def _manifest_meta(engine, tag, host_state, arrays_crc, keep_last_k):
    """The manifest body, snapshotted SYNCHRONOUSLY at save time — an async
    finalize thread must seal the dispatch-time state, not whatever steps the
    training thread has taken since."""
    import time
    return {
        "tag": str(tag),
        "global_steps": host_state["global_steps"],
        "global_samples": host_state["global_samples"],
        "micro_steps": host_state["micro_steps"],
        "skipped_steps": host_state["skipped_steps"],
        "loss_scale": {k: float(np.asarray(v))
                       for k, v in engine.scale_state._asdict().items()},
        "rng": np.asarray(host_state["rng"]).tolist()
               if host_state.get("rng") is not None else None,
        "data_state": _jsonable(host_state.get("client_state")),
        "world": _world_meta(engine),
        "keep_last_k": keep_last_k,
        "saved_unix": time.time(),
        "arrays": arrays_crc,
    }


def _gang_commit(engine, path, save_dir, tag, host_state, save_latest,
                 manifest_meta, keep_last_k):
    """Cross-rank commit atomicity (ISSUE 12c): per-rank shard seals land
    FIRST — each rank seals only after its own array commit is durable — and
    rank 0 writes the manifest LAST, after a deadline-bounded all-ranks-sealed
    check. A rank dying mid-save therefore yields a manifest-less (torn) tag,
    never a sealed manifest over missing shards. Single-process worlds reduce
    to seal-then-commit with no wait."""
    import jax
    rank = jax.process_index()
    count = jax.process_count()
    _maybe_die_during_save(engine, path)
    _write_rank_seal(path, rank)
    if rank != 0:
        return
    if count > 1:
        ck_cfg = getattr(engine._config, "checkpoint_config", None)
        timeout_s = float(getattr(ck_cfg, "gang_seal_timeout_s", 60.0) or 60.0)
        _await_gang_seals(path, count, timeout_s)
    _commit_host_side(engine, path, save_dir, tag, host_state, save_latest,
                      manifest_meta, keep_last_k)


def _commit_host_side(engine, path, save_dir, tag, host_state, save_latest,
                      manifest_meta, keep_last_k):
    """The durable-marker tail of a save, strictly ordered AFTER the array
    commit: host_state.pkl → MANIFEST.json (atomic, the commit marker) →
    ``latest`` pointer → retention. Only process 0 writes (shared-filesystem
    checkpoints must not see N concurrent writers)."""
    import jax
    if jax.process_index() != 0:
        return
    with open(os.path.join(path, "host_state.pkl"), "wb") as f:
        pickle.dump(host_state, f)
    write_manifest(path, manifest_meta)
    if save_latest:
        # atomic like the manifest: a crash mid-write must never leave an
        # empty/half-written pointer for the next load to chase
        tmp = os.path.join(save_dir, f".{LATEST_FILE}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(tag))
        os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    _count("saves")
    if keep_last_k > 0:
        prune_checkpoints(save_dir, keep_last_k)
    _maybe_inject_checkpoint_fault(engine, path)


def _jsonable(obj):
    """client/dataloader state for the manifest: best-effort JSON projection
    (the authoritative copy lives in host_state.pkl, CRC-sealed)."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


def _maybe_inject_checkpoint_fault(engine, path):
    """Training chaos harness hook (runtime/faults.py): a seeded injector may
    corrupt or truncate the checkpoint that was JUST committed — the torn/
    corrupt fallback path becomes provable end-to-end."""
    inj = getattr(engine, "_train_faults", None)
    if inj is None:
        return
    n = inj.fire("checkpoint_corrupt")
    if n is not None:
        inj.corrupt_checkpoint(path, n)
    if inj.fire("checkpoint_truncate") is not None:
        inj.truncate_checkpoint(path)


def save_engine_state(engine, save_dir, tag, client_state, save_latest,
                      async_save=False):
    """``async_save`` (reference nebula_checkpoint_engine.py role): the array
    commit proceeds on background threads while training continues; the
    host-state + MANIFEST + ``latest`` marker are written only AFTER the
    commit is durable, so a crash mid-commit leaves the previous checkpoint
    current (the reference's tier-commit semantics) and torn by construction
    (no manifest). ``checkpoint_barrier`` (taken by the next save/load, engine
    close, and atexit) bounds in-flight saves to one."""
    import threading

    path = _ckpt_path(save_dir, tag)
    os.makedirs(save_dir, exist_ok=True)

    checkpoint_barrier(engine)  # previous in-flight save must land first

    # re-saving an existing tag (e.g. replaying steps after a sentinel
    # rollback): drop the stale manifest FIRST, synchronously — while the
    # state dir is being rewritten the tag must read as torn, never as a
    # valid-looking seal over mismatched files. Rank 0 drops the WHOLE seal
    # dir (not just its own seal): a peer delayed entering this save must
    # never have its previous-save seal satisfy the all-ranks-sealed check.
    # Orbax's save itself barriers the gang before any rank reaches
    # _gang_commit, so fresh seals are always written after this wipe; if
    # that ordering ever breaks, the failure mode is a seal-wait timeout
    # (torn tag, loud fallback) — never a manifest over mismatched shards.
    import jax as _jax
    stale_manifest = os.path.join(path, MANIFEST_FILE)
    if _jax.process_index() == 0:
        if os.path.isfile(stale_manifest):
            os.unlink(stale_manifest)
        shutil.rmtree(os.path.join(path, SEAL_DIR), ignore_errors=True)
    else:
        _clear_rank_seal(path, _jax.process_index())
    hb = getattr(engine, "_gang_hb", None)
    if hb is not None:
        hb.beat(step=engine.global_steps, phase="save")

    arrays = {
        "params": engine.params,
        "opt_state": _named_opt_state(engine._offload.checkpoint_view(engine.opt_state)),
        "scale_state": engine.scale_state._asdict(),
    }
    host_state = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "skipped_steps": int(engine._overflow_count),
        "current_lr": engine._current_lr,
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        # the per-step rng stream: restoring it makes a resumed run replay the
        # EXACT step sequence an uninterrupted run would have taken (the
        # chaos-equivalence gate's requirement)
        "rng": np.asarray(engine._rng),
        "ds_config": engine._config._param_dict,
        "client_state": client_state,
    }
    ck_cfg = getattr(engine._config, "checkpoint_config", None)
    keep_last_k = int(getattr(ck_cfg, "keep_last_k", 0) or 0)
    # per-array CRCs are computed from a synchronous host snapshot (the async
    # path must checksum BEFORE later donated train steps invalidate the
    # buffers — same reason orbax stages synchronously)
    arrays_crc = array_checksums(arrays) \
        if getattr(ck_cfg, "array_checksums", True) else None
    manifest_meta = _manifest_meta(engine, tag, host_state, arrays_crc,
                                   keep_last_k)

    if not async_save:
        ck = OrbaxCheckpointEngine()
        ck.save(arrays, os.path.join(path, "state"))
        ck.wait()  # checkpoint must be durable before save_checkpoint returns
        _gang_commit(engine, path, save_dir, tag, host_state, save_latest,
                     manifest_meta, keep_last_k)
        logger.info(f"Saved checkpoint to {path}")
        return True

    st = getattr(engine, "_async_ckpt", None)
    if st is None:
        st = engine._async_ckpt = {"thread": None, "ckptr": None}
        # the atexit barrier guarantees the LAST async save of a short-lived
        # trainer still commits (or fails loudly) before interpreter teardown
        import atexit
        import weakref
        atexit.register(_atexit_barrier, weakref.ref(engine))
    if st["ckptr"] is None:
        st["ckptr"] = OrbaxCheckpointEngine(use_async=True)
    ck = st["ckptr"]
    # the async save stages a device→host snapshot synchronously (so later
    # donated train steps can't corrupt it) and commits on background threads
    ck.save(arrays, os.path.join(path, "state"))

    def finalize():
        try:
            ck.finish()
            _gang_commit(engine, path, save_dir, tag, host_state,
                         save_latest, manifest_meta, keep_last_k)
            logger.info(f"Async checkpoint committed to {path}")
        except BaseException as e:  # surfaced at the next checkpoint_barrier
            st["error"] = (tag, e)
            logger.error(f"Async checkpoint commit for {path} FAILED: {e}")

    # non-daemon: the interpreter joins it at exit, so a short-lived trainer
    # can't lose its last checkpoint
    t = threading.Thread(target=finalize, name=f"ckpt-commit-{tag}")
    t.start()
    st["thread"] = t
    logger.info(f"Async checkpoint save dispatched for {path}")
    return True


# -------------------------------------------------------------------- load --
def load_engine_state(engine, load_dir, tag, load_optimizer_states=True, load_lr_scheduler_states=True,
                      load_module_only=False):
    """Verified restore. An explicit ``tag`` is authoritative: a torn/corrupt
    tag raises :class:`CheckpointCorruptionError`. ``tag=None`` asks for the
    newest state: the ``latest`` pointer is tried first, then every other tag
    newest-first — each bad tag is skipped LOUDLY (error log +
    ``checkpoint_load_fallbacks_total``), and only when NO verified-good tag
    remains does the load raise. An empty directory (nothing ever committed)
    still returns ``(None, None)`` — a fresh start, not a failure."""
    checkpoint_barrier(engine)  # an in-flight async save must land first
    load_dir = os.path.abspath(load_dir)
    detect_reference_checkpoint(load_dir)
    ck_cfg = getattr(engine._config, "checkpoint_config", None)
    verify = bool(getattr(ck_cfg, "verify_on_load", True))

    explicit = tag is not None
    if explicit:
        candidates = [str(tag)]
    else:
        tags = list_tags(load_dir)
        latest = os.path.join(load_dir, LATEST_FILE)
        pointed = None
        if os.path.isfile(latest):
            with open(latest) as f:
                pointed = f.read().strip()
        # Fresh start ⟺ nothing was ever COMMITTED: no `latest` pointer (it
        # is written after the first manifest) and no tag carrying a manifest
        # (readable or not). Covers the empty dir, a dangling `latest` with
        # wiped tags, and a crash during the very FIRST save (torn partial
        # state dir) — none of which may crash-loop a supervisor.
        committed_any = any(e["status"] != "torn" for e in tags)
        pointed_exists = pointed is not None and \
            os.path.isdir(_ckpt_path(load_dir, pointed))
        if not committed_any and not pointed_exists:
            logger.warning(
                f"nothing ever committed under {load_dir} "
                f"(latest={'missing' if pointed is None else pointed!r}, "
                f"{len(tags)} torn partial tag(s)), returning (None, None)")
            return None, None
        candidates = ([pointed] if pointed is not None else []) + \
            [e["tag"] for e in tags if e["tag"] != pointed]

    failures = []
    for i, tg in enumerate(candidates):
        path = _ckpt_path(load_dir, tg)
        if not os.path.isdir(path):
            msg = f"checkpoint path {path} does not exist"
            if explicit:
                # explicit tags are authoritative: a typo'd tag must not
                # read as a silent fresh start
                raise CheckpointCorruptionError(msg)
            failures.append(msg)
            logger.error(msg + "; trying the next newest tag")
            continue
        detect_reference_checkpoint(path)  # never a silent orbax stacktrace
        if verify:
            status, detail = verify_checkpoint(path)
            if status != "good":
                _count("verify_failures")
                msg = f"checkpoint {path} is {status.upper()}: {detail}"
                if explicit:
                    raise CheckpointCorruptionError(msg)
                _count("fallbacks")
                failures.append(msg)
                logger.error(f"{msg} — falling back to the newest "
                             f"verified-good tag")
                continue
        try:
            return _restore_into_engine(
                engine, path, load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only,
                verify_arrays=verify and bool(
                    getattr(ck_cfg, "verify_arrays_on_load", False)))
        except ReferenceCheckpointError:
            raise
        except Exception as e:
            # includes an array-seal CheckpointCorruptionError from
            # _restore_into_engine (raised BEFORE any engine state mutates):
            # under tag=None it is one more bad tag to skip, not a dead end
            if explicit:
                raise
            _count("verify_failures")
            _count("fallbacks")
            msg = f"checkpoint {path} failed to restore: {e}"
            failures.append(msg)
            logger.error(f"{msg} — falling back to the newest "
                         f"verified-good tag")
            continue
    raise CheckpointCorruptionError(
        f"no verified-good checkpoint under {load_dir}: " + "; ".join(failures))


def _put_restored(tree, shardings):
    """Multiprocess-safe placement of a restored tree: orbax restored every
    leaf against the engine's CURRENT shardings, so a leaf that is already a
    non-fully-addressable global array is on the right mesh and passes
    through — ``device_put`` would refuse it (it only accepts addressable
    shardings as targets). Fully-addressable leaves (the single-process
    path, and host scalars) keep the defensive device_put."""
    import jax

    def put(leaf, sh):
        if leaf is None:
            return None
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return leaf
        try:
            return jax.device_put(leaf, sh)
        except ValueError:
            # a host value bound for a sharding that spans non-addressable
            # devices (e.g. the replicated loss-scale scalars on a
            # multi-process mesh): place it SPMD via a jitted constant —
            # every process executes this load path at the same point, and
            # the value is identical everywhere (it came from the manifest-
            # sealed checkpoint both read)
            import jax.numpy as jnp
            host = np.asarray(jax.device_get(leaf))
            return jax.jit(lambda: jnp.asarray(host), out_shardings=sh)()

    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree.map(lambda leaf: put(leaf, shardings), tree)
    return jax.tree.map(put, tree, shardings)


def _restore_into_engine(engine, path, load_optimizer_states,
                         load_lr_scheduler_states, load_module_only,
                         verify_arrays):
    import jax

    ck = OrbaxCheckpointEngine()
    # Restore against the engine's current shardings → automatic resharding
    # (the universal-checkpoint reshape of deepspeed/checkpoint/ds_to_universal.py).
    target = {
        "params": _shaped(engine.params, engine._param_shardings),
        "opt_state": _named_opt_state(engine._offload.restore_template(engine.opt_state)),
        "scale_state": {k: v for k, v in engine.scale_state._asdict().items()},
    }
    restored = ck.load(os.path.join(path, "state"), target=target)

    if verify_arrays:
        manifest = read_manifest(path) or {}
        bad = _verify_array_checksums(restored, manifest.get("arrays"))
        if bad:
            raise CheckpointCorruptionError(
                f"checkpoint {path}: restored arrays fail the manifest's "
                f"per-array CRC32 ({bad[:4]}{'...' if len(bad) > 4 else ''})")

    engine.params = _put_restored(restored["params"], engine._param_shardings)
    if load_optimizer_states and not load_module_only:
        # restore straight into the at-rest placement (pinned host when
        # offloaded, NVMe files under ZeRO-Infinity)
        engine.opt_state = engine._offload.accept_restored(
            type(engine.opt_state)(**restored["opt_state"]))
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaleState
        # scalars must live on the CURRENT mesh (restored under a different
        # topology they'd sit on one device and poison the jitted step)
        rep = NamedSharding(engine.mesh, P())
        engine.scale_state = LossScaleState(
            **{k: _put_restored(restored["scale_state"][k], rep)
               for k in ("cur_scale", "good_steps", "hysteresis")})

    with open(os.path.join(path, "host_state.pkl"), "rb") as f:
        host_state = pickle.load(f)
    if not load_module_only:
        import jax.numpy as jnp
        engine.global_steps = host_state["global_steps"]
        engine.global_samples = host_state["global_samples"]
        engine.micro_steps = host_state["micro_steps"]
        engine._current_lr = host_state["current_lr"]
        engine._overflow_count = jnp.asarray(host_state.get("skipped_steps", 0), jnp.int32)
        if host_state.get("rng") is not None:
            # resume the per-step rng stream exactly (pre-manifest checkpoints
            # lack it; they keep the engine's fresh key)
            engine._rng = jnp.asarray(np.asarray(host_state["rng"]))
        if load_lr_scheduler_states and engine.lr_scheduler is not None and host_state["lr_scheduler"]:
            engine.lr_scheduler.load_state_dict(host_state["lr_scheduler"])
    logger.info(f"Loaded checkpoint from {path}")
    return path, host_state.get("client_state", {})


def _named_opt_state(opt_state):
    """NamedTuple → dict (orbax-friendly)."""
    if hasattr(opt_state, "_asdict"):
        return dict(opt_state._asdict())
    return opt_state


def _shaped(tree, shardings):
    return tree
