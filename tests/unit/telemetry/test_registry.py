"""MetricsRegistry primitives, Prometheus exposition and the JSONL sink."""

import json

import pytest

from deepspeed_tpu.telemetry import MetricsRegistry, parse_prometheus_text


def test_counter_gauge_histogram_values():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests")
    c.inc()
    c.inc(4)
    assert c.value == 5

    g = reg.gauge("inflight", "in flight")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.value == 8

    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(5.555)


def test_get_or_create_is_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    assert reg.counter("a") is reg.counter("a")
    # same family, different labels → distinct instruments
    assert reg.counter("b", labels={"op": "x"}) is not reg.counter("b", labels={"op": "y"})
    with pytest.raises(ValueError):
        reg.gauge("a")


def test_histogram_family_shares_one_bucket_layout():
    reg = MetricsRegistry()
    h1 = reg.histogram("bytes", "b", labels={"op": "x"}, buckets=(10.0, 100.0))
    # omitted buckets inherit the family's layout (not the latency defaults)
    h2 = reg.histogram("bytes", "b", labels={"op": "y"})
    assert h2.buckets == h1.buckets == (10.0, 100.0)
    # a conflicting layout in the same family is rejected, not silently mixed
    with pytest.raises(ValueError):
        reg.histogram("bytes", "b", labels={"op": "z"}, buckets=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("bytes", "b", labels={"op": "x"}, buckets=(1.0, 2.0))
    # re-request with the matching layout still returns the same instrument
    assert reg.histogram("bytes", labels={"op": "x"}, buckets=(10.0, 100.0)) is h1


def test_prometheus_render_parse_round_trip():
    reg = MetricsRegistry()
    reg.counter("ops_total", "ops", labels={"op": "all_reduce"}).inc(3)
    reg.gauge("free_blocks", "blocks").set(11)
    h = reg.histogram("lat_seconds", "lat", buckets=(0.01, 1.0))
    h.observe(0.002)
    h.observe(0.5)
    h.observe(100.0)

    text = reg.render_prometheus()
    fams = parse_prometheus_text(text)
    assert fams["ops_total"]["type"] == "counter"
    assert fams["ops_total"]["samples"] == [("ops_total", {"op": "all_reduce"}, 3.0)]
    assert fams["free_blocks"]["samples"][0][2] == 11.0

    hist = {(n, labels.get("le")): v for n, labels, v in fams["lat_seconds"]["samples"]}
    # cumulative bucket semantics: le=0.01 → 1, le=1.0 → 2, +Inf → count=3
    assert hist[("lat_seconds_bucket", "0.01")] == 1.0
    assert hist[("lat_seconds_bucket", "1.0")] == 2.0
    assert hist[("lat_seconds_bucket", "+Inf")] == 3.0
    assert hist[("lat_seconds_count", None)] == 3.0
    assert hist[("lat_seconds_sum", None)] == pytest.approx(100.502)


def test_jsonl_event_sink(tmp_path):
    reg = MetricsRegistry()
    path = tmp_path / "events.jsonl"
    reg.open_jsonl(str(path))
    reg.event("train_step", step=1, loss=0.5)
    reg.event("train_step", step=2, loss=0.25, lr=1e-3)
    reg.close_jsonl()

    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["event"] == "train_step" and lines[0]["loss"] == 0.5
    assert lines[1]["step"] == 2 and lines[1]["lr"] == 1e-3
    assert all("ts" in rec for rec in lines)


def test_histogram_quantile_estimation():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "lat", buckets=(0.01, 0.1, 1.0))
    assert h.quantile(0.5) is None  # no observations yet
    # 100 observations spread 90/10 across the first two buckets
    for _ in range(90):
        h.observe(0.005)
    for _ in range(10):
        h.observe(0.05)
    # p50 interpolates inside the first bucket (0..0.01)
    assert 0.0 < h.quantile(0.5) < 0.01
    # p95 lands mid-way through the second bucket (0.01..0.1)
    assert 0.01 < h.quantile(0.95) < 0.1
    assert h.quantile(0.95) == pytest.approx(0.055, abs=1e-9)
    # monotone in q
    qs = [h.quantile(q) for q in (0.1, 0.5, 0.9, 0.99, 1.0)]
    assert qs == sorted(qs)
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_quantile_tail_clamps_to_last_bucket():
    reg = MetricsRegistry()
    h = reg.histogram("big_seconds", "b", buckets=(0.01, 1.0))
    h.observe(50.0)  # beyond every finite bucket
    assert h.quantile(0.5) == 1.0  # clamped: the true edge is unknown
    # quantile() is a read — not a counted telemetry call
    assert reg.api_calls == 1  # just the observe


def test_histogram_quantile_pinned_edges():
    """The documented q=0 / q=1 / empty contracts (not emergent bucket math)."""
    reg = MetricsRegistry()
    h = reg.histogram("edge_seconds", "e", buckets=(0.01, 0.1, 1.0))
    # empty: None for EVERY q, the edges included
    assert h.quantile(0.0) is None
    assert h.quantile(1.0) is None
    for _ in range(5):
        h.observe(0.05)  # all in the (0.01, 0.1] bucket
    # q=0 is the lower edge of the first non-empty bucket...
    assert h.quantile(0.0) == 0.01
    # ...and q=1 the upper bound (le) of the last non-empty one
    assert h.quantile(1.0) == 0.1
    h.observe(0.005)  # first bucket's lower edge is the implicit 0.0
    assert h.quantile(0.0) == 0.0
    assert h.quantile(1.0) == 0.1


def test_histogram_quantile_edges_with_overflow_tail():
    reg = MetricsRegistry()
    h = reg.histogram("ovf_seconds", "o", buckets=(0.01, 1.0))
    h.observe(50.0)  # every observation past the last finite bucket
    # the tail's true edges are unknown: both ends clamp to the last bound
    assert h.quantile(0.0) == 1.0
    assert h.quantile(1.0) == 1.0
    h.observe(0.5)  # now the (0.01, 1.0] bucket holds the q=0 floor
    assert h.quantile(0.0) == 0.01
    assert h.quantile(1.0) == 1.0  # overflow still clamps the top
    # edge reads are reads: observes were the only counted calls
    assert reg.api_calls == 2


def test_api_call_counting():
    """The registry counts every telemetry API call — the probe the disabled-
    hot-path test relies on."""
    reg = MetricsRegistry()
    assert reg.api_calls == 0
    reg.counter("c").inc()
    reg.gauge("g").set(1)
    reg.histogram("h").observe(0.1)
    reg.event("e")  # counted even with no sink attached
    assert reg.api_calls == 4
