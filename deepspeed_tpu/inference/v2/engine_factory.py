"""Engine construction + generation driver.

Reference: ``deepspeed/inference/v2/engine_factory.py`` (build_hf_engine:66 picks an
InferenceV2Policy by HF ``model_type``). Here model classes consume the training
pytree directly, so the "policy" is a config-type → model-class dispatch.

The decode loop (``generate``) is the serving-side driver the reference leaves to
MII: continuous-batching greedy/temperature sampling over ``engine.put()``.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2


def build_engine(params, model_config, engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Build an InferenceEngineV2 for a training param tree + model config;
    the model class resolves through the policy registry (reference
    engine_factory.py:66-120 model_type dispatch)."""
    from deepspeed_tpu.inference.v2.model_implementations.registry import model_cls_for

    if engine_config is None:
        engine_config = RaggedInferenceEngineConfig()
    model = model_cls_for(model_config)(params, model_config, engine_config)
    return InferenceEngineV2(model, engine_config)


def build_engine_from_ds_checkpoint(path: str,
                                    engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Rebuild an engine from an ``InferenceEngineV2.serialize`` directory
    (reference engine_factory.py:29) — the inference-checkpoint round-trip.
    The config is JSON (never pickle: a checkpoint directory must not be an
    arbitrary-code-execution vector) and its class is restricted to this
    package's model configs."""
    import importlib
    import json
    import os

    import jax.numpy as jnp

    with open(os.path.join(path, "ds_model_config.json")) as f:
        cfg_doc = json.load(f)
    mod_name, _, cls_name = cfg_doc["config_class"].rpartition(".")
    if not mod_name.startswith("deepspeed_tpu."):
        raise ValueError(f"refusing to import config class from {mod_name!r} "
                         "(only deepspeed_tpu model configs are loadable)")
    cfg_cls = getattr(importlib.import_module(mod_name), cls_name)

    def dec(v):
        if isinstance(v, dict) and "__dtype__" in v:
            # restore the jnp SCALAR TYPE (jnp.float32), not np.dtype: they
            # compare equal but models may branch on the exact object
            return getattr(jnp, v["__dtype__"], jnp.dtype(v["__dtype__"]))
        return v

    model_config = cfg_cls(**{k: dec(v) for k, v in cfg_doc["fields"].items()})
    with open(os.path.join(path, "metadata_rank0.json")) as f:
        meta = json.load(f)
    params: Dict = {}
    with np.load(os.path.join(path, "params_rank0.npz")) as z:
        for i, m in enumerate(meta):
            arr = z[f"p{i}"]
            if str(arr.dtype) != m["dtype"]:  # stored as a uint view (bf16)
                arr = jnp.asarray(arr).view(jnp.dtype(m["dtype"]))
            node = params
            keys = m["path"].split("/")
            for k in keys[:-1]:
                node = node.setdefault(k, {})
            node[keys[-1]] = jnp.asarray(arr).reshape(m["shape"])
    return build_engine(params, model_config, engine_config)


def build_hf_engine(path: str, engine_config: Optional[RaggedInferenceEngineConfig] = None):
    """Load an HF checkpoint directory and build an engine (reference
    engine_factory.py:66); a directory written by ``engine.serialize`` routes
    to the DS-checkpoint loader (reference :84 ds_model_config detection)."""
    import os

    if os.path.exists(os.path.join(path, "ds_model_config.json")):
        return build_engine_from_ds_checkpoint(path, engine_config)
    if os.path.exists(os.path.join(path, "ds_model_config.pkl")):
        raise ValueError(
            f"{path} is a LEGACY pickle-format DS checkpoint; the format was "
            "retired (pickle in a checkpoint is an arbitrary-code-execution "
            "vector). Re-serialize the engine with the current code to get "
            "the JSON-config format.")
    from deepspeed_tpu.inference.checkpoint import load_hf_checkpoint

    params, model_config = load_hf_checkpoint(path)
    return build_engine(params, model_config, engine_config)


def generate(engine: InferenceEngineV2,
             prompts: Sequence[Sequence[int]],
             max_new_tokens: int = 16,
             temperature: float = 0.0,
             eos_token_id: Optional[int] = None,
             seed: int = 0,
             decode_chunk: int = 1) -> List[List[int]]:
    """Synchronous continuous-batching decode: a thin wrapper over the serving
    scheduler (``deepspeed_tpu/serving``), so Dynamic SplitFuse admission —
    chunked prefill under the token budget, decode-first batching, KV-pressure
    shrink/evict — exists in exactly one place. Greedy when ``temperature == 0``.

    ``decode_chunk`` > 1 runs decode-only batches in chunks of K steps through
    the engine's on-device ``decode_loop`` (one dispatch per chunk instead of
    one per token); eos is checked between chunks, so a finished sequence
    over-generates up to K-1 discarded tokens before its KV blocks recycle —
    the standard chunked-serving tradeoff of host-RTT against speculative
    compute. The fast path is greedy-only: with ``temperature > 0`` each
    request samples from its own host numpy stream (seeded ``seed + index``)
    through the step-by-step path, so concurrent requests stay independently
    reproducible; greedy output is identical either way.
    """
    from deepspeed_tpu.serving.config import ServingConfig
    from deepspeed_tpu.serving.request import RequestState
    from deepspeed_tpu.serving.scheduler import ServingScheduler

    if len(prompts) == 0:
        return []
    # an engine already serving keeps its scheduler (requests just join the
    # live batch mix); otherwise a temporary one owns the engine for this
    # call and is driven INLINE — no background thread, the caller's thread
    # ticks the scheduler until every request finishes
    scheduler = engine.serving_scheduler
    own_scheduler = scheduler is None
    if own_scheduler:
        scheduler = ServingScheduler(
            engine,
            ServingConfig(queue_capacity=len(prompts), decode_chunk=decode_chunk,
                          default_max_new_tokens=max_new_tokens),
            start=False)
    requests = []
    try:
        for i, p in enumerate(prompts):
            requests.append(scheduler.submit(p, max_new_tokens=max_new_tokens,
                                             temperature=temperature,
                                             eos_token_id=eos_token_id, seed=seed + i))
        if own_scheduler:
            while not all(req.finished for req in requests):
                scheduler.step()
        outputs = []
        for req in requests:
            tokens = req.result()  # raises RuntimeError when the request FAILED
            if req.state is not RequestState.DONE:
                # reachable through a shared scheduler: its default deadline,
                # or a concurrent stop()/engine.close(), can cut the request
                raise RuntimeError(f"generate(): request finished {req.state.name} "
                                   f"after {len(tokens)} of {max_new_tokens} tokens")
            outputs.append(tokens)
        return outputs
    except BaseException:
        # a failed submit (queue full on a shared scheduler) or a failed
        # request must not orphan the rest: nobody will consume them
        for req in requests:
            req.cancel()
        raise
    finally:
        if own_scheduler:
            scheduler.stop(drain=False)
