from deepspeed_tpu.runtime.swap_tensor.partitioned_optimizer_swapper import (NvmeSwappedLeaf,
                                                                             PartitionedOptimizerSwapper)

__all__ = ["PartitionedOptimizerSwapper", "NvmeSwappedLeaf"]
