"""Runtime package. ``DeepSpeedOptimizer``/``ZeROOptimizer`` are the
reference's marker base classes (``deepspeed/runtime/__init__.py``) used by
callers for isinstance checks on wrapped optimizers."""


class DeepSpeedOptimizer:
    pass


class ZeROOptimizer(DeepSpeedOptimizer):
    pass
