"""XLA recompilation watch.

The repo's top TPU perf hazard is silent recompilation (see
``inference/v2/ragged/ragged_wrapper.py`` — every distinct padded batch bucket
is one compiled program, and a shape that slips past the bucketing recompiles
the decode path mid-traffic). This module makes recompiles measurable:

- A process-wide ``jax.monitoring`` duration listener catches every XLA
  backend compile (``/jax/core/compile/backend_compile_duration``) and turns
  it into ``compile_cache_misses_total``/``compile_seconds_total`` metrics, a
  ``xla_compile`` span (so recompiles show up inline in traces, attributed to
  whatever request/batch was running) and a JSONL event carrying the
  triggering key.
- ``wrap(site, key, fn)`` hooks a jit-cache entry at its creation site (the
  training engine's ``_compiled`` builds, the inference model's per-bucket
  forward/decode programs): every call through the wrapper makes the site and
  cache key ambient, so a compile firing inside is attributed to it — including
  shape-triggered recompiles jax performs internally on a cached callable.
- ``note_bucket(bucket)`` hooks the ragged batch bucketing
  (``RaggedBatchWrapper.finalize``): a batch landing in a bucket NOT seen
  among the last few distinct buckets increments
  ``compile_bucket_switches_total`` — shape churn that predicts (and
  explains) cache misses. The recently-seen window matters: steady-state
  SplitFuse traffic alternates prefill and decode buckets every batch, and
  counting those (already-compiled) alternations would saturate the metric
  with noise.

Hot-path contract: when telemetry is disabled ``get()`` is None and every call
site is a single global-read + None check; the monitoring listener is
registered at most once per process and forwards nothing while disabled.
"""

import threading
from collections import OrderedDict
from contextvars import ContextVar

from deepspeed_tpu.telemetry.spans import now_us

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# ambient (site, key) while a wrapped jit callable executes
_SITE_CTX: ContextVar = ContextVar("dstpu_compile_site", default=None)

# wrapped-call occupancy BY THREAD, module-global (like _SITE_CTX) so a
# telemetry reconfigure mid-call cannot strand the in-flight occupancy on a
# displaced watch: the flight-recorder watchdog uses this to tell "this
# loop's thread is blocked in a long XLA compile" apart from a genuinely
# wedged loop — per-thread, so a co-located trainer's watched calls grant no
# amnesty to a wedged serving loop
_OCCUPANCY_LOCK = threading.Lock()
_ACTIVE_THREADS = {}  # thread ident -> wrapped-call depth

_WATCH = None  # the active CompileWatch, None when telemetry is disabled
_LISTENER_LOCK = threading.Lock()
_LISTENER_REGISTERED = False

METRIC_NAMES = ("compile_cache_misses_total", "compile_seconds_total",
                "compile_cache_entries", "compile_bucket_switches_total")


def get():
    """The active watch (None disabled) — the one check on hot paths."""
    return _WATCH


def _on_event_duration(event, duration_secs, **kwargs):
    watch = _WATCH
    if watch is not None and event == _BACKEND_COMPILE_EVENT:
        watch._record_compile(duration_secs)


def _ensure_listener():
    """Register the jax.monitoring listener once per process (jax offers no
    per-listener unregister; the callback is a no-op while ``_WATCH`` is
    None, so leaving it registered is free)."""
    global _LISTENER_REGISTERED
    with _LISTENER_LOCK:
        if _LISTENER_REGISTERED:
            return
        try:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
            _LISTENER_REGISTERED = True
        except Exception:  # pragma: no cover - jax too old / absent: the
            # wrap()/note_bucket() site hooks still count entries and switches
            _LISTENER_REGISTERED = True


class CompileWatch:
    """Compile accounting on one registry + span recorder pair."""

    def __init__(self, registry, spans=None):
        self._registry = registry
        self._spans = spans
        self._lock = threading.Lock()
        self._site_metrics = {}  # site -> (misses counter, seconds counter, entries gauge)
        self._recent_buckets = OrderedDict()  # LRU of the last distinct buckets
        self._bucket_switches = registry.counter(
            "compile_bucket_switches_total",
            "Ragged batches landing in a pad bucket not recently seen")

    def _metrics_for(self, site):
        with self._lock:
            m = self._site_metrics.get(site)
            if m is None:
                labels = {"site": site}
                m = (self._registry.counter(
                         "compile_cache_misses_total",
                         "XLA backend compiles (jit cache misses)", labels=labels),
                     self._registry.counter(
                         "compile_seconds_total",
                         "Cumulative XLA backend compile wall seconds", labels=labels),
                     self._registry.gauge(
                         "compile_cache_entries",
                         "Live jit cache entries created at this site", labels=labels))
                self._site_metrics[site] = m
        return m

    # ------------------------------------------------------------- listener --
    def _record_compile(self, seconds):
        ctx = _SITE_CTX.get()
        site, key = ctx if ctx is not None else ("other", None)
        misses, secs, _ = self._metrics_for(site)
        misses.inc()
        secs.inc(seconds)
        end = now_us()
        dur = int(seconds * 1e6)
        args = {"site": site}
        if key is not None:
            args["key"] = repr(key)
        if self._spans is not None:
            self._spans.record("xla_compile", cat="compile", ts_us=end - dur,
                               dur_us=dur, args=args)
        self._registry.event("xla_compile", seconds=seconds, **args)

    # ------------------------------------------------------------ site hooks --
    def wrap(self, site, key, fn):
        """Wrap a fresh jit cache entry: counts it, and makes (site, key)
        ambient during every call so compiles inside attribute here."""
        self._metrics_for(site)[2].inc()

        def watched(*args, **kwargs):
            # check the ACTIVE watch, not the one that built this wrapper:
            # jit-cache entries outlive telemetry sessions, and a disabled
            # process pays one global read and nothing else (occupancy itself
            # is module-global, so it also survives a reconfigure mid-call)
            if _WATCH is None:
                return fn(*args, **kwargs)
            token = _SITE_CTX.set((site, key))
            ident = threading.get_ident()
            with _OCCUPANCY_LOCK:
                _ACTIVE_THREADS[ident] = _ACTIVE_THREADS.get(ident, 0) + 1
            try:
                return fn(*args, **kwargs)
            finally:
                with _OCCUPANCY_LOCK:
                    depth = _ACTIVE_THREADS[ident] - 1
                    if depth:
                        _ACTIVE_THREADS[ident] = depth
                    else:
                        del _ACTIVE_THREADS[ident]
                _SITE_CTX.reset(token)

        return watched

    @staticmethod
    def in_wrapped_call(thread_ident=None) -> bool:
        """True while a wrapped jit callable is executing — on the given
        thread, or on any thread when ``thread_ident`` is None."""
        if thread_ident is None:
            return bool(_ACTIVE_THREADS)
        return thread_ident in _ACTIVE_THREADS

    # buckets tracked before a re-entry counts as churn: SplitFuse steadily
    # alternates prefill and decode buckets (already compiled — not churn),
    # and a serving process cycles through only a handful of live buckets
    _RECENT_BUCKET_WINDOW = 8

    def note_bucket(self, bucket):
        """Called by RaggedBatchWrapper.finalize with the padded
        (tokens, sequences, blocks) bucket of each batch. A bucket absent
        from the recently-seen window counts as a switch — churn that
        predicts a recompile — while alternating between live buckets does
        not (the very first bucket is the baseline, not a switch)."""
        with self._lock:
            switched = bucket not in self._recent_buckets and bool(self._recent_buckets)
            self._recent_buckets[bucket] = None
            self._recent_buckets.move_to_end(bucket)
            if len(self._recent_buckets) > self._RECENT_BUCKET_WINDOW:
                self._recent_buckets.popitem(last=False)
        if switched:
            self._bucket_switches.inc()


def install(registry, spans=None):
    """Activate the watch (TelemetrySession does this when telemetry turns
    on). Returns the watch; replaces any previous one."""
    global _WATCH
    _ensure_listener()
    _WATCH = CompileWatch(registry, spans=spans)
    return _WATCH


def uninstall(watch=None):
    """Deactivate (a no-op if ``watch`` is given and is no longer active)."""
    global _WATCH
    if watch is None or _WATCH is watch:
        _WATCH = None
