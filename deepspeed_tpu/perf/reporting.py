"""Pure rendering for perf-gate artifacts (no jax imports — safe for
``bin/dstpu_report --perf`` on a machine with no backend at all).

Input is either a gate-report JSON (``dstpu_perfgate diff --json <out>``)
or a budgets directory; output is the human table."""

import json
import os
from typing import List

from deepspeed_tpu.perf.budgets import list_budgets

GREEN_OK = "\033[92m[OK]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}"
        n /= 1024
    return f"{n:,.1f} GiB"


def _fmt_flops(n) -> str:
    n = float(n)
    for unit, div in (("GF", 1e9), ("MF", 1e6), ("kF", 1e3)):
        if abs(n) >= div:
            return f"{n / div:,.2f} {unit}"
    return f"{n:,.0f} F"


def render_gate_report(report: dict, checked: bool = True) -> str:
    """``checked=False`` renders stats/rooflines only — ``inspect`` never
    consults the budget files, so it must not print a budget verdict a
    ``diff`` would contradict."""
    lines: List[str] = []
    lines.append("-" * 78)
    title = f"perf gate report (chip model: {report.get('chip', '?')})"
    if not checked:
        title += " — stats only, budgets NOT checked (run diff)"
    lines.append(title)
    lines.append("-" * 78)
    header = (f"{'program':<26} {'flops':>10} {'bytes':>12} {'peak':>12} "
              f"{'coll':>10} {'f32dots':>7}" + ("  verdict" if checked else ""))
    lines.append(header)
    for name, prog in sorted(report.get("programs", {}).items()):
        s = prog.get("stats", {})
        verdict = ""
        if checked:
            verdict = "  " + (GREEN_OK if prog.get("ok") else RED_NO)
            if prog.get("budget_missing"):
                verdict += " (no budget file — rebaseline)"
        lines.append(f"{name:<26} {_fmt_flops(s.get('flops', 0)):>10} "
                     f"{_fmt_bytes(s.get('bytes_accessed', 0)):>12} "
                     f"{_fmt_bytes(s.get('peak_bytes', 0)):>12} "
                     f"{_fmt_bytes(s.get('collective_bytes_total', 0)):>10} "
                     f"{s.get('f32_dot_count', 0):>7}{verdict}")
        rl = prog.get("roofline") or {}
        if rl:
            lines.append(f"{'':<26} roofline: {rl.get('bound', '?')}-bound, "
                         f"step >= {rl.get('step_s', 0) * 1e6:,.1f} us, "
                         f"MFU <= {rl.get('mfu_bound', 0):.1%}")
        for v in prog.get("violations", []):
            lines.append(f"{'':<26} VIOLATION {v['metric']}: measured "
                         f"{v['measured']:g} > limit {v['limit']:g} "
                         f"(budget {v['budget']:g})"
                         + (f" — {v['detail']}" if v.get("detail") else ""))
    lines.append("-" * 78)
    if checked:
        lines.append(f"verdict ................ "
                     f"{GREEN_OK + ' within budgets' if report.get('ok') else RED_NO + ' budget violations'}")
    return "\n".join(lines)


def render_budgets_dir(budgets_dir: str) -> str:
    lines = ["-" * 78, f"perf budgets in {budgets_dir}", "-" * 78]
    names = list_budgets(budgets_dir)
    if not names:
        lines.append("(no budget files; create them with bin/dstpu_perfgate rebaseline)")
    for name in names:
        with open(os.path.join(budgets_dir, f"{name}.json")) as f:
            b = json.load(f)
        s = b.get("stats", {})
        lines.append(f"{name:<26} flops={_fmt_flops(s.get('flops', 0))} "
                     f"bytes={_fmt_bytes(s.get('bytes_accessed', 0))} "
                     f"peak={_fmt_bytes(s.get('peak_bytes', 0))} "
                     f"colls={len(s.get('collectives', {}))} "
                     f"created={b.get('created', '?')}")
        rl = b.get("roofline") or {}
        if rl:
            lines.append(f"{'':<26} roofline({rl.get('chip', '?')}): "
                         f"{rl.get('bound', '?')}-bound, "
                         f"step >= {rl.get('step_s', 0) * 1e6:,.1f} us, "
                         f"MFU <= {rl.get('mfu_bound', 0):.1%}")
    lines.append("-" * 78)
    return "\n".join(lines)


def perf_report(path: str) -> int:
    """``dstpu_report --perf <budgets-dir | gate-report.json>``. A directory
    renders its budget files (and, if a ``gate_report.json`` the CLI wrote is
    present, the current-vs-budget table from it); a file is a gate report.
    Returns a process exit code (1 = violations recorded)."""
    if os.path.isfile(path):
        with open(path) as f:
            report = json.load(f)
        print(render_gate_report(report))
        return 0 if report.get("ok") else 1
    if not os.path.isdir(path):
        print(f"--perf: {path} is neither a budgets dir nor a gate-report JSON")
        return 2
    rc = 0
    report_path = os.path.join(path, "gate_report.json")
    if os.path.isfile(report_path):
        with open(report_path) as f:
            report = json.load(f)
        print(render_gate_report(report))
        rc = 0 if report.get("ok") else 1
    print(render_budgets_dir(path))
    return rc
