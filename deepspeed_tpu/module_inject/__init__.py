from deepspeed_tpu.module_inject.auto_tp import auto_tp_specs
from deepspeed_tpu.module_inject.layers import (EmbeddingLayer, LinearAllreduce, LinearLayer,
                                                Normalize)
from deepspeed_tpu.module_inject.replace_module import (replace_transformer_layer,
                                                        revert_transformer_layer)
