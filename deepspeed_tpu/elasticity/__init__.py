from deepspeed_tpu.elasticity.elasticity import (ElasticityConfig, ElasticityError,
                                                 compute_elastic_config, elasticity_enabled)
