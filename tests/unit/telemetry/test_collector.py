"""TraceCollector: clock-offset correction, dedupe, incremental pulls with
lookback, shared-recorder skip, trace eviction, and the merged Chrome doc."""

import json
import os

from deepspeed_tpu.telemetry import MetricsRegistry, SpanRecorder
from deepspeed_tpu.telemetry.collector import LOOKBACK_US, TraceCollector
from deepspeed_tpu.telemetry.spans import now_us


class _FakeReplica:
    """A wire replica: spans stamped on a skewed remote clock."""

    def __init__(self, replica_id, pid, skew_us=0, shared=None):
        self.id = replica_id  # fleet Replica identity attribute
        self.pid = pid
        self.skew_us = skew_us
        self.span_recorder = shared  # None = over-the-wire (HttpReplica)
        self.spans = []
        self.calls = []  # since_us of every pull, for incremental asserts
        self.fail = False

    def add(self, name, ts_us, dur_us=10, trace_id="t1", span_id=None,
            parent_id=None):
        self.spans.append({"name": name, "cat": "serving", "ts_us": ts_us,
                           "dur_us": dur_us, "trace_id": trace_id,
                           "span_id": span_id, "parent_id": parent_id,
                           "args": {}})

    def collect_spans(self, since_us):
        self.calls.append(since_us)
        if self.fail:
            raise OSError("replica unreachable")
        return {"pid": self.pid,
                "now_us": now_us() + self.skew_us,
                "dropped": 0,
                "spans": [s for s in self.spans if s["ts_us"] >= since_us]}


def test_clock_offset_correction_aligns_remote_spans():
    """A replica whose clock runs 5s ahead: its spans come back corrected
    onto the collector's clock — a leg span lands INSIDE the router span
    instead of five seconds in the future."""
    collector = TraceCollector()
    local = SpanRecorder()
    t = now_us()
    local.record("route", ts_us=t, dur_us=2000, trace_id="t1", span_id="r")

    skew = 5_000_000
    replica = _FakeReplica("r0", pid=4242, skew_us=skew)
    # the leg started 100us into the route — stamped on the skewed clock
    replica.add("request", ts_us=t + 100 + skew, dur_us=1000,
                span_id="q", parent_id="r")

    collector.collect(recorder=local, replicas=[replica])
    evs = collector.spans_for("t1")
    assert [e["name"] for e in evs] == ["route", "request"]
    route, request = evs
    # corrected: nested inside the route span, not 5s away (the pull
    # round-trip bounds the residual error; be generous)
    assert abs(request["ts"] - (t + 100)) < 100_000
    assert route["ts"] <= request["ts"]
    assert request["ts"] + request["dur"] <= route["ts"] + route["dur"] + 100_000
    assert request["pid"] == 4242 and request["args"]["source"] == "replica:r0"
    assert route["pid"] == os.getpid() and route["args"]["source"] == "local"


def test_incremental_pulls_lookback_and_dedupe():
    collector = TraceCollector()
    replica = _FakeReplica("r0", pid=7, skew_us=0)
    base = now_us()
    replica.add("a", ts_us=base, span_id="s-a")
    collector.collect(replicas=[replica])
    assert replica.calls == [0]  # first pull drains from the beginning
    assert collector.spans_collected == 1

    # the next pull asks only for the recent window (high-water - lookback)
    collector.collect(replicas=[replica])
    assert replica.calls[1] > 0
    assert replica.calls[1] >= base - LOOKBACK_US - 1_000_000
    # span "a" was re-sent inside the lookback overlap: deduped, not doubled
    assert collector.spans_collected == 1
    assert len(collector.spans_for("t1")) == 1

    # same span_id from a DIFFERENT pid is a distinct span (no cross-process
    # id collision risk)
    other = _FakeReplica("r1", pid=8)
    other.add("a", ts_us=base, span_id="s-a")
    collector.collect(replicas=[other])
    assert len(collector.spans_for("t1")) == 2


def test_shared_recorder_replicas_are_skipped():
    """LocalReplica shares the process-global ring with the router: reading
    it again would double every span, so recorder-identity dedupe skips it
    (and skips the offset math — same process, same clock)."""
    collector = TraceCollector()
    local = SpanRecorder()
    local.record("route", ts_us=now_us(), dur_us=5, trace_id="t1", span_id="r")
    shared = _FakeReplica("local0", pid=1, shared=local)
    collector.collect(recorder=local, replicas=[shared])
    assert shared.calls == []  # never pulled
    assert len(collector.spans_for("t1")) == 1
    # two local replicas sharing one ring: only the first is read
    collector2 = TraceCollector()
    a = _FakeReplica("a", pid=1, shared=local)
    b = _FakeReplica("b", pid=1, shared=local)
    a.add("x", ts_us=now_us(), span_id="sx")
    collector2.collect(replicas=[a, b])
    assert a.calls and b.calls == []


def test_unreachable_replica_skips_the_round_not_the_fleet():
    collector = TraceCollector()
    dead = _FakeReplica("dead", pid=2)
    dead.fail = True
    live = _FakeReplica("live", pid=3)
    live.add("request", ts_us=now_us(), span_id="s1")
    collector.collect(replicas=[dead, live])
    assert len(collector.spans_for("t1")) == 1
    assert "replica:dead" not in collector.describe()["sources"]


def test_spans_without_trace_id_are_dropped_and_traces_evict():
    collector = TraceCollector(max_traces=2)
    replica = _FakeReplica("r0", pid=9)
    t = now_us()
    replica.add("orphan", ts_us=t, trace_id=None, span_id="o")
    for i in range(3):
        replica.add("request", ts_us=t + i, trace_id=f"trace{i}",
                    span_id=f"s{i}")
    collector.collect(replicas=[replica])
    assert collector.trace_ids() == ["trace1", "trace2"]  # oldest evicted
    assert collector.spans_collected == 3  # the orphan never counted


def test_chrome_trace_meta_and_counters():
    reg = MetricsRegistry()

    class _M:  # the FleetMetrics shape the collector consumes
        trace_collections = reg.counter("fleet_trace_collections_total", "c")
        trace_spans_collected = reg.counter("fleet_trace_spans_collected_total", "s")

    collector = TraceCollector(metrics=_M())
    local = SpanRecorder()
    t = now_us()
    local.record("route", ts_us=t, dur_us=10, trace_id="tA", span_id="r1")
    replica = _FakeReplica("r0", pid=555)
    replica.add("request", ts_us=t + 1, trace_id="tA", span_id="q1",
                parent_id="r1")
    replica.add("request", ts_us=t + 2, trace_id="tB", span_id="q2")
    collector.collect(recorder=local, replicas=[replica])

    doc = collector.chrome_trace()
    json.dumps(doc)  # wire-clean
    assert doc["displayTimeUnit"] == "ms"
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    names = {e["name"] for e in meta}
    assert names == {"process_name", "thread_name"}
    proc_names = {e["args"]["name"] for e in meta if e["name"] == "process_name"}
    assert "local" in proc_names and "replica:r0" in proc_names
    # one tid per trace, stable across processes
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    tids = {e["args"]["trace_id"]: e["tid"] for e in events}
    assert len(set(tids.values())) == 2
    assert doc["collector"]["collections"] == 1
    assert doc["collector"]["spans_collected"] == 3

    # filtered export: one trace only
    one = collector.chrome_trace("tB")
    assert {e["args"]["trace_id"] for e in one["traceEvents"]
            if e["ph"] == "X"} == {"tB"}

    assert reg.counter("fleet_trace_collections_total").value == 1
    assert reg.counter("fleet_trace_spans_collected_total").value == 3
