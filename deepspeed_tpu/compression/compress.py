"""Config-driven model compression.

Reference: ``deepspeed/compression/compress.py`` (``init_compression:100``
walks the model swapping layers for compressed variants per config patterns;
``redundancy_clean:148`` materializes structured pruning). TPU formulation:
the "model" is a parameter pytree — compression is a tree transform keyed by
the same config schema (``weight_quantization`` / ``sparse_pruning`` /
``row_pruning`` / ``head_pruning`` blocks with ``modules`` glob patterns).
"""

import fnmatch
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import (apply_head_mask, fake_quantize,
                                                  head_prune_mask, row_prune_mask)
from deepspeed_tpu.utils.logging import logger


def get_compression_config(param_dict: dict) -> dict:
    return param_dict.get("compression_training", {})


def _path_str(path):
    return ".".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)


def _matches(name: str, patterns) -> bool:
    return any(fnmatch.fnmatch(name, f"*{p}*") if "*" not in p else fnmatch.fnmatch(name, p)
               for p in patterns)


def _block(cfg: dict, key: str):
    """shared_parameters + the first enabled group's modules/params."""
    blk = cfg.get(key, {})
    shared = blk.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return None
    groups = blk.get("different_groups", {})
    out = []
    for g in groups.values():
        params = g.get("params", {})
        out.append((g.get("modules", ["*"]), params))
    return {"shared": shared, "groups": out}


def init_compression(params, deepspeed_config: dict, mpu=None):
    """Apply the configured compression transforms to a parameter pytree
    (reference init_compression:100 — layer swap becomes a leaf transform).
    Returns the new pytree; fake-quant keeps shapes/dtypes."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict)
                                 else {})
    wq = _block(cfg, "weight_quantization")
    rp = _block(cfg, "row_pruning")
    hp = _block(cfg, "head_pruning")
    sp = _block(cfg, "sparse_pruning")

    def transform(path, leaf):
        if getattr(leaf, "ndim", 0) < 2:
            return leaf
        name = _path_str(path)
        out = leaf
        if wq is not None:
            for patterns, p in wq["groups"]:
                if _matches(name, patterns):
                    bits = p.get("start_bits", p.get("target_bits", 8))
                    out = fake_quantize(out, bits=int(bits),
                                        symmetric=p.get("quantization_type", "symmetric")
                                        == "symmetric")
                    break
        if sp is not None:
            for patterns, p in sp["groups"]:
                if _matches(name, patterns):
                    ratio = float(p.get("dense_ratio", 0.5))
                    k = int(np.ceil((1 - ratio) * out.size))
                    if k > 0:
                        flat = jnp.abs(out).reshape(-1)
                        thresh = jnp.sort(flat)[k - 1]
                        out = out * (jnp.abs(out) > thresh).astype(out.dtype)
                    break
        if rp is not None:
            for patterns, p in rp["groups"]:
                if _matches(name, patterns):
                    mask = row_prune_mask(out, float(p.get("row_sparsity", 0.5)), axis=0)
                    out = out * mask[:, None].astype(out.dtype)
                    break
        if hp is not None:
            for patterns, p in hp["groups"]:
                if _matches(name, patterns):
                    heads = int(p.get("num_heads", 1))
                    mask = head_prune_mask(out, float(p.get("head_sparsity", 0.5)), heads)
                    out = apply_head_mask(out, mask, heads)
                    break
        return out

    new = jax.tree_util.tree_map_with_path(transform, params)
    logger.info("init_compression: applied "
                + ", ".join(k for k, v in (("weight_quantization", wq), ("row_pruning", rp),
                                           ("head_pruning", hp), ("sparse_pruning", sp))
                            if v is not None))
    return new


def redundancy_clean(params, deepspeed_config: dict, mpu=None):
    """Materialize structured pruning: physically drop zeroed rows (reference
    redundancy_clean:148 shrinks the swapped layers). Only row pruning changes
    shapes; masked-but-kept transforms are already materialized in the tree."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict)
                                 else {})
    rp = _block(cfg, "row_pruning")
    if rp is None:
        return params

    def transform(path, leaf):
        if getattr(leaf, "ndim", 0) != 2:
            return leaf
        name = _path_str(path)
        for patterns, p in rp["groups"]:
            if _matches(name, patterns):
                keep = np.asarray(jnp.any(jnp.asarray(leaf) != 0, axis=1))
                return jnp.asarray(leaf)[keep]
        return leaf

    return jax.tree_util.tree_map_with_path(transform, params)
