"""Automatic prefix cache: a radix/trie index over the paged KV cache.

Role model: vLLM's automatic prefix caching / SGLang's RadixAttention — the
mechanism that makes the shared-system-prompt workload (N requests over one
long common prefix) pay prefill once instead of N times.

Design
------
The unit of sharing is one **full KV block** (``block_size`` tokens). Each
trie node represents one block's worth of tokens and is keyed by a *chained*
content hash: ``digest(node) = sha1(digest(parent) + token_bytes(block))``, so
a node's identity pins the entire token prefix up to and including its block —
two prompts share a node iff they share every token up to that block boundary.

Ownership is reference counts on the :class:`~.blocked_allocator.BlockedAllocator`:

- the **trie holds one reference** on every block it indexes;
- every live sequence holds one reference on each block in its table (its
  private blocks arrive at refcount 1 from ``allocate``; shared prefix blocks
  are increffed by :meth:`acquire`);
- a sequence flush *decrefs* (``kv_cache.free``), so publishing a finished
  sequence's blocks and then flushing it leaves exactly the trie's reference;
- evicting a trie leaf decrefs once — the device block is reclaimed only when
  no live sequence still maps it.

Writes never touch shared blocks: a hit is block-aligned, so the suffix's KV
scatters land in freshly-allocated blocks — except a **fully-cached prompt**,
whose re-fed final token would write into the last shared block; the scheduler
forks that block copy-on-write (``kv_cache.fork_blocks``) before mapping it.

Eviction is LRU over *evictable leaves*: leaf nodes whose block has refcount 1
(the trie's own — no live sharer; freeing a shared leaf reclaims nothing).
Interior nodes become evictable once their children go.

Thread model: all mutation happens on the serving scheduler's thread (the
engine-owning thread); the stats snapshot reads scalar counters and is safe
from any thread.
"""

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

DIGEST_HEX = 16
"""Truncated-hex width of a digest as published in probe docs / fleet stats.
64 bits is plenty for a routing *hint* (the worst a collision costs is one
misrouted dispatch that then misses locally); the fetch path always matches
full 20-byte digests."""


def digest_chain(tokens, block_size: int,
                 base: Optional[List[bytes]] = None) -> List[bytes]:
    """Chained sha1 digests of every *full* ``block_size`` run of ``tokens``:
    ``digest[i] = sha1(digest[i-1] + token_bytes(block_i))``. The one hashing
    authority — :meth:`PrefixCache.chain` and the fleet router's cache-aware
    placement both call this, so a replica's published catalog and the
    router's request chain can never disagree on the algorithm. ``base``
    seeds the chain with already-computed leading digests."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    n_full = tokens.size // block_size
    out = list(base[:n_full]) if base else []
    digest = out[-1] if out else b""
    for i in range(len(out), n_full):
        h = hashlib.sha1()
        h.update(digest)
        h.update(np.ascontiguousarray(
            tokens[i * block_size:(i + 1) * block_size],
            dtype=np.int32).tobytes())
        digest = h.digest()
        out.append(digest)
    return out


class _Node:
    __slots__ = ("digest", "block", "parent", "children", "last_touch",
                 "tokens", "tier", "handle")

    def __init__(self, digest: bytes, block: int, parent: Optional["_Node"],
                 tokens: Optional[np.ndarray] = None):
        self.digest = digest
        self.block = block          # device block id this node owns a ref on
        self.parent = parent
        self.children: Dict[bytes, "_Node"] = {}
        self.last_touch = 0
        # the block's token ids (host copy): what a prompt-lookup drafter
        # mines — the trie holds exactly the token histories it wants
        self.tokens = tokens
        # tier ladder state (ragged.tiering.TIERS): a "device" node owns a
        # trie reference on `block`; a demoted node owns `handle` in the
        # tiered store instead (block is -1) and promotes back on the next
        # acquire that walks through it
        self.tier = "device"
        self.handle: Optional[int] = None


class PrefixHit:
    """A successful :meth:`PrefixCache.acquire`: ``blocks`` are device block
    ids (one reference each now held on the caller's behalf) covering
    ``tokens`` leading prompt tokens."""

    __slots__ = ("blocks", "tokens")

    def __init__(self, blocks: List[int], tokens: int):
        self.blocks = blocks
        self.tokens = tokens


class PrefixCache:
    """Radix index + refcount choreography over one :class:`BlockedKVCache`.

    ``max_blocks`` caps how many device blocks the trie may pin (None = the
    whole pool — under KV pressure the scheduler evicts trie leaves before
    touching live sequences, so an uncapped trie is backpressured naturally).
    ``min_prefix_blocks`` is the smallest match worth applying: shorter hits
    return empty (the bookkeeping would cost more than the saved prefill).
    """

    def __init__(self, kv_cache, max_blocks: Optional[int] = None,
                 min_prefix_blocks: int = 1):
        self._kv = kv_cache
        self._block_size = kv_cache.block_size
        self._max_blocks = max_blocks
        self._min_prefix_blocks = max(1, int(min_prefix_blocks))
        self._root = _Node(b"", -1, None)
        self._by_digest: Dict[bytes, _Node] = {}
        # guards _by_digest's structure only: mutation stays on the scheduler
        # thread, but digest_catalog() snapshots from probe threads
        self._index_lock = threading.Lock()
        self._clock = 0  # monotonic LRU counter (no wall clock: deterministic)
        self._device_nodes = 0  # nodes whose tier is "device" (pinned blocks)
        # stats (read lock-free from stats threads; written on scheduler thread)
        self.lookups = 0
        self.hits = 0
        self.hit_blocks = 0
        self.tokens_served = 0   # prompt tokens served from cache
        self.evictions = 0       # trie leaves evicted (blocks unpinned)
        self.published_blocks = 0
        self.tier_demotions = 0   # nodes moved device→store (host tier)
        self.tier_promotions = 0  # nodes moved store→device on acquire
        self.promote_failures = 0  # promotions lost to device pressure

    # ------------------------------------------------------------- hashing --
    def chain(self, tokens, base: Optional[List[bytes]] = None) -> List[bytes]:
        """Chained digests of every *full* block of ``tokens``. ``base`` seeds
        the chain with digests already computed for the leading blocks (the
        scheduler hashes each prompt once at admission and extends over the
        generated tail at publish time, instead of re-hashing the whole
        history on the hot thread)."""
        return digest_chain(tokens, self._block_size, base=base)

    # -------------------------------------------------------------- lookup --
    def acquire(self, prompt, digests: Optional[List[bytes]] = None) -> PrefixHit:
        """Longest cached prefix of ``prompt``, with one reference taken on
        every matched block (release with :meth:`release`, or hand them to a
        sequence whose flush decrefs). Matches shorter than
        ``min_prefix_blocks`` blocks come back empty. ``digests`` is the
        prompt's precomputed :meth:`chain`."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.lookups += 1
        node = self._root
        matched: List[_Node] = []
        for digest in (digests if digests is not None else self.chain(prompt)):
            child = node.children.get(digest)
            if child is None:
                break
            if child.tier != "device" and not self._promote(child):
                # demoted node and no room to bring it back: the hit ends at
                # the deepest device-resident (or promotable) depth — a miss
                # at this depth, never a stall
                break
            matched.append(child)
            node = child
        if len(matched) < self._min_prefix_blocks:
            return PrefixHit([], 0)
        self._clock += 1
        for n in matched:
            n.last_touch = self._clock  # whole path stays warm
        blocks = [n.block for n in matched]
        self._kv.incref(blocks)
        return PrefixHit(blocks, len(blocks) * self._block_size)

    def _promote(self, node: _Node) -> bool:
        """Bring a demoted node's block back onto the device (store read →
        ``scatter_blocks``). Failure (device pool full, store entry gone)
        leaves the node demoted and its payload intact — the caller treats
        that depth as a miss."""
        store = getattr(self._kv, "tiered_store", None)
        if store is None or node.handle is None:
            return False
        try:
            data, _tier = store.read(node.handle)
            new_blocks = self._kv.scatter_blocks(data)
        except Exception:
            self.promote_failures += 1
            return False
        store.drop(node.handle)
        node.block = int(new_blocks[0])
        node.handle = None
        node.tier = "device"
        self._device_nodes += 1
        self.tier_promotions += 1
        return True

    def record_hit(self, n_blocks: int, tokens: int) -> None:
        """Account a hit the scheduler actually *applied* (a degraded or
        failed application releases its blocks and records nothing, so
        ``stats()`` agrees exactly with the scheduler's own counters)."""
        self.hits += 1
        self.hit_blocks += n_blocks
        self.tokens_served += tokens

    def release(self, blocks) -> None:
        """Return references taken by :meth:`acquire` (decref)."""
        if len(blocks):
            self._kv.free(blocks)

    # ----------------------------------------------------- drafter mining --
    def lookup_continuation(self, tokens, k: int,
                            digests: Optional[List[bytes]] = None) -> np.ndarray:
        """Mine the trie for a continuation of ``tokens`` — the prompt-lookup
        drafter's trie leg (speculative decoding): walk the full-block digest
        chain to the deepest indexed node, then descend children whose stored
        token blocks extend the partial tail, returning up to ``k`` proposed
        next tokens. Read-only: takes no block references and leaves LRU
        clocks untouched (drafting a continuation is not evidence the prefix
        will be re-prefilled). Empty when the history diverges from every
        indexed path."""
        if k <= 0:
            return np.empty(0, np.int32)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self._block_size
        n_full = tokens.size // bs
        if digests is None or len(digests) < n_full:
            # extend (never trust a short prompt-only chain: the walk depth
            # and the partial tail below must agree)
            digests = self.chain(tokens, base=digests)
        node = self._root
        for digest in digests[:n_full]:
            child = node.children.get(digest)
            if child is None:
                return np.empty(0, np.int32)
            node = child
        rem = tokens[n_full * bs:]
        out: List[int] = []
        while len(out) < k:
            nxt = None
            for child in node.children.values():
                ct = child.tokens
                if ct is not None and rem.size < ct.size and \
                        np.array_equal(ct[:rem.size], rem):
                    nxt = child
                    break
            if nxt is None:
                break
            tail = nxt.tokens[rem.size:]
            out.extend(int(t) for t in tail[:k - len(out)])
            node, rem = nxt, np.empty(0, np.int32)
        return np.asarray(out, np.int32)

    # ------------------------------------------------------- fleet export --
    def digest_catalog(self, limit: int = 64) -> List[str]:
        """The trie's fleet-visible shape: up to ``limit`` node digests
        (truncated hex, recency-first) for the replica's probe doc. A chained
        digest pins the whole prefix up to its block, so the router needs no
        structure — membership of the request chain's i-th digest means this
        replica holds the first ``i+1`` blocks. Safe from probe threads (the
        index lock guards the snapshot; staleness is bounded by the probe
        TTL)."""
        with self._index_lock:
            nodes = list(self._by_digest.values())
        nodes.sort(key=lambda n: n.last_touch, reverse=True)
        return [n.digest.hex()[:DIGEST_HEX] for n in nodes[:max(0, limit)]]

    def export_nodes(self, digests: List[bytes]) -> Tuple[List[int], np.ndarray]:
        """Deepest indexed path along ``digests`` (full chained digests):
        returns ``(block_ids, tokens)`` covering the matched prefix — the
        peer-fetch donor's read. Takes NO block references: the caller must
        run on the scheduler thread (the replica routes the fetch through the
        scheduler's control queue) and frame the blocks before yielding it."""
        node = self._root
        blocks: List[int] = []
        tokens: List[np.ndarray] = []
        self._clock += 1
        for digest in digests:
            child = node.children.get(digest)
            if child is None or child.tier != "device":
                # a donor serves only device-resident KV — promoting on a
                # peer's behalf would charge this replica's pool for another
                # replica's miss
                break
            child.last_touch = self._clock  # a fetched path is a hot path
            blocks.append(child.block)
            tokens.append(child.tokens)
            node = child
        if not blocks:
            return [], np.empty(0, np.int32)
        return blocks, np.concatenate(tokens)

    # ------------------------------------------------------------- publish --
    def publish(self, tokens, block_ids, committed_tokens: int,
                digests: Optional[List[bytes]] = None) -> int:
        """Index a sequence's full blocks: ``tokens`` is the token history,
        ``block_ids`` its block table, ``committed_tokens`` how many leading
        positions hold KV computed from exactly those tokens (the scheduler
        caps it below ``seen_tokens`` when chunked decode committed discarded
        over-run tokens); ``digests`` is a precomputed :meth:`chain` prefix.
        Each *newly indexed* block gains one trie reference; blocks whose
        prefix is already indexed are left to the sequence's flush. Returns
        the number of blocks newly pinned."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        block_ids = np.atleast_1d(np.asarray(block_ids)).astype(np.int64)
        bs = self._block_size
        n_full = min(int(committed_tokens) // bs, int(block_ids.size),
                     tokens.size // bs)
        if n_full <= 0:
            return 0
        node = self._root
        added = 0
        path = {id(self._root)}  # the walk's spine must not be evicted under it
        self._clock += 1
        for i, digest in enumerate(self.chain(tokens[:n_full * bs], base=digests)):
            child = node.children.get(digest)
            if child is None:
                if not self._make_room(1, protect=path):
                    break  # cap reached and nothing evictable: stop indexing
                block = int(block_ids[i])
                self._kv.incref([block])
                child = _Node(digest, block, node,
                              tokens=np.array(tokens[i * bs:(i + 1) * bs],
                                              np.int32, copy=True))
                node.children[digest] = child
                with self._index_lock:
                    self._by_digest[digest] = child
                self._device_nodes += 1
                added += 1
            child.last_touch = self._clock
            node = child
            path.add(id(node))
        self.published_blocks += added
        return added

    # ------------------------------------------------------------- evict --
    @property
    def n_blocks(self) -> int:
        """Device blocks currently pinned by the trie (demoted nodes pin
        none — their payloads live in the tiered store)."""
        return self._device_nodes

    @property
    def offloaded_nodes(self) -> int:
        return len(self._by_digest) - self._device_nodes

    def _evictable_leaves(self, protect) -> List[_Node]:
        return [n for n in self._by_digest.values()
                if not n.children and id(n) not in protect
                and n.tier == "device"
                and self._kv.ref_count(n.block) == 1]

    def _demotable_nodes(self, protect) -> List[_Node]:
        """Nodes whose device block can be demoted: device-resident with only
        the trie's reference (freeing a block a live sequence still maps
        reclaims nothing). Interior nodes qualify — a demoted mid-path node
        promotes back when an acquire walks through it."""
        return [n for n in self._by_digest.values()
                if n.tier == "device" and id(n) not in protect
                and self._kv.ref_count(n.block) == 1]

    def demote(self, n_blocks: int = 1, protect=frozenset()) -> int:
        """Move up to ``n_blocks`` trie blocks off the device into the tiered
        store (coldest first), freeing their device blocks WITHOUT forgetting
        the cached KV — the scheduler's ``_evict_one`` and the brownout
        demote-before-shed stage prefer this over :meth:`evict`, which
        discards. Returns how many device blocks were freed."""
        store = getattr(self._kv, "tiered_store", None)
        if store is None:
            return 0
        nodes = self._demotable_nodes(protect)
        nodes.sort(key=lambda n: n.last_touch)
        demoted = 0
        for node in nodes[:max(0, n_blocks)]:
            data = self._kv.gather_blocks([node.block])
            node.handle = store.put(data)
            self._kv.free([node.block])
            node.block = -1
            node.tier = "host"
            self._device_nodes -= 1
            demoted += 1
        self.tier_demotions += demoted
        return demoted

    def evict(self, n_blocks: int = 1, protect=frozenset()) -> int:
        """Unpin up to ``n_blocks`` device blocks, LRU-first, restricted to
        leaves no live sequence shares (freeing a shared leaf reclaims no
        memory — those blocks return when their sequences flush) and outside
        ``protect`` (node ids a publish walk is standing on). Evicting a leaf
        can expose its parent; the scan repeats until satisfied or dry.
        Returns how many blocks were actually freed."""
        freed = 0
        while freed < n_blocks:
            leaves = self._evictable_leaves(protect)
            if not leaves:
                break
            if n_blocks - freed == 1:
                # the common KV-pressure shape (evict(1) per needed block):
                # one O(n) min beats a full sort
                self._remove(min(leaves, key=lambda n: n.last_touch))
                freed += 1
                break
            leaves.sort(key=lambda n: n.last_touch)
            for leaf in leaves:
                self._remove(leaf)
                freed += 1
                if freed >= n_blocks:
                    break
        self.evictions += freed
        return freed

    def _remove(self, node: _Node) -> None:
        assert not node.children
        del node.parent.children[node.digest]
        with self._index_lock:
            del self._by_digest[node.digest]
        if node.tier == "device":
            self._kv.free([node.block])
            self._device_nodes -= 1
        elif node.handle is not None:
            store = getattr(self._kv, "tiered_store", None)
            if store is not None:
                store.drop(node.handle)

    def _make_room(self, n: int, protect=frozenset()) -> bool:
        """Ensure the trie can pin ``n`` more blocks under ``max_blocks``."""
        if self._max_blocks is None:
            return True
        over = self.n_blocks + n - self._max_blocks
        if over <= 0:
            return True
        return self.evict(over, protect=protect) >= over

    def clear(self) -> None:
        """Release every trie reference (scheduler shutdown): blocks shared
        with still-live sequences survive until those sequences flush."""
        store = getattr(self._kv, "tiered_store", None)
        for node in list(self._by_digest.values()):
            node.children.clear()
        for node in list(self._by_digest.values()):
            with self._index_lock:
                del self._by_digest[node.digest]
            if node.tier == "device":
                self._kv.free([node.block])
                self._device_nodes -= 1
            elif node.handle is not None and store is not None:
                store.drop(node.handle)
        self._root.children.clear()

    # --------------------------------------------------------------- stats --
    def stats(self) -> dict:
        lookups = self.lookups
        return {
            "lookups": lookups,
            "hits": self.hits,
            "hit_rate": (self.hits / lookups) if lookups else 0.0,
            "hit_blocks": self.hit_blocks,
            "tokens_served": self.tokens_served,
            "trie_blocks": self.n_blocks,
            "evictions": self.evictions,
            "published_blocks": self.published_blocks,
            "max_blocks": self._max_blocks,
            "offloaded_nodes": self.offloaded_nodes,
            "tier_demotions": self.tier_demotions,
            "tier_promotions": self.tier_promotions,
            "promote_failures": self.promote_failures,
        }
