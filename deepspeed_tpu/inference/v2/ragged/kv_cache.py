"""Blocked (paged) KV cache.

Reference: ``deepspeed/inference/v2/ragged/kv_cache.py`` (BlockedKVCache:40 —
reserve/free block ids, device cache tensors, offload/restore hooks).

TPU layout: one cache array per allocation group of shape
``[num_layers, 2, num_blocks, kv_heads, block_size, head_dim]`` — a (layer, k|v,
block) triple is one contiguous ``[kv_heads, block_size, head_dim]`` tile, which is
exactly one DMA for the Pallas paged-attention kernel
(``ops/pallas/paged_attention.py``) and a clean dynamic-slice for the XLA gather
fallback. The trailing ``[block_size, head_dim]`` = (16, 128) matches the TPU tile
so per-block copies are layout-native.
"""

import os
from typing import Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.manager_configs import AllocationMode, KVCacheConfig, MemoryConfig
from deepspeed_tpu.inference.v2.ragged.tiering import TieredKVStore
from deepspeed_tpu.utils.logging import logger


def _dtype_size(name):
    return {"bfloat16": 2, "float16": 2, "float32": 4, "int8": 1}[name]


class _LazyAIO:
    """Spill-file I/O for the tiered store that defers to the cache's AIO
    engine — built lazily so a cache that never spills never imports
    ``ops.aio`` or touches the spill directory."""

    def __init__(self, cache: "BlockedKVCache"):
        self._cache = cache

    def sync_pwrite(self, buf, path):
        self._cache._aio_handle().sync_pwrite(buf, path)

    def sync_pread(self, buf, path):
        self._cache._aio_handle().sync_pread(buf, path)


class BlockedKVCache:

    def __init__(self, config: KVCacheConfig, memory_config: MemoryConfig, mp_group=None,
                 offload: bool = False, offload_path: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        self._config = config
        num_layers, kv_heads, head_dim = config.cache_shape
        block_bytes = (config.block_size * 2 * num_layers * kv_heads * head_dim *
                       _dtype_size(config.cache_dtype))
        if memory_config.mode == AllocationMode.RESERVE:
            num_blocks = max(1, int(memory_config.size // block_bytes))
        else:
            num_blocks = int(memory_config.size)
        self._num_blocks = num_blocks
        self._allocator = BlockedAllocator(num_blocks)

        dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16, "float32": jnp.float32}[config.cache_dtype]
        self._cache = jnp.zeros((num_layers, 2, num_blocks, kv_heads, config.block_size, head_dim), dtype)
        logger.info(f"BlockedKVCache: {num_blocks} blocks x {config.block_size} tokens "
                    f"({num_blocks * block_bytes / 1e9:.2f} GB)")

        # off-device tiers (reference BlockedKVCache:40 declares
        # offload/restore and raises NotImplementedError — implemented here
        # as the host→disk ladder in ragged/tiering.py): offloaded payloads
        # land in host memory and demote to spill files under offload_path
        # when the host tier runs past its budget
        self._offload_path = offload_path
        self._aio = None
        self._tiers = TieredKVStore(spill_dir=offload_path, io=_LazyAIO(self))
        # pre-tiering NVMe semantics: offload_path with no host budget means
        # every offload spills to disk, synchronously (configure_tiering
        # replaces this with the budgeted async ladder)
        self._sync_spill = offload_path is not None
        self._restore_fn = None
        self._fork_fn = None

    @property
    def free_blocks(self) -> int:
        return self._allocator.free_blocks

    @property
    def num_blocks(self) -> int:
        return self._num_blocks

    @property
    def block_size(self) -> int:
        return self._config.block_size

    @property
    def cache(self):
        return self._cache

    def set_cache(self, cache):
        self._cache = cache

    def reserve(self, num_blocks: int):
        return self._allocator.allocate(num_blocks)

    def free(self, blocks):
        self._allocator.free(blocks)

    def incref(self, blocks) -> None:
        """Add one reference per block (prefix-cache sharing; see
        ``BlockedAllocator.incref``). ``free`` is the matching decref."""
        self._allocator.incref(blocks)

    def ref_count(self, block: int) -> int:
        return self._allocator.ref_count(block)

    def fork_blocks(self, src_blocks) -> np.ndarray:
        """Copy-on-write fork: allocate fresh blocks and device-copy
        ``src_blocks``' contents (every layer, K and V) into them, returning
        the new ids. The sources are untouched — the caller maps the copies
        into a sequence that is about to *write* where the sources are shared
        read-only (the prefix cache's first-divergent-block fork). A failed
        allocation consumes nothing."""
        import jax
        import jax.numpy as jnp

        src_blocks = np.atleast_1d(np.asarray(src_blocks)).astype(np.int64)
        new_blocks = self._allocator.allocate(src_blocks.size)
        if self._fork_fn is None:
            self._fork_fn = jax.jit(
                lambda cache, src, dst: cache.at[:, :, dst].set(cache[:, :, src]),
                donate_argnums=(0, ))
        try:
            new_cache = self._fork_fn(self._cache, jnp.asarray(src_blocks),
                                      jnp.asarray(new_blocks))
            jax.block_until_ready(new_cache)
        except Exception:
            self._allocator.free(new_blocks)
            raise
        self._cache = new_cache
        return new_blocks

    def gather_blocks(self, blocks) -> np.ndarray:
        """Device→host copy of ``blocks``' contents (every layer, K and V)
        WITHOUT freeing them — the read half of :meth:`offload`, reused by the
        fleet KV-handoff exporter (``ragged/handoff.py``), where the donor
        keeps its blocks until the recipient has taken over."""
        import jax
        import jax.numpy as jnp

        blocks = np.atleast_1d(np.asarray(blocks)).astype(np.int64)
        return np.asarray(jax.device_get(self._cache[:, :, jnp.asarray(blocks)]))

    def scatter_blocks(self, data) -> np.ndarray:
        """Allocate fresh device blocks and write ``data`` (a
        :meth:`gather_blocks`/offload-shaped payload
        ``[layers, 2, n, kv_heads, block_size, head_dim]``) into them; returns
        the new block ids — the write half of :meth:`restore`, reused by the
        fleet KV-handoff importer. A failed allocation or write consumes
        nothing."""
        data = np.asarray(data)
        num_layers, kv_heads, head_dim = self._config.cache_shape
        expect = (num_layers, 2, kv_heads, self._config.block_size, head_dim)
        got = data.shape[:2] + data.shape[3:] if data.ndim == 6 else None
        if got != expect:
            raise ValueError(
                f"scatter_blocks: payload shape {data.shape} does not fit this "
                f"cache's geometry [layers=2x{num_layers}, n, kv_heads={kv_heads}, "
                f"block_size={self._config.block_size}, head_dim={head_dim}]")
        new_blocks = self._allocator.allocate(data.shape[2])
        try:
            self._write_blocks(data, new_blocks)
        except Exception:
            self._allocator.free(new_blocks)
            raise
        return new_blocks

    def _write_blocks(self, data, block_ids) -> None:
        import jax
        import jax.numpy as jnp

        if self._restore_fn is None:
            self._restore_fn = jax.jit(
                lambda cache, payload, ids: cache.at[:, :, ids].set(payload.astype(cache.dtype)),
                donate_argnums=(0, ))
        new_cache = self._restore_fn(self._cache, jnp.asarray(data),
                                     jnp.asarray(block_ids))
        jax.block_until_ready(new_cache)
        self._cache = new_cache

    def offload(self, blocks) -> int:
        """Move ``blocks``' contents (every layer, K and V) to the host tier
        and free the device blocks for reuse. Returns a handle for
        :meth:`restore`.

        Role parity: reference ``kv_cache.py`` ``offload`` (declared :166,
        unimplemented there). Divergence: device block ids are NOT stable
        across an offload — freeing returns them to the allocator, and restore
        hands back fresh ids (the caller rewrites its block table; the
        state manager's ``offload_sequence`` does exactly that). This is the
        functional-array formulation: the cache is an immutable jax array, so
        "parking" data in place has no meaning.
        """
        blocks = np.atleast_1d(np.asarray(blocks)).astype(np.int64)
        data = self.gather_blocks(blocks)
        handle = self._tiers.put(data)
        self._allocator.free(blocks)
        if self._sync_spill:
            self._tiers.demote(handle, wait=True)
        return handle

    def restore(self, handle: int) -> np.ndarray:
        """Allocate fresh device blocks, write the offloaded contents back,
        and return the new block ids (see :meth:`offload` on id stability)."""
        needed = self._tiers.n_blocks(handle)
        if needed > self._allocator.free_blocks:
            # fail before touching disk: the caller's evict-and-retry loop
            # must not pay a full payload read per failed attempt
            raise ValueError(
                f"Allocator has {self._allocator.free_blocks} free blocks, "
                f"but {needed} were requested")
        data, _tier = self._tiers.read(handle)
        # on failure the payload stays in the store (and on disk): the
        # caller's evict-and-retry contract depends on it surviving a failed
        # restore
        new_blocks = self.scatter_blocks(data)
        self._tiers.drop(handle)
        return new_blocks

    def drop_offloaded(self, handle: int) -> None:
        """Discard an offloaded payload without restoring (sequence flushed)."""
        self._tiers.drop(handle)

    def configure_tiering(self, spill_dir: Optional[str] = None,
                          host_bytes: Optional[int] = None) -> None:
        """Enable the budgeted host→disk ladder (serving ``kv_tiers`` config
        arrives after the engine — and this cache — are built). Replaces the
        legacy spill-everything-synchronously NVMe mode: offloads land in host
        memory and demote asynchronously when over ``host_bytes``."""
        if spill_dir is not None:
            self._offload_path = spill_dir
        self._sync_spill = False
        self._tiers.configure(spill_dir=spill_dir, host_bytes=host_bytes)

    def offload_tier(self, handle: int) -> str:
        """Which tier currently holds an offloaded payload (host | disk)."""
        return self._tiers.tier_of(handle)

    def demote_offloaded(self, handle: int, wait: bool = False) -> bool:
        """Push one offloaded payload host→disk (brownout's demote stage)."""
        return self._tiers.demote(handle, wait=wait)

    def tier_stats(self) -> dict:
        return self._tiers.stats()

    @property
    def tiered_store(self) -> TieredKVStore:
        return self._tiers

    def _aio_handle(self):
        if self._aio is None:
            from deepspeed_tpu.ops.aio import AsyncIOHandle
            os.makedirs(self._offload_path, exist_ok=True)
            self._aio = AsyncIOHandle(thread_count=2)
        return self._aio
