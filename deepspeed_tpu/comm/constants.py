"""Communication constants (reference: deepspeed/comm/constants.py)."""

XLA_BACKEND = "xla"
CPU_BACKEND = "xla"  # same collective stack on host XLA
DEFAULT_BACKEND = XLA_BACKEND

COMMS_LOGGER_FORMAT = "COMMS"

# config keys
COMMS_LOGGER = "comms_logger"
COMMS_LOGGER_ENABLED = "enabled"
COMMS_LOGGER_ENABLED_DEFAULT = False
COMMS_LOGGER_VERBOSE = "verbose"
COMMS_LOGGER_VERBOSE_DEFAULT = False
COMMS_LOGGER_PROF_OPS = "prof_ops"
COMMS_LOGGER_PROF_OPS_DEFAULT = []
COMMS_LOGGER_PROF_ALL = "prof_all"
COMMS_LOGGER_PROF_ALL_DEFAULT = True
COMMS_LOGGER_DEBUG = "debug"
COMMS_LOGGER_DEBUG_DEFAULT = False
