"""Safe-mode / numerical-sanity helpers.

Reference role: SURVEY.md §5.2 — the reference's overflow detection
(``check_grad_overflow``), torch anomaly detection, and the multi-rank
consistency checks scattered through its engine (tag validation, NCCL sanity).

TPU surface:
- :func:`enable_debug_nans` flips ``jax_debug_nans`` (XLA re-runs the failing
  op un-jitted and points at it — the torch detect-anomaly analog).
- :func:`find_nonfinite` walks a pytree and names the offending leaves.
- :func:`assert_cross_rank_consistent` proves every process holds the same
  host value (config hashes, tags, schedules) — the class of bug the
  reference's tag validation catches.
"""

from typing import Any, List

import numpy as np


def enable_debug_nans(enable: bool = True):
    import jax
    jax.config.update("jax_debug_nans", enable)


def find_nonfinite(tree, name: str = "tree") -> List[str]:
    """Paths of leaves containing NaN/Inf (host sync — debug tool, not a hot
    path)."""
    import jax
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        if not np.all(np.isfinite(arr)):
            n_bad = int((~np.isfinite(arr)).sum())
            bad.append(f"{name}{jax.tree_util.keystr(path)}: {n_bad}/{arr.size} non-finite")
    return bad


def assert_all_finite(tree, name: str = "tree"):
    bad = find_nonfinite(tree, name)
    if bad:
        raise FloatingPointError("non-finite values detected:\n  " + "\n  ".join(bad))


def assert_cross_rank_consistent(value: Any, what: str = "value"):
    """Raise if any process disagrees on ``value`` (hashed, broadcast from
    process 0 — covers every process regardless of mesh layout)."""
    import zlib
    import jax
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    h = np.int64(zlib.crc32(repr(value).encode()))
    agreed = int(multihost_utils.broadcast_one_to_all(h))
    if agreed != int(h):
        raise RuntimeError(f"{what} differs across processes (local hash {int(h)}, "
                           f"process-0 hash {agreed})")
