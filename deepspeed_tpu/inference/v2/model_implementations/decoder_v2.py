"""Ragged inference for the configurable decoder family (OPT / Falcon / Phi).

Reference: ``deepspeed/inference/v2/model_implementations/{opt,falcon,phi}``
(one directory per model in the reference; one parameterized implementation
here — the axes are position encoding, residual topology, norm, activation,
MQA — see ``models/decoder.py``). Consumes the training pytree verbatim so
logits are testable against the training forward.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.model_implementations.llama_v2 import _root, rotary_embedding
from deepspeed_tpu.inference.v2.model_implementations.transformer_base import \
    DSTransformerModelBase
from deepspeed_tpu.inference.v2.tracer import record
from deepspeed_tpu.models.decoder import DecoderConfig, _act


def _ln(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * p["scale"] + p["bias"]).astype(x.dtype)


def _linear(h, p):
    out = h @ p["kernel"].astype(h.dtype)
    if "bias" in p:
        out = out + p["bias"].astype(h.dtype)
    return out


def _rotary_at_partial(x, pos, cos_tab, sin_tab, pct, interleaved=False):
    if pct <= 0.0:
        return x
    D = x.shape[-1]
    rot = int(round(D * pct)) // 2 * 2
    cos = cos_tab[pos][:, None, :]
    sin = sin_tab[pos][:, None, :]
    xr = x[..., :rot]
    if interleaved:  # gptj: adjacent (even, odd) pairs rotate together
        x1 = xr[..., 0::2]
        x2 = xr[..., 1::2]
        rotated = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1) \
            .reshape(xr.shape)
    else:            # llama/neox half-split
        x1, x2 = jnp.split(xr, 2, axis=-1)
        rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([rotated.astype(x.dtype), x[..., rot:]], axis=-1)


class DecoderV2Model(DSTransformerModelBase):

    def __init__(self, params, config: DecoderConfig, engine_config, state_manager=None):
        if config.pos_embed == "alibi" or config.embed_layernorm:
            # BEFORE super(): the base may quantize the whole tree first
            raise NotImplementedError(
                f"inference-v2 DecoderV2Model does not serve {config.model_type!r}: "
                "ALiBi biases are not implemented in the paged attention paths — "
                "use the v1 engine (init_inference over the converted checkpoint)")
        super().__init__(params, config, engine_config, state_manager)
        if config.pos_embed == "rotary":
            D = config.hidden_size // config.num_attention_heads
            rot = int(round(D * config.rotary_pct)) // 2 * 2
            self._cos, self._sin = rotary_embedding(engine_config.state_manager.max_context,
                                                    rot, config.rope_theta, jnp.float32)

    @property
    def num_layers(self):
        return self._config.num_hidden_layers

    @property
    def num_heads(self):
        return self._config.num_attention_heads

    @property
    def num_kv_heads(self):
        return self._config.num_key_value_heads

    @property
    def head_dim(self):
        return self._config.hidden_size // self._config.num_attention_heads

    @property
    def vocab_size(self):
        return self._config.vocab_size

    # --------------------------------------------------------------- phases --
    def embed(self, params, ids):
        r = _root(params)
        x = r["embed_tokens"]["embedding"][ids].astype(self._config.dtype)
        return x

    def _add_positions(self, params, x, batch):
        cfg = self._config
        if cfg.pos_embed != "learned":
            return x
        wpe = _root(params)["embed_positions"]["embedding"]
        pos = batch["token_pos"] + cfg.learned_pos_offset
        return x + wpe[pos].astype(x.dtype)

    def unembed(self, params, x):
        r = _root(params)
        x = _ln(x, r["final_layer_norm"], self._config.layer_norm_eps)
        logits = x @ r["lm_head"]["kernel"].astype(x.dtype)
        if "bias" in r["lm_head"]:  # gptj's biased head
            logits = logits + r["lm_head"]["bias"].astype(x.dtype)
        return logits

    def _attn(self, params, li, h, cache, attn_fn, batch):
        cfg = self._config
        ap = _root(params)[f"layers_{li}"]["self_attn"]
        H, KVH, D = self.num_heads, self.num_kv_heads, self.head_dim
        q = _linear(h, ap["q_proj"]).reshape(-1, H, D)
        k = _linear(h, ap["k_proj"]).reshape(-1, KVH, D)
        v = _linear(h, ap["v_proj"]).reshape(-1, KVH, D)
        if cfg.pos_embed == "rotary":
            pos = batch["token_pos"]
            q = _rotary_at_partial(q, pos, self._cos, self._sin, cfg.rotary_pct,
                                   cfg.rotary_interleaved)
            k = _rotary_at_partial(k, pos, self._cos, self._sin, cfg.rotary_pct,
                                   cfg.rotary_interleaved)
        out, cache = attn_fn(q, k, v, cache, li)
        return _linear(out.reshape(h.shape[0], H * D), ap["out_proj"]), cache

    def _mlp(self, params, li, h):
        cfg = self._config
        mp = _root(params)[f"layers_{li}"]["mlp"]
        act = _act(cfg)  # shared table: unknown activations fail loudly
        return _linear(act(_linear(h, mp["fc1"])), mp["fc2"])

    def layer_forward(self, params, li, x, cache, attn_fn, batch):
        cfg = self._config
        lp = _root(params)[f"layers_{li}"]
        if li == 0:
            x = self._add_positions(params, x, batch)
        if cfg.parallel_residual:
            h = _ln(x, lp["input_layernorm"], cfg.layer_norm_eps)
            hm = _ln(x, lp["post_attention_layernorm"], cfg.layer_norm_eps) \
                if cfg.parallel_mlp_norm else h
            attn_out, cache = self._attn(params, li, h, cache, attn_fn, batch)
            return x + attn_out + self._mlp(params, li, hm), cache
        h = _ln(x, lp["input_layernorm"], cfg.layer_norm_eps)
        attn_out, cache = self._attn(params, li, h, cache, attn_fn, batch)
        x = x + attn_out
        h = _ln(x, lp["post_attention_layernorm"], cfg.layer_norm_eps)
        return x + self._mlp(params, li, h), cache

    def layer_forward_traced(self, params, li, x, cache, attn_fn, batch):
        with record("layer"):
            x, cache = self.layer_forward(params, li, x, cache, attn_fn, batch)
            x.block_until_ready()
        return x, cache
