"""Communication backend interface.

Reference: ``deepspeed/comm/backend.py`` (Backend ABC) + ``deepspeed/comm/torch.py:99``
(TorchBackend). The TPU build has exactly one backend — XLA collectives over the
global mesh — so the capability probes that the reference feature-detects
(``has_all_gather_into_tensor`` etc., torch.py:41-58) are all True here.
"""


class Backend:

    def __init__(self, name="backend", rank=0, size=1):
        self.name = name
        self.world_group = None
        self.world_size = size
        self.world_rank = rank
        self.initialized = False

    def is_initialized(self):
        return self.initialized

    def new_group(self, ranks):
        raise NotImplementedError

    def init_process_group(self):
        self.initialized = True

    def destroy_process_group(self):
        self.initialized = False

    # capability flags (reference feature-detects these; XLA always has them)
    def has_all_gather_into_tensor(self):
        return True

    def has_reduce_scatter_tensor(self):
        return True

    def has_coalescing_manager(self):
        return True

    def has_all_reduce_coalesced(self):
        return True
