"""Benchmark harness (driver contract: print ONE JSON line).

Measures single-chip Llama training-step throughput (tokens/sec) and derives MFU
against the chip's bf16 peak. ``vs_baseline`` = MFU / 0.45 — the BASELINE.json
north-star is ZeRO-3 Llama SFT at >=45% MFU, so 1.0 means parity with the target.
"""

import json
import os
import sys
import time

import numpy as np


def _peak_flops():
    """bf16 peak per chip."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind:
        return 918e12
    return 197e12  # conservative default


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.utils import groups

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        B, S = 8, 1024
        cfg = llama.LlamaConfig(vocab_size=32000, hidden_size=768, intermediate_size=2048,
                                num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=12,
                                max_position_embeddings=S, remat=False, dtype=jnp.bfloat16)
        steps, warmup = 20, 3
    else:  # smoke-test shape for CPU runs
        B, S = 2, 128
        cfg = llama.LlamaConfig.tiny()
        steps, warmup = 8, 1

    model, params = llama.init_params(cfg, batch_size=B, seq_len=S)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    groups.initialize_mesh(force=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": B,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 0},
            "bf16": {"enabled": True},
        })

    rng = np.random.default_rng(0)
    def make_batch():
        ids = rng.integers(0, cfg.vocab_size, size=(B, S + 1), dtype=np.int64)
        return ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32)

    for _ in range(warmup):
        float(engine.train_batch(batch=make_batch()))  # host fetch = true barrier

    # Two-point measurement: total(N) = N*step + RTT. The steps chain through the
    # donated params, so ONE final scalar fetch forces the whole chain; differencing
    # two N's cancels the (tunnel) round-trip latency and async-dispatch skew.
    def run(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = engine.train_batch(batch=make_batch())
        float(loss)
        return time.perf_counter() - t0, loss

    n1 = max(2, steps // 4)
    t1, _ = run(n1)
    t2, loss = run(steps)
    step_time = (t2 - t1) / (steps - n1)
    if step_time <= 0:  # timing noise (fast local backends) — fall back to plain avg
        step_time = t2 / steps
    tokens_per_sec = B * S / step_time
    flops_per_token = 6.0 * n_params  # fwd+bwd dense-transformer estimate
    mfu = tokens_per_sec * flops_per_token / _peak_flops()
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "n_params": n_params,
            "batch": B,
            "seq": S,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "loss_final": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
