"""Ragged batch container.

Reference: ``deepspeed/inference/v2/ragged/ragged_wrapper.py`` (RaggedBatchWrapper:31
— host shadow buffers for input ids / token→sequence map / per-sequence descriptors /
KV block lists, finalized into device tensors once per forward).

TPU design: XLA needs static shapes, so ``finalize()`` pads every buffer to a
*bucket*: token count rounded up with :func:`to_padded`, sequence count to a
multiple of 8, per-sequence block count to a multiple of 4. Each distinct bucket
shape compiles once; steady-state decode reuses one bucket. Padded token slots
carry an out-of-range KV block id so cache scatters drop them (XLA scatter
``mode=drop`` — no masking pass needed).
"""

from typing import List

import numpy as np

from deepspeed_tpu.inference.v2.ragged.manager_configs import DSStateManagerConfig
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor
from deepspeed_tpu.telemetry import compile_watch


def to_padded(original_size: int) -> int:
    """Pad a token count to a compile-friendly bucket: powers of two up to 64
    (8 minimum — decode batches stay small and must not burn a 64-token MLP),
    then 128-granularity for prefill chunks."""
    if original_size <= 64:
        n = 8
        while n < original_size:
            n *= 2
        return n
    return (original_size + 127) // 128 * 128


def _pad_to(n: int, mult: int) -> int:
    return max(mult, (n + mult - 1) // mult * mult)


def _pow2_pad(n: int, minimum: int = 4) -> int:
    """Power-of-two bucket: the block-table width grows every block with plain
    granularity padding, which would recompile the decode program every few
    generated tokens; pow2 bucketing bounds recompiles to log2(max_blocks)."""
    p = minimum
    while p < n:
        p *= 2
    return p


class RaggedBatchWrapper:
    """Host-side composition of one ragged forward batch."""

    def __init__(self, config: DSStateManagerConfig, block_size: int = 128) -> None:
        self._config = config
        self._block_size = block_size
        self.clear()

    def clear(self) -> None:
        self._token_ids: List[int] = []
        self._token_seq: List[int] = []      # token -> index of its sequence in this batch
        self._token_pos: List[int] = []      # absolute position within the sequence
        # tree-verify metadata (inference/v2/spec/tree.py): per token, the
        # parent's LOCAL feed index within its sequence (-1 = root) and the
        # root distance. Linear feeds default to the chain (parent = i-1,
        # depth = i), so mixed chain/tree batches pack uniformly.
        self._token_parent: List[int] = []
        self._token_depth: List[int] = []
        self._has_tree = False
        self._seq_descs: List[DSSequenceDescriptor] = []
        self._seq_seen: List[int] = []
        self._seq_ntok: List[int] = []
        self._seq_blocks: List[np.ndarray] = []
        self._device_batch = None

    @property
    def current_sequences(self) -> int:
        return len(self._seq_descs)

    @property
    def current_tokens(self) -> int:
        return len(self._token_ids)

    def insert_sequence(self, seq_desc: DSSequenceDescriptor, tokens, do_checks: bool = True,
                        tree=None) -> None:
        """``tree`` (optional) is a ``(parents, depths)`` pair of local-index
        arrays aligned with ``tokens`` — a speculative token tree (see
        spec/tree.py). Token i then occupies KV SLOT ``seen + i`` (sibling
        branches get distinct cache slots) while its ``token_pos`` stays the
        slot position; the tree-verify program derives the LOGICAL (RoPE)
        position ``seen + depths[i]`` from the packed tree metadata."""
        tokens = np.atleast_1d(np.asarray(tokens)).astype(np.int32)
        if do_checks:
            if self.current_tokens + tokens.size > self._config.max_ragged_batch_size:
                raise ValueError("ragged batch token budget exceeded")
            if self.current_sequences + 1 > self._config.max_ragged_sequence_count:
                raise ValueError("ragged batch sequence budget exceeded")
        if tree is not None:
            # validate BEFORE mutating: a rejected insert must leave the
            # wrapper consistent so the caller can retry with a clean feed
            parents = np.asarray(tree[0], np.int32).reshape(-1)
            depths = np.asarray(tree[1], np.int32).reshape(-1)
            if do_checks:
                if parents.size != tokens.size or depths.size != tokens.size:
                    raise ValueError("tree metadata must align with the token feed")
                if tokens.size and (parents[0] != -1 or depths[0] != 0):
                    raise ValueError("tree node 0 must be the root (parent -1, depth 0)")
                if any(not (-1 <= int(parents[i]) < i) for i in range(tokens.size)):
                    raise ValueError("tree parents must be topological local indices")
        seq_idx = len(self._seq_descs)
        seen = seq_desc.seen_tokens
        self._seq_descs.append(seq_desc)
        self._seq_seen.append(seen)
        self._seq_ntok.append(int(tokens.size))
        self._seq_blocks.append(seq_desc.kv_blocks)
        self._token_ids.extend(int(t) for t in tokens)
        self._token_seq.extend([seq_idx] * tokens.size)
        self._token_pos.extend(range(seen, seen + tokens.size))
        if tree is None:
            self._token_parent.extend(range(-1, tokens.size - 1))
            self._token_depth.extend(range(tokens.size))
        else:
            self._token_parent.extend(int(p) for p in parents)
            self._token_depth.extend(int(d) for d in depths)
            self._has_tree = True

    def finalize(self):
        """Pad to the bucket and build the device-ready numpy struct."""
        T = to_padded(max(1, self.current_tokens))
        S = _pad_to(max(1, self.current_sequences), 8)
        mb = max((len(b) for b in self._seq_blocks), default=1)
        MB = _pow2_pad(mb, 4)
        cw = compile_watch.get()
        if cw is not None:
            # (T, S, MB) IS the jit cache key downstream — the watch counts
            # batch-to-batch bucket churn, the leading recompile indicator
            cw.note_bucket((T, S, MB))
        n_tok = self.current_tokens
        n_seq = self.current_sequences

        input_ids = np.zeros(T, np.int32)
        token_seq = np.full(T, S - 1, np.int32)
        token_pos = np.zeros(T, np.int32)
        token_valid = np.zeros(T, bool)
        input_ids[:n_tok] = self._token_ids
        token_seq[:n_tok] = self._token_seq
        token_pos[:n_tok] = self._token_pos
        token_valid[:n_tok] = True

        seq_seen = np.zeros(S, np.int32)
        seq_ntok = np.zeros(S, np.int32)
        last_tok = np.zeros(S, np.int32)
        seq_valid = np.zeros(S, bool)
        # padded/invalid slots point one past the last block -> scatters drop
        block_table = np.full((S, MB), -1, np.int32)
        cursor = 0
        for i in range(n_seq):
            seq_seen[i] = self._seq_seen[i]
            seq_ntok[i] = self._seq_ntok[i]
            cursor += self._seq_ntok[i]
            last_tok[i] = cursor - 1
            seq_valid[i] = True
            blocks = self._seq_blocks[i]
            block_table[i, :len(blocks)] = blocks

        # Pack into TWO device arrays (plus host-only counts): under a tunneled
        # or multi-host dispatch every h2d transfer pays latency, and decode
        # issues one batch per generated token — 2 transfers/step, not 10.
        # transformer_base._unpack_batch restores the named views inside jit.
        tok_meta = np.stack([input_ids, token_seq, token_pos,
                             token_valid.astype(np.int32)])  # [4, T]
        seq_meta = np.concatenate([
            np.stack([seq_seen, seq_ntok, last_tok, seq_valid.astype(np.int32)], axis=1),
            block_table
        ], axis=1)  # [S, 4 + MB]
        self._device_batch = dict(
            tok_meta=tok_meta,
            seq_meta=seq_meta,
            n_tokens=n_tok,
            n_seqs=n_seq,
        )
        if self._has_tree:
            # packed only when a tree was inserted: the plain decode/prefill
            # hot path builds exactly the two arrays it always did
            parent = np.full(T, -1, np.int32)
            depth = np.zeros(T, np.int32)
            parent[:n_tok] = self._token_parent
            depth[:n_tok] = self._token_depth
            self._device_batch["tree_meta"] = np.stack([parent, depth])  # [2, T]
        return self._device_batch

    @property
    def device_batch(self):
        assert self._device_batch is not None, "finalize() the batch first"
        return self._device_batch

    def masked_input_ids(self) -> np.ndarray:
        return self.device_batch["tok_meta"][0, :self.current_tokens]
