"""Built-in model factories for the subprocess autotuner.

A ``model_factory`` is the subprocess-mode replacement for live model
objects (reference analog: the user training script the reference launcher
re-runs per experiment). Signature::

    fn(config: dict) -> (model, params, batch_fn)

where ``batch_fn(micro_batch_size) -> batch``. Point ``autotuning.
model_factory`` at any importable "pkg.mod:fn"; the ones here serve tests,
examples, and quick starts.
"""

import numpy as np


def tiny_llama(config: dict):
    """A tiny Llama for smoke-scale tuning runs (and the e2e tests)."""
    from deepspeed_tpu.models import llama

    S = 32
    cfg = llama.LlamaConfig.tiny(max_position_embeddings=S)
    model, params = llama.init_params(cfg, batch_size=1, seq_len=S)

    def batch_fn(micro):
        gas = int(config.get("gradient_accumulation_steps", 1))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, size=(micro * gas, S + 1), dtype=np.int64)
        return (ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32))

    return model, params, batch_fn


def failing(config: dict):
    """Deliberately dies — exercises the scheduler's crash isolation."""
    raise RuntimeError("model_factories.failing: intentional experiment failure")


def tiny_llama_fragile(config: dict):
    """tiny_llama, but hard-dies (no results.json, like an OOM kill) when the
    micro batch is 4 — exercises the scheduler surviving a dead experiment
    process, the failure mode in-process measurement cannot."""
    import os
    if int(config.get("train_micro_batch_size_per_gpu", 1)) == 4:
        os._exit(137)
    return tiny_llama(config)
