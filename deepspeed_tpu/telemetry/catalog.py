"""Metric-family catalog: the single source of truth for every metric the
codebase can register on the unified registry.

Two invariants, both unit-enforced by ``tests/unit/telemetry/test_metrics_docs.py``:

1. every family here appears in a README metric table (and vice versa) — a
   new metric cannot land undocumented;
2. every string-literal ``counter("...")``/``gauge``/``histogram`` name in the
   source tree appears here — a new metric cannot dodge the catalog either.

Keep entries grouped by owning subsystem; the value is the one-line
description the README table should carry (wording may differ — the test
diffs *names*, not prose).
"""

METRIC_FAMILIES = {
    # training engine (runtime/engine.py _write_telemetry)
    "train_loss": "last boundary-step training loss",
    "train_lr": "current learning rate",
    "train_samples_per_sec": "boundary-to-boundary throughput",
    "train_grad_norm": "global gradient norm at the last step",
    "train_skipped_steps": "overflow-skipped optimizer steps",
    "train_global_steps": "optimizer steps taken",
    "train_samples_total": "samples consumed",
    # training fault tolerance (runtime/checkpoint_engine/engine.py,
    # runtime/engine.py, runtime/sentinel.py, runtime/faults.py,
    # elasticity/train_supervisor.py)
    "checkpoint_saves_total": "committed (manifest-sealed) checkpoint saves",
    "checkpoint_verify_failures_total": "checkpoint tags that failed manifest verification (torn/corrupt)",
    "checkpoint_load_fallbacks_total": "loads that skipped a bad tag and fell back to an older good one",
    "checkpoint_pruned_total": "checkpoint tags deleted by keep-last-K retention",
    "train_preemptions_total": "preemption notices converted into a final checkpoint + clean exit",
    "train_anomalies_total": "loss anomalies (NaN/inf/spike) seen by the sentinel",
    "train_rollbacks_total": "sentinel rollbacks to the last good checkpoint",
    "train_restarts_total": "training process restarts by the supervisor after a crash",
    "train_faults_injected_total": "faults injected by the training chaos harness",
    # gang fault tolerance (elasticity/elastic_agent.py, comm/comm.py)
    "train_gang_crashes_total": "rank crashes observed by the gang watchdog",
    "train_gang_hangs_total": "wedged ranks detected via stale heartbeat",
    "train_gang_teardowns_total": "whole-gang teardowns (SIGTERM-grace-SIGKILL)",
    "train_gang_relaunches_total": "gang relaunches by the elastic agent",
    "train_gang_shrinks_total": "crash-budget shrinks to a smaller world size",
    "train_gang_world_size": "current gang world size (processes)",
    "barrier_timeouts_total": "monitored_barrier deadline expiries (absent ranks named in the error)",
    # comms layer (telemetry/__init__.record_comm_op)
    "comm_op_latency_seconds": "per-collective wall latency",
    "comm_op_bytes": "per-collective message size",
    "comm_ops_total": "collectives executed",
    # v2 inference engine (inference/v2/engine_v2.py)
    "inference_batches_total": "ragged batches executed",
    "inference_tokens_total": "tokens scheduled into batches",
    "inference_in_flight_tokens": "tokens in the last ragged batch",
    "inference_kv_free_blocks": "free KV-cache blocks",
    "inference_tracked_sequences": "sequences tracked",
    "inference_empty_runs_total": "EP lock-step forwards with zero tokens",
    # serving layer (serving/metrics.py)
    "serving_queue_depth": "requests waiting for admission",
    "serving_in_flight_requests": "requests in PREFILL or DECODE",
    "serving_ttft_seconds": "submission to first generated token",
    "serving_inter_token_seconds": "gap between consecutive streamed tokens",
    "serving_e2e_latency_seconds": "submission to terminal state",
    "serving_admissions_total": "requests accepted into the queue",
    "serving_rejections_total": "requests rejected by backpressure",
    "serving_completions_total": "requests finished DONE",
    "serving_timeouts_total": "requests that hit their deadline",
    "serving_cancellations_total": "requests cancelled mid-flight",
    "serving_failures_total": "requests that FAILED",
    "serving_kv_evictions_total": "idle sequences offloaded under KV pressure",
    # automatic prefix cache (serving/metrics.py over
    # inference/v2/ragged/prefix_cache.py)
    "serving_prefix_lookups_total": "admitted prompts looked up in the prefix trie",
    "serving_prefix_hits_total": "admitted prompts served a cached prefix",
    "serving_prefix_lookup_depth_blocks": "cached-prefix depth (KV blocks) applied per lookup",
    "serving_prefix_tokens_saved_total": "prompt tokens served from cached KV instead of prefilled",
    "serving_prefix_trie_blocks": "device KV blocks pinned by the prefix trie",
    "serving_prefix_evictions_total": "prefix-trie leaves evicted (LRU) under KV pressure or the trie cap",
    # speculative decoding (serving/metrics.py over inference/v2/spec/ and
    # the scheduler's verify execute path)
    "serving_spec_draft_tokens_total": "draft tokens proposed into speculative verify feeds",
    "serving_spec_accepted_tokens_total": "draft tokens the target model's verify step accepted",
    "serving_spec_verify_steps_total": "decode dispatches that carried at least one draft token",
    "serving_spec_rollback_tokens_total": "rejected draft positions truncated from committed KV",
    "serving_spec_accept_rate": "EWMA of the speculative acceptance rate across verify steps",
    "serving_spec_tokens_per_step": "tokens emitted per speculative verify step (1 = nothing accepted)",
    "serving_spec_tree_nodes_total": "token-tree nodes fed through verify_tree dispatches (root included)",
    "serving_spec_tree_accept_depth": "accepted path depth per tree-verify step (0 = root only survived)",
    "serving_spec_tree_compactions_total": "tree-verify steps whose accepted path needed a KV gather-compact",
    "serving_spec_drafter_switches_total": "per-request drafter changes decided by the auto arbitration",
    "serving_spec_drafter_learned_ewma": "EWMA of the learned drafter's accepted-depth rate across requests",
    "serving_spec_drafter_lookup_ewma": "EWMA of the prompt-lookup drafter's accepted-depth rate across requests",
    # tiered KV memory (serving/metrics.py over inference/v2/ragged/tiering.py
    # and serving/kv_tiers.py)
    "serving_kv_tier_demotions_total": "KV payloads demoted down the tier ladder (device pressure and host-to-disk writeback)",
    "serving_kv_tier_disk_demotions_total": "host-tier payloads committed to disk spill files by the async writer",
    "serving_kv_tier_promotions_total": "demoted payloads promoted back up the ladder on access",
    "serving_kv_tier_device_blocks": "KV blocks resident on device",
    "serving_kv_tier_host_blocks": "KV blocks resident in the host tier",
    "serving_kv_tier_disk_blocks": "KV blocks resident in disk spill files",
    # overload control (serving/metrics.py over serving/overload.py)
    "serving_shed_admission_total": "requests rejected at admission: deadline provably unmeetable",
    "serving_shed_queue_total": "queued requests shed under sustained overload pressure",
    "serving_brownout_stage": "current brownout degradation stage (0 = normal service)",
    "serving_brownout_transitions_total": "brownout stage changes (hysteresis-smoothed)",
    "serving_brownout_clamped_total": "batch-class requests whose max_new_tokens was brownout-clamped",
    "serving_brownout_rejections_total": "batch-class requests rejected outright at brownout stage 3",
    # cost attribution plane (telemetry/ledger.py, serving/metrics.py,
    # perf/observed.py)
    "serving_cost_billed_tokens_total": "tokens billed by the cost ledger, by engine phase",
    "serving_cost_device_seconds_total": "dispatch wall-seconds attributed to requests (amortized over batch occupants)",
    "serving_cost_amnesty_seconds_total": "dispatch wall-seconds forgiven as compile amnesty (first sight of a (program, bucket))",
    "serving_cost_kv_block_seconds_total": "KV block-seconds billed to requests, by residency tier",
    "serving_cost_wire_bytes_total": "KV payload bytes billed to requests, by motion channel",
    "serving_cost_saved_tokens_total": "tokens the request did NOT pay for (prefix-cache hits, accepted spec drafts)",
    "serving_tenant_tokens_total": "tokens billed per tenant (top-K tenants; overflow under <other>)",
    "serving_tenant_requests_total": "finished requests per tenant (top-K tenants; overflow under <other>)",
    "serving_fair_share_sheds_total": "requests shed/429'd by the fair-share stage (tenant over measured share under pressure)",
    "perf_observed_dispatch_seconds": "wall seconds around the engine's jitted dispatches, by program/bucket",
    "perf_observed_ratio": "observed dispatch seconds over roofline-predicted step seconds",
    "perf_drift_events_total": "sustained observed-vs-predicted dispatch-time drift episodes",
    # compile watch (telemetry/compile_watch.py)
    "compile_cache_misses_total": "XLA backend compiles (jit cache misses), by site",
    "compile_seconds_total": "cumulative XLA compile wall seconds, by site",
    "compile_cache_entries": "live jit cache entries created at each site",
    "compile_bucket_switches_total": "ragged batches landing in a pad bucket not recently seen",
    # flight recorder (telemetry/flight_recorder.py)
    "flight_recorder_dumps_total": "flight-recorder dumps written, by trigger",
    "serving_stalled_total": "watchdog detections of a stalled scheduler loop",
    # fleet layer (fleet/metrics.py)
    "fleet_replicas": "live (non-DOWN) replicas registered with the manager",
    "fleet_queue_depth": "fleet-wide queued requests at the last probe sweep",
    "fleet_kv_pressure": "mean replica KV-pool occupancy (1 - free/capacity)",
    "fleet_requests_total": "client requests accepted by the router",
    "fleet_dispatch_retries_total": "dispatch attempts that failed over to another replica",
    "fleet_routing_failures_total": "requests that exhausted every candidate replica",
    "fleet_handoffs_total": "prefill-to-decode KV-block handoffs completed",
    "fleet_handoff_bytes": "KV-handoff payload size",
    "fleet_scale_ups_total": "autoscaler replica additions",
    "fleet_scale_downs_total": "autoscaler replica drains",
    # perf gates (perf/gate.py _publish_telemetry)
    "perf_gate_runs_total": "perf-gate program checks executed",
    "perf_gate_violations_total": "perf-gate budget violations detected",
    "perf_program_flops": "HLO cost-analysis FLOPs per flagship program",
    "perf_program_bytes_accessed": "HLO cost-analysis bytes moved per flagship program",
    "perf_program_peak_bytes": "live-buffer peak per flagship program",
    "perf_program_collective_bytes": "collective payload bytes per flagship program",
    "perf_program_f32_dots": "f32-operand dots on the program's (bf16) path",
    "perf_predicted_step_seconds": "roofline step-time lower bound per program/chip",
    "perf_predicted_mfu_bound": "roofline MFU upper bound per program/chip",
    # fleet fault tolerance (fleet/breaker.py, fleet/supervisor.py,
    # fleet/router.py, fleet/faults.py)
    "fleet_breaker_opens_total": "circuit-breaker transitions into OPEN",
    "fleet_breaker_closes_total": "circuit-breaker recoveries (HALF_OPEN trial succeeded)",
    "fleet_breaker_open_replicas": "replicas currently behind an OPEN breaker",
    "fleet_breaker_short_circuits_total": "dispatch candidates skipped on an open breaker",
    "fleet_restarts_total": "supervised replica restarts after a crash or hang",
    "fleet_restart_quarantines_total": "supervised replicas quarantined after crash-looping",
    "fleet_degraded_requests_total": "requests served monolithically with a disaggregated pool dark",
    "fleet_faults_injected_total": "faults injected by the chaos harness",
    # overload control (fleet/global_queue.py, fleet/router.py hedging)
    "fleet_global_queue_depth": "requests (and chaos phantoms) waiting in the router global queue",
    "fleet_global_queue_wait_seconds": "queue wait from router admission to replica grant",
    "fleet_global_queue_grants_total": "pull-dispatch grants (a replica slot freed and took work)",
    "fleet_global_queue_expired_total": "entries shed at the queue: admission estimate or deadline/wait expiry",
    "fleet_hedge_dispatches_total": "hedge legs dispatched after a first-token budget expiry",
    "fleet_hedge_wins_total": "hedged requests where the hedge leg produced the stream",
    "fleet_hedge_cancellations_total": "hedge losers cancelled first-writer-wins (KV freed)",
    "fleet_hedge_slow_demotions_total": "dispatch picks where a slow replica (TTFT EWMA) was demoted",
    "fleet_deadline_stream_cuts_total": "streams cut at the router because the deadline passed mid-decode",
    "fleet_hedge_suppressed_total": "hedges suppressed by the storm brake (no evidence, bucket dry)",
    # fleet data motion (fleet/router.py cache-aware routing, fleet/replica.py
    # zero-copy transport, fleet/manager.py peer prefix fetch, work stealing)
    "fleet_cache_route_hits_total": "dispatches placed by digest match (a replica advertised the request's prefix chain)",
    "fleet_cache_route_misses_total": "cache-aware placements that fell back to rendezvous/least-loaded",
    "fleet_peer_prefix_fetches_total": "cross-replica prefix-KV fetches that imported blocks into the local trie",
    "fleet_peer_prefix_fetch_rejects_total": "peer prefix fetches rejected at import (CRC/geometry/digest mismatch) and recomputed cold",
    "fleet_kv_transport_bytes_total": "KV payload bytes moved across replica dispatch interfaces, all transports",
    "fleet_kv_transport_binary_bytes_total": "KV payload bytes moved as raw handoff frames (zero-copy wire transport)",
    "fleet_kv_transport_base64_bytes_total": "KV payload bytes moved as base64 text (compatibility transport, encoded size)",
    "fleet_steals_total": "requests moved off a hot replica by work stealing (re-granted or exported mid-decode)",
    "fleet_steal_attempts_total": "steal probes sent to victim replicas (includes races the victim won)",
    # fleet-parked sessions (fleet/park_store.py)
    "fleet_park_sessions": "sessions currently parked in the router's park store",
    "fleet_park_bytes": "bytes of parked KV frames held by the router's park store",
    "fleet_parks_total": "finished-session KV frames banked in the router's park store",
    "fleet_park_rehydrates_total": "returning turns dispatched as rehydrate legs (parked KV imported, only the new suffix prefilled)",
    "fleet_park_rehydrate_misses_total": "known parked sessions that could not rehydrate (expired or diverged prompt)",
    "fleet_park_corrupt_rejects_total": "park frames dropped after a loud CRC/framing reject (the turn ran cold)",
    "fleet_park_evictions_total": "parked sessions dropped by the LRU byte/count budget or TTL",
    # fleet observability plane (telemetry/spans.py, telemetry/collector.py,
    # telemetry/slo.py, fleet/metrics.py)
    "spans_dropped_total": "spans dropped from the ring buffer past max_spans",
    "fleet_trace_collections_total": "trace-collector pull rounds across the fleet's span rings",
    "fleet_trace_spans_collected_total": "spans merged into the fleet trace store (deduped, clock-corrected)",
    "slo_breaches_total": "SLO breach episodes (fast and slow burn both over threshold)",
    "slo_burn_rate": "error-budget burn rate per objective and window (fast/slow)",
}
