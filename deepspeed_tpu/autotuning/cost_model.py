"""Autotuning cost model.

Reference: ``deepspeed/autotuning/tuner/cost_model.py`` (XGBoost regressor
over measured experiments) + ``model_based_tuner.py`` (rank candidates by
predicted cost, measure the most promising first).

TPU formulation, two tiers:

- an ANALYTIC prior from one profile pass (parameter count, device HBM):
  per-config memory estimate — master fp32 + compute copy + grads + Adam
  moments, each divided by the ZeRO degree their stage shards them at, opt
  state dropped to host when offloaded — prunes configs that cannot fit
  before anything runs; plus a throughput prior (micro·GAS amortizes the
  per-step optimizer/master traffic; remat trades ~30% more FLOPs for memory).
- a LEARNED refinement: after each measured run, a ridge regression over
  config features re-ranks the remaining candidates (the reference's
  XGBoost role, dependency-free).
"""

from typing import Dict, List, Optional

import numpy as np


def device_memory_bytes(default: int = 16 << 30) -> int:
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return default


class AnalyticCostModel:
    """Static prior from one profile pass (no experiment runs)."""

    def __init__(self, n_params: int, zero_degree: int, hbm_bytes: Optional[int] = None,
                 bytes_per_token_act: float = 0.0):
        self.n_params = n_params
        self.zero_degree = max(1, zero_degree)
        self.hbm = hbm_bytes if hbm_bytes is not None else device_memory_bytes()
        self.act_bpt = bytes_per_token_act

    def memory_bytes(self, cfg: Dict) -> float:
        """Estimated peak HBM for a candidate (params+opt+grads+activations)."""
        stage = int(cfg.get("zero_optimization.stage", 0))
        offload = str(cfg.get("zero_optimization.offload_optimizer.device", "none"))
        micro = int(cfg.get("train_micro_batch_size_per_gpu", 1))
        remat = bool(cfg.get("remat", True))
        Z = self.zero_degree
        p = self.n_params
        # the fp32 master copy is optimizer state: ZeRO shards it from stage 1
        # (charging it unsharded at stages 1/2 over-estimates by ~4P(1-1/Z)
        # and prunes viable candidates as predicted-OOM)
        master = 4 * p / (Z if stage >= 1 else 1)
        compute = 2 * p  # bf16 copy is materialized per step regardless of stage
        grads = 4 * p / (Z if stage >= 2 else 1)
        opt = 8 * p / (Z if stage >= 1 else 1)
        if offload in ("cpu", "nvme"):
            opt = 0
        act = self.act_bpt * micro * (0.35 if remat else 1.0)
        return master + compute + grads + opt + act

    def fits(self, cfg: Dict, safety: float = 0.85) -> bool:
        return self.memory_bytes(cfg) <= self.hbm * safety

    def throughput_prior(self, cfg: Dict) -> float:
        """Relative samples/sec prior (unitless; ordering is what matters):
        bigger micro·GAS amortizes the ~12·P bytes/step of optimizer+master
        traffic; offloaded optimizers pay host PCIe/DMA per step; remat costs
        ~30% extra FLOPs."""
        micro = int(cfg.get("train_micro_batch_size_per_gpu", 1))
        gas = int(cfg.get("gradient_accumulation_steps", 1))
        offload = str(cfg.get("zero_optimization.offload_optimizer.device", "none"))
        remat = bool(cfg.get("remat", True))
        compute = 1.0 * (1.3 if remat else 1.0)          # per-sample compute cost
        step_overhead = (12.0 if offload == "none" else 40.0) / (micro * gas)
        return micro * gas / (compute * micro * gas + step_overhead)


class LearnedCostModel:
    """Ridge regression over config features, refit after every measurement
    (the reference's XGBoost cost model role)."""

    FEATURES = ("micro", "gas", "stage", "offload", "remat", "log_tokens")

    def __init__(self, l2: float = 1e-3):
        self.l2 = l2
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        self._w = None

    @staticmethod
    def featurize(cfg: Dict) -> np.ndarray:
        micro = int(cfg.get("train_micro_batch_size_per_gpu", 1))
        gas = int(cfg.get("gradient_accumulation_steps", 1))
        return np.asarray([
            micro,
            gas,
            int(cfg.get("zero_optimization.stage", 0)),
            1.0 if str(cfg.get("zero_optimization.offload_optimizer.device", "none")) != "none" else 0.0,
            1.0 if cfg.get("remat", True) else 0.0,
            np.log1p(micro * gas),
        ], np.float64)

    def observe(self, cfg: Dict, throughput: float) -> None:
        self._X.append(self.featurize(cfg))
        self._y.append(float(throughput))
        X = np.stack(self._X)
        X = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        y = np.asarray(self._y)
        A = X.T @ X + self.l2 * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)

    @property
    def trained(self) -> bool:
        return self._w is not None and len(self._y) >= 3

    def predict(self, cfg: Dict) -> float:
        x = np.concatenate([self.featurize(cfg), [1.0]])
        return float(x @ self._w)
