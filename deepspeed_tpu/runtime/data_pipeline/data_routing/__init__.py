from deepspeed_tpu.runtime.data_pipeline.data_routing.random_ltd import (RandomLTDScheduler, gather_tokens,
                                                                         random_token_indices, scatter_tokens)

__all__ = ["RandomLTDScheduler", "random_token_indices", "gather_tokens", "scatter_tokens"]
