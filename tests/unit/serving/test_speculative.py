"""Speculative decoding through the serving scheduler: token-identical
outputs spec-on vs spec-off (greedy AND seeded sampling), the CPU perf gates
(a repeated — templated/code-like — prompt decodes in ≤ ceil((N-1)/(1+k))
verify dispatches with 100% acceptance; an adversarial random-token workload
costs ≤5% extra engine batches because adaptive k backs off to 0), KV
rollback leaving the pool balance exact under a concurrent soak, brownout
stage 2 zeroing the draft budget, and fleet handoff carrying drafter state.

Mechanism units (drafter, trie mining, engine verify/rollback) live in
tests/unit/inference/v2/test_spec.py.
"""

import math
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving import (PrefixCacheConfig, RequestState, ServingConfig,
                                   ServingScheduler, SpeculativeConfig)

MAX_STEPS = 600


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _spec_config(k=4, prefix=True, **spec_kw):
    spec = SpeculativeConfig(enabled=True, max_draft_tokens=k, **spec_kw)
    return ServingConfig(speculative=spec,
                         prefix_cache=PrefixCacheConfig(enabled=prefix))


def _prompt(cfg, n, seed=0):
    return np.random.default_rng(seed).integers(0, cfg.vocab_size, n).tolist()


# --------------------------------------------------------- token identity --
def test_token_identical_greedy_spec_on_vs_off(make_engine, llama_setup):
    """Cold (self-lookup drafting) AND warm (trie-mined drafting over a full
    prefix hit) speculative runs emit exactly the spec-off token sequence."""
    cfg, _, _ = llama_setup
    prompt = _prompt(cfg, 16, seed=3)
    N = 12

    off = ServingScheduler(make_engine(block_size=4), ServingConfig(), start=False)
    on_engine = make_engine(block_size=4)
    on = ServingScheduler(on_engine, _spec_config(k=3), start=False)
    try:
        ref = off.submit(prompt, max_new_tokens=N)
        _run_until(off, lambda: ref.finished)

        cold = on.submit(prompt, max_new_tokens=N)
        _run_until(on, lambda: cold.finished)
        assert cold.result() == ref.result()

        warm = on.submit(prompt, max_new_tokens=N)
        _run_until(on, lambda: warm.finished)
        assert warm.result() == ref.result()
        # the warm repeat really speculated: trie drafts accepted, fewer
        # decode dispatches than tokens
        assert warm.spec_accepted > 0
        assert warm.decode_steps < N - 1
    finally:
        off.stop(drain=False)
        on.stop(drain=False)
    assert on_engine.free_blocks == on_engine._state_manager.kv_cache.num_blocks


def test_token_identical_sampled_spec_on_vs_off(make_engine, llama_setup):
    """Seeded sampling: each emitted token is drawn from the target
    distribution with the request's own stream in spec-off draw order, so
    spec-on output is bitwise identical at the same seed."""
    cfg, _, _ = llama_setup
    prompt = _prompt(cfg, 16, seed=3)
    kw = dict(max_new_tokens=10, temperature=0.8, seed=77)

    off = ServingScheduler(make_engine(block_size=4), ServingConfig(), start=False)
    on = ServingScheduler(make_engine(block_size=4), _spec_config(k=3), start=False)
    try:
        ref = off.submit(prompt, **kw)
        _run_until(off, lambda: ref.finished)
        cold = on.submit(prompt, **kw)
        _run_until(on, lambda: cold.finished)
        warm = on.submit(prompt, **kw)
        _run_until(on, lambda: warm.finished)
        assert cold.result() == ref.result()
        assert warm.result() == ref.result()
        assert warm.spec_accepted > 0  # sampling accepted drafts for real
    finally:
        off.stop(drain=False)
        on.stop(drain=False)


# ------------------------------------------------------------- perf gates --
def test_repeated_prompt_verify_dispatch_cpu_perf_gate(make_engine, llama_setup):
    """The chip-independent speculative evidence (ROADMAP item 2): on the
    repetitive workload shape — a repeated prompt, the templated/chat/code
    pattern — the trie-drafted warm request emits N tokens in 1 prefill step
    plus ≤ ceil((N-1)/(1+k)) fully-accepted verify dispatches (>1 accepted
    token per decode step), bitwise token-identical to spec-off; and the
    THIRD run compiles nothing new (every verify width lands in one pad
    bucket — compile-watch-proved boundedness)."""
    cfg, _, _ = llama_setup
    telemetry.configure(telemetry.TelemetryConfig(enabled=True))
    engine = make_engine(block_size=4)
    K = 4
    N = 13
    sched = ServingScheduler(engine, _spec_config(k=K), start=False)
    ref_sched = ServingScheduler(make_engine(block_size=4), ServingConfig(),
                                 start=False)
    prompt = _prompt(cfg, 16, seed=3)

    def counters():
        snap = telemetry.get_registry().snapshot()
        return (sched._counters["batches"],
                sum(v for _, v in snap.get("compile_cache_misses_total", [])))

    try:
        ref = ref_sched.submit(prompt, max_new_tokens=N)
        _run_until(ref_sched, lambda: ref.finished)

        seed_req = sched.submit(prompt, max_new_tokens=N)  # publisher
        _run_until(sched, lambda: seed_req.finished)
        assert seed_req.result() == ref.result()
        batch0, _ = counters()

        warm = sched.submit(prompt, max_new_tokens=N)
        _run_until(sched, lambda: warm.finished)
        batch1, compile1 = counters()
        assert warm.result() == ref.result()  # bitwise token-identical
        # full prefix hit (1 prefill step) + fully-accepted verify dispatches
        decode_dispatches = batch1 - batch0 - 1
        assert decode_dispatches <= math.ceil((N - 1) / (1 + K)), \
            (decode_dispatches, N, K)
        assert warm.spec_drafted > 0
        assert warm.spec_accepted == warm.spec_drafted  # 100% acceptance
        # >1 accepted token per decode step — the ROADMAP target
        assert (N - 1) / decode_dispatches > 1.0

        warm2 = sched.submit(prompt, max_new_tokens=N)
        _run_until(sched, lambda: warm2.finished)
        batch2, compile2 = counters()
        assert warm2.result() == ref.result()
        assert batch2 - batch1 == batch1 - batch0  # steady state
        # bucket-count boundedness: every verify width (k recovers/caps vary
        # the feed) pads into the same bucket — zero steady-state compiles
        assert compile2 == compile1
    finally:
        sched.stop(drain=False)
        ref_sched.stop(drain=False)
    assert engine.free_blocks == engine._state_manager.kv_cache.num_blocks


def test_adversarial_random_tokens_cpu_perf_gate(make_engine, llama_setup):
    """Adversarial (pattern-free random) text: adaptive k backs off to 0, so
    spec-on costs ≤5% extra engine batches vs the k=0 control — and the
    output stays bitwise identical."""
    cfg, _, _ = llama_setup
    N = 24
    prompt = _prompt(cfg, 17, seed=9)  # odd length: no block alignment gifts

    off = ServingScheduler(make_engine(), ServingConfig(), start=False)
    on = ServingScheduler(make_engine(),
                          ServingConfig(speculative=SpeculativeConfig(
                              enabled=True, max_draft_tokens=4)), start=False)
    try:
        ref = off.submit(prompt, max_new_tokens=N)
        _run_until(off, lambda: ref.finished)
        off_batches = off._counters["batches"]

        req = on.submit(prompt, max_new_tokens=N)
        _run_until(on, lambda: req.finished)
        on_batches = on._counters["batches"]
        assert req.result() == ref.result()
        assert on_batches <= math.ceil(1.05 * off_batches), \
            (on_batches, off_batches)
        # the back-off is real: acceptance collapsed and k reached 0 (drafted
        # tokens stay far below the N * k_max a non-adaptive drafter spends)
        assert req._spec_ewma is not None and req._spec_ewma < 0.3
        assert req.spec_drafted < N
    finally:
        off.stop(drain=False)
        on.stop(drain=False)


# --------------------------------------------------------------- adaptive k --
def test_acceptance_ewma_adapts_and_probes(make_engine, llama_setup):
    """The EWMA drives k both ways: repetitive text holds k near max (steps
    << tokens), adversarial text collapses it to 0 with only the periodic
    probe drafting afterwards."""
    cfg, _, _ = llama_setup
    sched = ServingScheduler(
        make_engine(),
        ServingConfig(speculative=SpeculativeConfig(
            enabled=True, max_draft_tokens=4, probe_interval=8)), start=False)
    try:
        # repetitive: the prompt IS a short cycle, self-lookup nails it when
        # the model echoes the pattern; at minimum the ewma must stay warm
        rep = sched.submit([5, 6, 7] * 8, max_new_tokens=16)
        _run_until(sched, lambda: rep.finished)
        assert rep.spec_drafted > 0

        adv = sched.submit(_prompt(cfg, 19, seed=11), max_new_tokens=40)
        _run_until(sched, lambda: adv.finished)
        assert adv._spec_ewma is not None and adv._spec_ewma < 0.3
        # k collapsed: total drafts ≈ the first optimistic feeds + probes
        # (probe_interval=8 over ~39 decode steps), nowhere near 4/step
        assert adv.spec_drafted <= 16
        stats = sched.stats()["speculative"]
        assert stats["enabled"] and stats["verify_steps"] > 0
        assert stats["rollback_tokens"] > 0
    finally:
        sched.stop(drain=False)


# ----------------------------------------------------------------- rollback --
def test_rollback_soak_pool_balance_exact(make_engine, llama_setup):
    """PR-10-style refcount soak with speculation on: concurrent submitters
    over shared repetitive prompts, mid-flight cancellations, a pool small
    enough to force trie evictions — every verify rollback and every cancel
    must leave the allocator exactly balanced."""
    cfg, _, _ = llama_setup
    engine = make_engine(num_blocks=24)
    sched = ServingScheduler(engine, _spec_config(k=3))
    prefixes = [_prompt(cfg, 32, 100 + g) for g in range(3)]
    requests, lock = [], threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        for i in range(4):
            prompt = prefixes[int(rng.integers(3))] + \
                rng.integers(0, cfg.vocab_size, 8).tolist()
            req = sched.submit(prompt, max_new_tokens=6)
            with lock:
                requests.append(req)
            if rng.random() < 0.3:
                time.sleep(float(rng.random()) * 0.01)
                req.cancel()

    threads = [threading.Thread(target=client, args=(s, )) for s in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 120
    for req in requests:
        assert req.wait(timeout=max(0.1, deadline - time.monotonic())), req

    pc = sched._prefix_cache
    kv = engine._state_manager.kv_cache
    assert engine.free_blocks + pc.n_blocks == kv.num_blocks
    assert engine._state_manager.n_tracked_sequences == 0
    sched.stop(drain=False)
    assert engine.free_blocks == kv.num_blocks


# ----------------------------------------------------------------- brownout --
def test_brownout_stage2_zeroes_draft_budget_before_clamping(make_engine,
                                                             llama_setup):
    """The PR-14 satellite: brownout escalation kills drafting (stage 2)
    without touching an interactive request's max_new_tokens — speculation is
    the first capacity lever, client token budgets the later one."""
    from tests.unit.serving.test_overload import _force_stage
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, _spec_config(k=4, prefix=False), start=False)
    prompt = [5, 6, 7] * 8  # repetitive: drafting fires when allowed
    try:
        base = sched.submit(prompt, max_new_tokens=8)
        _run_until(sched, lambda: base.finished)
        assert base.spec_drafted > 0  # stage 0: speculation on

        _force_stage(sched, 2, pin=True)
        req = sched.submit(prompt, max_new_tokens=8)
        assert "speculative_disabled" in req.degraded_mode
        assert req.max_new_tokens == 8  # interactive budget untouched
        _run_until(sched, lambda: req.finished)
        assert req.spec_drafted == 0  # the draft budget is actually zero
        assert req.tokens == base.tokens  # degraded, not different
        assert req.decode_steps == 7  # one token per dispatch again
    finally:
        sched.stop(drain=False)


# ------------------------------------------------------------------ handoff --
def test_handoff_preserves_drafter_state(make_engine, llama_setup):
    """Mid-stream prefill→decode handoff: the acceptance EWMA and counters
    ride the payload, and the continuation is token-identical."""
    cfg, _, _ = llama_setup
    prompt = [5, 6, 7] * 8

    whole_s = ServingScheduler(make_engine(), ServingConfig(), start=False)
    donor = ServingScheduler(make_engine(), _spec_config(k=3, prefix=False),
                             start=False)
    recipient = ServingScheduler(make_engine(), _spec_config(k=3, prefix=False),
                                 start=False)
    try:
        whole = whole_s.submit(prompt, max_new_tokens=12)
        _run_until(whole_s, lambda: whole.finished)

        head = donor.submit(prompt, max_new_tokens=6, handoff=True)
        _run_until(donor, lambda: head.finished)
        assert head.spec_drafted > 0  # the donor really adapted
        assert head.handoff_payload is not None

        tail = recipient.submit_resume(head.handoff_payload, max_new_tokens=6)
        # drafter state adopted at admission, before any recipient step
        assert tail._spec_ewma == head._spec_ewma
        assert tail.spec_drafted == head.spec_drafted
        assert tail.spec_accepted == head.spec_accepted
        assert tail.decode_steps == head.decode_steps
        _run_until(recipient, lambda: tail.finished)
        assert head.result() + tail.result() == whole.result()
    finally:
        whole_s.stop(drain=False)
        donor.stop(drain=False)
        recipient.stop(drain=False)


# ----------------------------------------------------- config and plumbing --
def test_speculative_config_validation():
    with pytest.raises(Exception):
        SpeculativeConfig(max_draft_tokens=0)
    with pytest.raises(Exception):
        SpeculativeConfig(min_ngram=3, max_ngram=2)
    with pytest.raises(Exception):
        SpeculativeConfig(draft_token_budget=0)
    cfg = ServingConfig(speculative={"enabled": True, "max_draft_tokens": 6})
    assert cfg.speculative.enabled and cfg.speculative.max_draft_tokens == 6


def test_fleet_config_plumbs_speculative_per_role():
    """FleetConfig.speculative is authoritative per role when set: decode and
    mixed pools draft, the prefill pool (one token per request — nothing to
    speed up) does not; a silent fleet leaves replica configs untouched."""
    from deepspeed_tpu.fleet.config import FleetConfig
    from deepspeed_tpu.fleet.manager import ReplicaManager

    fleet = FleetConfig(speculative=SpeculativeConfig(enabled=True,
                                                      max_draft_tokens=5))
    mgr = ReplicaManager(config=fleet,
                         serving_config=ServingConfig(default_max_new_tokens=7))
    for role in ("mixed", "decode"):
        sc = mgr._role_serving_config(role)
        assert sc.speculative.enabled and sc.speculative.max_draft_tokens == 5
        assert sc.default_max_new_tokens == 7  # the base config survives
    assert not mgr._role_serving_config("prefill").speculative.enabled

    silent = ReplicaManager(config=FleetConfig(),
                            serving_config=_spec_config(k=2))
    assert silent._role_serving_config("decode").speculative.enabled


def test_stats_report_none_when_disabled(make_engine):
    sched = ServingScheduler(make_engine(), ServingConfig(), start=False)
    try:
        assert sched.stats()["speculative"] is None
    finally:
        sched.stop(drain=False)
